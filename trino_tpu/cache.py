"""Query-shape caching for high-QPS serving: plan + compiled-pipeline
cache, result cache, and the shape keys that drive admission batching.

Reference analog: the dispatcher-level ``QueryPreparer`` / prepared-
statement machinery plus the proposed Presto/Trino plan-cache designs —
repeat dashboard-style statements must not re-pay
parse -> analyze -> plan -> optimize -> expression-trace on every
submission.  The jit layer already proves shape-keyed reuse one level
down (``_exchange_program``'s lru_cache, ``KERNEL_SIZING``); this module
generalizes it to whole statements.

Key anatomy (the ONE key shared by every cache tier and the admission
batcher)::

    shape        normalized AST: every parameterizable literal replaced
                 by ast.Parameter(i) — "select c from t where k = 5" and
                 "... k = 9" share a shape
    literals     the parameterized-out literal vector, in walk order
    session_fp   catalog/schema/start_date/timezone + the FULL sorted
                 session-property overrides — any SET SESSION lands in a
                 fresh keyspace (a stale knob can never leak a plan)
    snapshot_fp  per-referenced-catalog connector data versions; a DDL
                 or write bumps the version so every dependent entry
                 misses loudly.  A connector that reports no version
                 (``data_version() is None`` — e.g. the live ``system``
                 catalog) makes the statement UNCACHEABLE.

The plan cache stores the optimized plan root per FULL key (shape +
literals + fingerprints): literal values flow into constant folding and
connector pushdown, so a plan is only provably reusable for the exact
vector it was planned with.  The shape level still pays off twice: the
admission batcher groups same-shape statements, and a "shape hit" /
"invalidation" split in the metrics shows WHY a miss happened.  Repeat
executions reuse the root AND the compiled ``PageProcessor`` instances
(the per-instance ``jax.jit`` in ``expr/compiler.py`` — without sharing,
every resubmission retraces every filter/projection), so the hot path
re-instantiates only cheap operator shells: zero jit traces, fresh
splits, fresh memory pools.

The result cache keys WITH literals and charges its pages against a
``QueryMemoryPool`` (the PR 4 governance substrate) — over budget it
evicts LRU entries instead of growing without bound.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import fields, is_dataclass
from typing import Dict, List, Optional, Tuple

from .sql import ast

#: functions whose output varies between identical executions — results
#: must never be served from cache (plans are still fine: the call
#: executes per run)
NONDETERMINISTIC_FUNCTIONS = {"random", "rand", "uuid", "shuffle"}

#: AST literal kinds a shape parameterizes out.  Boolean/NULL literals
#: stay in the shape (two-valued — parameterizing them buys nothing and
#: they often steer planning); interval literals keep their unit parsing
#: in the shape too.
_PARAM_LITERALS = (ast.LongLiteral, ast.DoubleLiteral, ast.DecimalLiteral,
                   ast.StringLiteral, ast.GenericLiteral)


def _literal_token(node) -> tuple:
    """Canonical, hashable value token for one parameterized literal.
    The kind tag keeps 5 (long) and 5.0 (double) distinct — their IR
    types differ, so their plans must too."""
    if isinstance(node, ast.LongLiteral):
        return ("long", node.value)
    if isinstance(node, ast.DoubleLiteral):
        return ("double", node.value)
    if isinstance(node, ast.DecimalLiteral):
        return ("decimal", node.text)
    if isinstance(node, ast.StringLiteral):
        return ("string", node.value)
    return ("generic", node.type_name, node.value)


def normalize_statement(stmt: ast.Statement
                        ) -> Tuple[ast.Node, Tuple[tuple, ...]]:
    """Rewrite ``stmt`` into its shape: literals out, ``Parameter(i)``
    in, returning ``(shape, literal_tokens)``.  The shape is a frozen
    AST tree — hashable, equality-comparable — usable directly as a
    cache-key component."""
    literals: List[tuple] = []

    def walk(node):
        if isinstance(node, _PARAM_LITERALS):
            literals.append(_literal_token(node))
            return ast.Parameter(len(literals) - 1)
        if isinstance(node, tuple):
            return tuple(walk(x) for x in node)
        if is_dataclass(node) and isinstance(node, ast.Node):
            return type(node)(**{f.name: walk(getattr(node, f.name))
                                 for f in fields(node)})
        return node

    return walk(stmt), tuple(literals)


def _walk_nodes(node):
    """Yield every AST node in a statement tree (dataclass fields +
    tuples)."""
    if isinstance(node, tuple):
        for x in node:
            yield from _walk_nodes(x)
        return
    if is_dataclass(node) and isinstance(node, ast.Node):
        yield node
        for f in fields(node):
            yield from _walk_nodes(getattr(node, f.name))


def statement_catalogs(stmt: ast.Statement, session) -> frozenset:
    """Catalogs a statement MAY read: every Table reference resolves to
    its explicit catalog or the session default.  Over-approximates (a
    WITH alias counts as a session-catalog table) — an extra catalog in
    the snapshot fingerprint only costs cache misses, never staleness."""
    cats = set()
    for node in _walk_nodes(stmt):
        if isinstance(node, ast.Table):
            if len(node.name) >= 3:
                cats.add(node.name[0].lower())
            elif session.catalog:
                cats.add(session.catalog.lower())
    return frozenset(cats)


def is_deterministic(stmt: ast.Statement) -> bool:
    """False when any function call can vary between identical runs
    (``current_date``/``now`` are session-pinned via ``start_date`` —
    deterministic under the session fingerprint)."""
    for node in _walk_nodes(stmt):
        if isinstance(node, ast.FunctionCall) and \
                node.name.lower() in NONDETERMINISTIC_FUNCTIONS:
            return False
    return True


def session_fingerprint(session) -> tuple:
    """Everything about a Session that can steer analysis or planning:
    resolution context + start date + the full property override map.
    A SET SESSION of ANY property moves subsequent statements into a
    fresh keyspace — coarse, but it makes "stale knob reuses a plan"
    structurally impossible."""
    return (session.catalog, session.schema, session.timezone,
            session.start_date.toordinal(),
            tuple(sorted(session.properties.items())))


def snapshot_fingerprint(catalogs: frozenset, metadata
                         ) -> Optional[tuple]:
    """(catalog, data_version) per referenced catalog, or None when any
    referenced connector is unversioned (live catalogs like ``system``)
    — None = this statement is uncacheable."""
    out = []
    for cat in sorted(catalogs):
        conn = metadata.connectors.get(cat)
        if conn is None:
            return None
        v = conn.data_version()
        if v is None:
            return None
        out.append((cat, v))
    return tuple(out)


class ParsedQuery:
    """Memoized per-statement-text parse + shape analysis."""

    __slots__ = ("stmt", "shape", "literals", "catalogs",
                 "is_query", "deterministic")

    def __init__(self, stmt, session):
        self.stmt = stmt
        self.is_query = isinstance(stmt, ast.QueryStatement)
        if self.is_query:
            self.shape, self.literals = normalize_statement(stmt)
            self.catalogs = statement_catalogs(stmt, session)
            self.deterministic = is_deterministic(stmt)
        else:
            self.shape = None
            self.literals = ()
            self.catalogs = frozenset()
            self.deterministic = False


class ProcessorCache:
    """Shared compiled ``PageProcessor`` instances keyed by their exact
    build inputs (input types + projection/filter IR — frozen
    dataclasses, so the key is the semantics).  THIS is where repeat
    statements stop retracing: a PageProcessor owns a per-instance
    ``jax.jit``, so re-planning without sharing re-traces every
    expression of every pipeline on every submission."""

    def __init__(self, max_entries: int = 512):
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0

    def get(self, input_types, projections, filter_expr):
        from .expr.compiler import PageProcessor

        key = (tuple(input_types), tuple(projections), filter_expr)
        with self._lock:
            proc = self._entries.get(key)
            if proc is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return proc
            self.misses += 1
        # build OUTSIDE the lock: tracing setup is the expensive part
        proc = PageProcessor(list(input_types), list(projections),
                             filter_expr)
        with self._lock:
            self._entries.setdefault(key, proc)
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            return self._entries[key]


class PlanCache:
    """Optimized plan roots per full key; LRU-bounded.  ``shape_hits``
    counts misses where the SHAPE was known but the literal vector was
    new; ``invalidations`` counts misses where a known shape's snapshot
    moved (a DDL/write bumped a referenced connector)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self._shape_snap: Dict = {}   # shape -> last stored snapshot_fp
        self.hits = 0
        self.misses = 0
        self.shape_hits = 0
        self.invalidations = 0
        self.evictions = 0
        self.hbo_invalidations = 0

    def lookup(self, key):
        shape, snapshot_fp = key[0], key[3]
        with self._lock:
            root = self._entries.get(key)
            if root is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return root
            self.misses += 1
            last_snap = self._shape_snap.get(shape)
            if last_snap is not None:
                if last_snap != snapshot_fp:
                    self.invalidations += 1
                else:
                    self.shape_hits += 1
            return None

    def store(self, key, root, max_entries: int):
        with self._lock:
            self._entries[key] = root
            self._entries.move_to_end(key)
            self._shape_snap[key[0]] = key[3]
            while len(self._entries) > max(1, max_entries):
                self._entries.popitem(last=False)
                self.evictions += 1
            if len(self._shape_snap) > 4 * max(1, max_entries):
                live = {k[0] for k in self._entries}
                self._shape_snap = {s: v for s, v
                                    in self._shape_snap.items()
                                    if s in live}

    def invalidate_shape(self, shape) -> int:
        """Drop every cached plan of one statement shape: history-based
        statistics learned a MATERIALLY different cardinality for a
        decision node, so plans optimized from the old estimates must
        re-plan against history on their next submission (the HBO
        analog of a snapshot bump — same loud-miss philosophy)."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == shape]
            for k in doomed:
                del self._entries[k]
            if doomed:
                self._shape_snap.pop(shape, None)
                self.hbo_invalidations += len(doomed)
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._entries)


def _estimate_result_bytes(rows: List[tuple]) -> int:
    """Cheap accounting estimate for cached rows (sampled string cost);
    governance wants a budget, not an audit."""
    if not rows:
        return 256
    ncols = len(rows[0]) if rows[0] else 1
    per_row = 48 + 24 * ncols
    sample = rows[:: max(1, len(rows) // 32)][:32]
    str_extra = 0
    for r in sample:
        for v in r:
            if isinstance(v, str):
                str_extra += len(v)
    if sample:
        per_row += str_extra // len(sample)
    return 256 + per_row * len(rows)


class ResultCache:
    """Finished result rows per full key (WITH literals).  Entries
    charge a dedicated ``QueryMemoryPool`` — over budget the pool's
    reserve fails and LRU entries evict until the new entry fits (or is
    skipped when larger than the whole budget)."""

    def __init__(self, max_bytes: int = 64 << 20,
                 max_rows: int = 100_000):
        from .exec.memory import QueryMemoryPool

        self.max_bytes = int(max_bytes)
        self.max_rows = int(max_rows)
        self.pool = QueryMemoryPool(self.max_bytes,
                                    query_id="result-cache")
        self._ctx = self.pool.create_context("cached-results")
        self._lock = threading.Lock()
        # key -> (column_names, types, rows, nbytes)
        self._entries: "OrderedDict" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def lookup(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit
            self.misses += 1
            return None

    def store(self, key, column_names, types, rows, scans=()):
        """``scans`` carries the plan's (catalog, schema, table,
        columns) references so a later hit can re-enforce SELECT for
        the requesting user before serving cached rows."""
        from .types import TrinoError

        if len(rows) > self.max_rows:
            return
        nbytes = _estimate_result_bytes(rows)
        if nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._ctx.free(old[3], revocable=False)
            while True:
                try:
                    self._ctx.reserve(nbytes, revocable=False)
                    break
                except TrinoError:
                    if not self._entries:
                        return  # single entry over budget: skip
                    _, evicted = self._entries.popitem(last=False)
                    self._ctx.free(evicted[3], revocable=False)
                    self.evictions += 1
            self._entries[key] = (column_names, types, rows, nbytes,
                                  tuple(scans))

    @property
    def reserved_bytes(self) -> int:
        return self.pool.reserved

    def __len__(self):
        with self._lock:
            return len(self._entries)


def literal_nodes(tokens) -> List[ast.Expression]:
    """Rebuild the AST literal node each ``_literal_token`` came from —
    the inverse of the tokenization, so template machinery can re-run
    the ANALYZER's typing rules (decimal precision, varchar length,
    DATE parsing under the session timezone) instead of duplicating
    them."""
    out: List[ast.Expression] = []
    for tok in tokens:
        kind = tok[0]
        if kind == "long":
            out.append(ast.LongLiteral(tok[1]))
        elif kind == "double":
            out.append(ast.DoubleLiteral(tok[1]))
        elif kind == "decimal":
            out.append(ast.DecimalLiteral(tok[1]))
        elif kind == "string":
            out.append(ast.StringLiteral(tok[1]))
        else:
            out.append(ast.GenericLiteral(tok[1], tok[2]))
    return out


def analyze_literal_tokens(tokens, session):
    """Lower literal tokens to typed IR ``Literal``s via the analyzer
    (one per token, in slot order).  Raises ``AnalysisError`` for
    malformed generic literals — callers treat that as template
    ineligibility."""
    from .sql.analyzer import ExpressionAnalyzer, Scope

    an = ExpressionAnalyzer(Scope([], None), session)
    return [an.analyze(node) for node in literal_nodes(tokens)]


class PlanTemplate:
    """One value-independent optimized plan serving EVERY literal vector
    of a statement shape (round 16).  ``param_types`` are the IR types
    the template was planned against — a member whose analyzed literal
    types differ (e.g. varchar(3) vs varchar(5), decimal scale drift)
    must not ride it."""

    __slots__ = ("root", "param_types", "scan_refs")

    def __init__(self, root, param_types, scan_refs=()):
        self.root = root
        self.param_types = tuple(param_types)
        self.scan_refs = tuple(scan_refs)


class TemplateCache:
    """Plan templates per (shape, session_fp, snapshot_fp, user) — the
    full cache key MINUS literals.  Entries are positive (a
    ``PlanTemplate``) or negative (a fallback-reason string: the shape
    was tried and its planning genuinely depends on a literal value, so
    per-statement planning is the loudly-counted answer and rebuild
    attempts stop).  ``shape_uses`` feeds the admission policy: a shape
    earns a template only after enough repeat uses (or an HBO hint)
    prove the template build will amortize."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self._shape_uses: Dict = {}
        self.hits = 0
        self.misses = 0
        self.builds = 0
        self.fallbacks: Dict[str, int] = {}
        self.dispositions: Dict[str, int] = {}

    def lookup(self, key):
        """-> ("hit", PlanTemplate) | ("fallback", reason) | None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ("fallback", e) if isinstance(e, str) else ("hit", e)

    def store(self, key, template: PlanTemplate, max_entries: int):
        with self._lock:
            self.builds += 1
            self._entries[key] = template
            self._entries.move_to_end(key)
            while len(self._entries) > max(1, max_entries):
                self._entries.popitem(last=False)

    def store_fallback(self, key, reason: str, max_entries: int):
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
            self._entries[key] = reason
            self._entries.move_to_end(key)
            while len(self._entries) > max(1, max_entries):
                self._entries.popitem(last=False)

    def note_fallback(self, reason: str):
        """Count a per-member/per-batch fallback that doesn't negative-
        cache the whole key (e.g. one member's literal types drifted
        from the template's — other members still ride it)."""
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def note_disposition(self, reason: str):
        """Count HOW a batched launch executed beyond filter/project
        stages (``agg_stage_vmapped`` / ``join_stage_vmapped``) — the
        positive half of the ``non_fp_stage`` split (round 17): the
        metric family shows vmapped agg/join launches next to the
        ``unsupported_stage`` fallbacks they replaced."""
        with self._lock:
            self.dispositions[reason] = self.dispositions.get(reason, 0) + 1

    def note_uses(self, shape, n: int = 1) -> int:
        """Count ``n`` submissions of ``shape``; returns the running
        total (a batch of B counts as B uses — a same-shape burst is
        exactly the evidence a template pays for)."""
        with self._lock:
            total = self._shape_uses.get(shape, 0) + n
            self._shape_uses[shape] = total
            if len(self._shape_uses) > 4096:
                # bound the counter map: keep the hottest half
                keep = sorted(self._shape_uses.items(),
                              key=lambda kv: kv[1], reverse=True)[:2048]
                self._shape_uses = dict(keep)
            return total

    def invalidate_shape(self, shape) -> int:
        """HBO re-plan hook (mirrors ``PlanCache.invalidate_shape``)."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == shape]
            for k in doomed:
                del self._entries[k]
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._entries)


class TemplateSeedStore:
    """Process-wide template-earn state shared across the cluster
    (round 17): the coordinator's per-shape use totals and negative
    (fallback) verdicts, keyed by ``statement_fingerprint(shape)`` so
    the payload is JSON-safe and process-independent.

    Transport mirrors the HBO seed (PR 15): the coordinator exports a
    bounded snapshot that piggybacks on worker ``configure()`` and on
    the heartbeat when the local version advanced, so a REPLACEMENT
    worker rides an already-earned template on its first statement
    instead of re-earning ``batched_execution_min_shape_uses``
    locally — and skips shapes the cluster already proved
    value-dependent without paying its own trial plan.

    Merge discipline is max-wins (use totals only ever grow; the max of
    two counters is a sound lower bound of true cluster-wide uses) and
    a remote fallback verdict never overwrites a local one (the local
    process observed its own trial).  All mutation holds ``_lock`` —
    readers race with the heartbeat exporter otherwise.
    """

    MAX_SHAPES = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._uses: Dict[str, int] = {}
        self._fallbacks: Dict[str, str] = {}
        self.version = 0          # bumps on any growth: heartbeat delta gate
        self.corrupt_loads = 0

    def note(self, fp: str, total: int):
        with self._lock:
            cur = self._uses.get(fp, 0)
            if total > cur:
                self._uses[fp] = total
                self.version += 1
            self._trim()

    def note_fallback_shape(self, fp: str, reason: str):
        with self._lock:
            if fp not in self._fallbacks:
                self._fallbacks[fp] = reason
                self.version += 1

    def uses(self, fp: str) -> int:
        with self._lock:
            return self._uses.get(fp, 0)

    def fallback_reason(self, fp: str) -> Optional[str]:
        with self._lock:
            return self._fallbacks.get(fp)

    def _trim(self):
        # caller holds _lock; bound like TemplateCache._shape_uses
        if len(self._uses) > self.MAX_SHAPES:
            keep = sorted(self._uses.items(), key=lambda kv: kv[1],
                          reverse=True)[:self.MAX_SHAPES // 2]
            self._uses = dict(keep)

    def export_seed(self, max_shapes: int = 64) -> dict:
        """Bounded JSON-safe snapshot of the HOTTEST shapes (use totals
        are the heat signal the admission policy consults)."""
        with self._lock:
            hot = sorted(self._uses.items(), key=lambda kv: kv[1],
                         reverse=True)[:max_shapes]
            shapes = [[fp, int(n), self._fallbacks.get(fp)]
                      for fp, n in hot]
            for fp, reason in self._fallbacks.items():
                if len(shapes) >= max_shapes:
                    break
                if fp not in self._uses:
                    shapes.append([fp, 0, reason])
            return {"version": 1, "shapes": shapes}

    def import_seed(self, payload: dict) -> int:
        """Fold a coordinator seed in; returns how many shapes carried
        NEW information (higher total or a fresh verdict).  A malformed
        payload warns loudly and imports nothing (the HBO seed's
        half-load rule)."""
        import warnings

        try:
            rows = [(str(fp), int(n), None if reason is None
                     else str(reason))
                    for fp, n, reason in payload["shapes"]]
        except (ValueError, KeyError, TypeError) as e:
            with self._lock:
                self.corrupt_loads += 1
            warnings.warn(
                f"template seed payload is malformed and was IGNORED: "
                f"{e!r}", RuntimeWarning, stacklevel=2)
            return 0
        imported = 0
        with self._lock:
            for fp, n, reason in rows:
                grew = False
                if n > self._uses.get(fp, 0):
                    self._uses[fp] = n
                    grew = True
                if reason is not None and fp not in self._fallbacks:
                    self._fallbacks[fp] = reason
                    grew = True
                if grew:
                    imported += 1
                    self.version += 1
            self._trim()
        return imported

    def clear(self):
        with self._lock:
            self._uses.clear()
            self._fallbacks.clear()
            self.version = 0


#: the process-wide seed store (coordinator and workers each own one,
#: like ``telemetry.stats_store.store()``); tests reset via ``clear()``
_TEMPLATE_SEEDS = TemplateSeedStore()


def template_seeds() -> TemplateSeedStore:
    return _TEMPLATE_SEEDS


class QueryCache:
    """Per-runner facade: parse memo + plan cache + result cache +
    shared-processor cache, with one metrics surface.  Owned by
    LocalQueryRunner; the admission batcher reads ``parse()`` shapes to
    group same-shape statements."""

    def __init__(self, metadata, result_cache_bytes: int = 64 << 20,
                 max_text_entries: int = 1024):
        self.metadata = metadata
        self._lock = threading.Lock()
        self._texts: "OrderedDict[str, ParsedQuery]" = OrderedDict()
        self.max_text_entries = max_text_entries
        self.plans = PlanCache()
        self.results = ResultCache(max_bytes=result_cache_bytes)
        self.processors = ProcessorCache()
        self.templates = TemplateCache()
        self.coalesced = 0          # identical in-batch statements demuxed
        self.batches = 0            # admission batches executed
        self.batched_queries = 0    # statements that rode a batch
        self.batched_launches = 0   # statements served by ONE vmapped launch
        self.batched_spills = 0     # lanes that overflowed a unified capacity
        self.result_shortcircuits = 0  # batch members served from result cache

    def parse(self, sql: str, session) -> ParsedQuery:
        """Memoized parse + shape analysis (exact statement text).  The
        memo is session-independent for the pieces that matter — shape
        and literals derive from text alone; catalogs use the session
        default catalog, so the memo keys on that too."""
        memo_key = (sql, session.catalog)
        with self._lock:
            pq = self._texts.get(memo_key)
            if pq is not None:
                self._texts.move_to_end(memo_key)
                return pq
        from .sql.parser import parse_statement

        pq = ParsedQuery(parse_statement(sql), session)
        with self._lock:
            self._texts[memo_key] = pq
            while len(self._texts) > self.max_text_entries:
                self._texts.popitem(last=False)
        return pq

    def cache_key(self, pq: ParsedQuery, session,
                  user: Optional[str] = None) -> Optional[tuple]:
        """Full cache key for this statement under this session, or
        None when uncacheable (not a plain query, or a referenced
        catalog is unversioned).  The effective ``user`` scopes the
        entry: tenants with per-user ACLs must never share cached
        plans or rows."""
        if not pq.is_query:
            return None
        snap = snapshot_fingerprint(pq.catalogs, self.metadata)
        if snap is None:
            return None
        return (pq.shape, pq.literals, session_fingerprint(session),
                snap, user or session.user)

    def template_key(self, pq: ParsedQuery, session,
                     user: Optional[str] = None) -> Optional[tuple]:
        """Template cache key: the full key MINUS literals — one entry
        serves every literal vector of the shape.  Same None rules as
        ``cache_key`` (and additionally None for literal-free shapes:
        with zero parameter slots the plan cache already covers them)."""
        if not pq.is_query or not pq.literals:
            return None
        snap = snapshot_fingerprint(pq.catalogs, self.metadata)
        if snap is None:
            return None
        return (pq.shape, session_fingerprint(session), snap,
                user or session.user)

    def note_batch(self, size: int, coalesced: int):
        with self._lock:
            self.batches += 1
            self.batched_queries += size
            self.coalesced += coalesced

    def counters(self) -> Dict[str, int]:
        return {
            "plan_hits": self.plans.hits,
            "plan_misses": self.plans.misses,
            "plan_shape_hits": self.plans.shape_hits,
            "plan_invalidations": self.plans.invalidations,
            "plan_hbo_invalidations": self.plans.hbo_invalidations,
            "plan_evictions": self.plans.evictions,
            "plan_entries": len(self.plans),
            "result_hits": self.results.hits,
            "result_misses": self.results.misses,
            "result_evictions": self.results.evictions,
            "result_entries": len(self.results),
            "result_bytes": self.results.reserved_bytes,
            "processor_hits": self.processors.hits,
            "processor_misses": self.processors.misses,
            "batches": self.batches,
            "batched_queries": self.batched_queries,
            "coalesced": self.coalesced,
            "batched_launches": self.batched_launches,
            "batched_spills": self.batched_spills,
            "result_shortcircuits": self.result_shortcircuits,
            "template_hits": self.templates.hits,
            "template_misses": self.templates.misses,
            "template_builds": self.templates.builds,
            "template_fallbacks": sum(self.templates.fallbacks.values()),
            "template_entries": len(self.templates),
        }

    def add_families(self, reg):
        """Export the cache counters into a MetricsRegistry (the PR 6
        surface: GET /v1/metrics + system.runtime.metrics)."""
        c = self.counters()
        pc = reg.counter("trino_plan_cache_total",
                         "Plan-cache lookups by outcome (hit|miss|"
                         "shape_hit|invalidation|hbo_invalidation|"
                         "eviction)")
        pc.inc(c["plan_hits"], outcome="hit")
        pc.inc(c["plan_misses"], outcome="miss")
        pc.inc(c["plan_shape_hits"], outcome="shape_hit")
        pc.inc(c["plan_invalidations"], outcome="invalidation")
        pc.inc(c["plan_hbo_invalidations"], outcome="hbo_invalidation")
        pc.inc(c["plan_evictions"], outcome="eviction")
        reg.gauge("trino_plan_cache_entries",
                  "Plan-cache resident entries").set(c["plan_entries"])
        rc = reg.counter("trino_result_cache_total",
                         "Result-cache lookups by outcome "
                         "(hit|miss|eviction)")
        rc.inc(c["result_hits"], outcome="hit")
        rc.inc(c["result_misses"], outcome="miss")
        rc.inc(c["result_evictions"], outcome="eviction")
        reg.gauge("trino_result_cache_bytes",
                  "Result-cache bytes charged to its memory pool").set(
            c["result_bytes"])
        reg.gauge("trino_result_cache_entries",
                  "Result-cache resident entries").set(
            c["result_entries"])
        proc = reg.counter("trino_processor_cache_total",
                           "Shared compiled-PageProcessor lookups "
                           "(hit = a pipeline reused an already-traced "
                           "jit program)")
        proc.inc(c["processor_hits"], outcome="hit")
        proc.inc(c["processor_misses"], outcome="miss")
        b = reg.counter("trino_admission_batches_total",
                        "Admission batching (kind=batches|queries|"
                        "coalesced)")
        b.inc(c["batches"], kind="batches")
        b.inc(c["batched_queries"], kind="queries")
        b.inc(c["coalesced"], kind="coalesced")
        b.inc(c["batched_launches"], kind="vmapped")
        b.inc(c["batched_spills"], kind="spilled")
        b.inc(c["result_shortcircuits"], kind="result_shortcircuit")
        t = reg.counter("trino_plan_template_total",
                        "Plan-template lookups/builds by outcome "
                        "(hit|miss|build|fallback:<reason>|"
                        "disposition:<reason>)")
        t.inc(c["template_hits"], outcome="hit")
        t.inc(c["template_misses"], outcome="miss")
        t.inc(c["template_builds"], outcome="build")
        for reason, n in sorted(self.templates.fallbacks.items()):
            t.inc(n, outcome=f"fallback:{reason}")
        # round-17 taxonomy split: ``non_fp_stage`` became
        # ``unsupported_stage`` (+ the vmapped dispositions below);
        # export the old key as an alias for one release so dashboards
        # keyed on it keep reading during the rename
        legacy = self.templates.fallbacks.get("unsupported_stage", 0)
        if legacy:
            t.inc(legacy, outcome="fallback:non_fp_stage")
        for reason, n in sorted(self.templates.dispositions.items()):
            t.inc(n, outcome=f"disposition:{reason}")
        reg.gauge("trino_plan_template_entries",
                  "Plan-template resident entries (positive + "
                  "negative)").set(c["template_entries"])
