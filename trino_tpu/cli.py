"""Interactive SQL CLI.

Reference analog: ``client/trino-cli/.../Console.java:82`` — a REPL over
the statement protocol with aligned tabular output.  Two modes: connect
to a running server (``--server``) or embed a LocalQueryRunner over the
built-in catalogs (``--embedded``), which is also how the CLI is tested
without networking.

Usage:
    python -m trino_tpu.cli --embedded --catalog tpch --schema tiny
    python -m trino_tpu.cli --server http://127.0.0.1:8080 \
        -e "select count(*) from tpch.tiny.orders"
"""

from __future__ import annotations

import argparse
import sys


def format_table(names, rows) -> str:
    cells = [[("" if v is None else str(v)) for v in row] for row in rows]
    widths = [len(n) for n in names]
    for row in cells:
        for i, c in enumerate(row):
            widths[i] = max(widths[i], len(c))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(n.ljust(w) for n, w in zip(names, widths)), sep]
    for row in cells:
        out.append(" | ".join(c.ljust(w) for c, w in zip(row, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


def _embedded_runner(catalog: str, schema: str):
    from .connectors.catalog import create_catalogs
    from .runner import LocalQueryRunner
    from .sql.analyzer import Session

    catalogs = {"tpch": {"connector": "tpch"},
                "memory": {"connector": "memory"},
                "blackhole": {"connector": "blackhole"}}
    return LocalQueryRunner(create_catalogs(catalogs),
                            Session(catalog=catalog, schema=schema))


class _ServerBackend:
    def __init__(self, server: str):
        from .client import Client

        self.client = Client(server)

    def run(self, sql: str):
        res = self.client.execute(sql)
        return res.column_names, res.rows


class _EmbeddedBackend:
    def __init__(self, catalog: str, schema: str):
        self.runner = _embedded_runner(catalog, schema)

    def run(self, sql: str):
        res = self.runner.execute(sql)
        return res.column_names, res.rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", help="coordinator URI")
    ap.add_argument("--embedded", action="store_true",
                    help="in-process engine (no server)")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("-e", "--execute", help="run one statement and exit")
    args = ap.parse_args(argv)

    if args.server:
        backend = _ServerBackend(args.server)
    else:
        backend = _EmbeddedBackend(args.catalog, args.schema)

    def run_one(sql: str) -> int:
        sql = sql.strip().rstrip(";")
        if not sql:
            return 0
        try:
            names, rows = backend.run(sql)
        except Exception as e:
            print(f"Query failed: {e}", file=sys.stderr)
            return 1
        print(format_table(names, rows))
        return 0

    if args.execute:
        return run_one(args.execute)

    print("trino-tpu> ", end="", flush=True)
    buf = []
    for line in sys.stdin:
        buf.append(line)
        if line.rstrip().endswith(";") or not line.strip():
            run_one(" ".join(buf))
            buf = []
            print("trino-tpu> ", end="", flush=True)
    if buf:
        run_one(" ".join(buf))
    return 0


if __name__ == "__main__":
    sys.exit(main())
