"""Python client for the HTTP statement protocol.

Reference analog: ``client/trino-client/.../StatementClientV1.java:65,
334-346`` — POST the statement, follow ``nextUri`` until it disappears,
accumulating typed rows; surface server errors as exceptions.
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass, field
from typing import List, Optional

from .types import TrinoError


@dataclass
class ClientResult:
    columns: List[dict] = field(default_factory=list)
    rows: List[list] = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    @property
    def column_names(self) -> List[str]:
        return [c["name"] for c in self.columns]


class Client:
    """``Client("http://host:port").execute("select 1")``"""

    def __init__(self, server: str, poll_interval: float = 0.05,
                 timeout: float = 600.0, user: Optional[str] = None):
        self.server = server.rstrip("/")
        self.poll_interval = poll_interval
        self.timeout = timeout
        #: tenant identity for resource-group routing + admission
        #: batching (reference: the X-Trino-User request header)
        self.user = user

    def _http(self, method: str, url: str, body: Optional[bytes] = None):
        headers = {"X-Trino-User": self.user} if self.user else {}
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    def execute(self, sql: str) -> ClientResult:
        doc = self._http("POST", f"{self.server}/v1/statement",
                         sql.encode())
        out = ClientResult()
        deadline = time.time() + self.timeout
        while True:
            if doc.get("error"):
                e = doc["error"]
                raise TrinoError(e.get("message", "query failed"),
                                 e.get("errorCode",
                                       "GENERIC_INTERNAL_ERROR"))
            if doc.get("columns") and not out.columns:
                out.columns = doc["columns"]
            out.rows.extend(doc.get("data", []))
            if doc.get("stats"):
                out.stats = doc["stats"]
            nxt = doc.get("nextUri")
            if not nxt:
                return out
            if time.time() > deadline:
                raise TrinoError("client poll timeout",
                                 "CLIENT_TIMEOUT")
            state = doc.get("stats", {}).get("state")
            if state in ("QUEUED", "RUNNING"):
                time.sleep(self.poll_interval)
            doc = self._http("GET", nxt)
