"""Cluster configuration: the three config scopes.

Reference analog: airlift ``@Config`` binding over
``etc/config.properties`` (cluster scope), ``etc/catalog/*.properties``
(catalog scope, ``connector/StaticCatalogManager.java``), and per-query
session properties (``session_properties.py``). JSON sidecar files
configure access control and resource groups the way the reference's
file-based plugins do (``etc/access-control.json``,
``etc/resource-groups.json``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from .connectors.catalog import create_catalog
from .connectors.spi import Connector
from .resource_groups import ResourceGroupManager
from .security import (ALLOW_ALL, RuleBasedAccessControl,
                       SystemAccessControl)


def parse_properties(text: str) -> Dict[str, str]:
    """Java-style .properties: key=value lines, # comments."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        k, _, v = line.partition("=")
        out[k.strip()] = v.strip()
    return out


def _coerce(v: str):
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


@dataclass
class ServerConfig:
    """Everything ``Server.start`` needs (reference: server/Server.java
    bootstrap over the airlift module graph)."""

    properties: Dict[str, str] = field(default_factory=dict)
    connectors: Dict[str, Connector] = field(default_factory=dict)
    access_control: SystemAccessControl = ALLOW_ALL
    resource_groups: Optional[ResourceGroupManager] = None

    @property
    def default_catalog(self) -> Optional[str]:
        return self.properties.get("default-catalog") \
            or next(iter(self.connectors), None)


def load_etc(etc_dir: str) -> ServerConfig:
    """Load an ``etc/`` directory: config.properties,
    catalog/*.properties, access-control.json, resource-groups.json."""
    cfg = ServerConfig()
    props_path = os.path.join(etc_dir, "config.properties")
    if os.path.exists(props_path):
        cfg.properties = parse_properties(open(props_path).read())

    catalog_dir = os.path.join(etc_dir, "catalog")
    if os.path.isdir(catalog_dir):
        for fn in sorted(os.listdir(catalog_dir)):
            if not fn.endswith(".properties"):
                continue
            name = fn[:-len(".properties")]
            props = parse_properties(
                open(os.path.join(catalog_dir, fn)).read())
            conf = {"connector": props.pop("connector.name", name)}
            conf.update({k: _coerce(v) for k, v in props.items()})
            cfg.connectors[name] = create_catalog(name, conf)

    ac_path = os.path.join(etc_dir, "access-control.json")
    if os.path.exists(ac_path):
        cfg.access_control = RuleBasedAccessControl.from_config(
            json.load(open(ac_path)))

    rg_path = os.path.join(etc_dir, "resource-groups.json")
    if os.path.exists(rg_path):
        cfg.resource_groups = ResourceGroupManager.from_config(
            json.load(open(rg_path)))
    return cfg
