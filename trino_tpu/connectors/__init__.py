from .spi import (  # noqa: F401
    ColumnHandle,
    Connector,
    ConnectorMetadata,
    ConnectorPageSource,
    ConnectorSplit,
    ConnectorSplitManager,
    TableHandle,
)
