"""Blackhole connector: /dev/null tables with synthetic rows.

Reference analog: ``plugin/trino-blackhole`` (``BlackHoleConnector.java``)
— writes are discarded (counted), reads produce a configurable number of
synthetic rows; the perf/test fixture for write paths and scheduling.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..block import Block, Dictionary, Page
from .spi import (ColumnHandle, Connector, ConnectorMetadata,
                  ConnectorPageSink, ConnectorPageSource, ConnectorSplit,
                  ConnectorSplitManager, TableHandle, TableStatistics)


class _BhTable:
    def __init__(self, columns: List[ColumnHandle], rows_per_page: int,
                 pages_per_split: int, splits: int):
        self.columns = columns
        self.rows_per_page = rows_per_page
        self.pages_per_split = pages_per_split
        self.splits = splits


class _BhPageSource(ConnectorPageSource):
    def __init__(self, table: _BhTable, columns: Sequence[ColumnHandle]):
        self.table = table
        self.columns = list(columns)
        self.remaining = table.pages_per_split
        self._dicts: Dict[str, Dictionary] = {}

    def get_next_page(self) -> Optional[Page]:
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        n = self.table.rows_per_page
        blocks = []
        for c in self.columns:
            if c.type.is_string:
                d = self._dicts.setdefault(c.name, Dictionary(["x"]))
                blocks.append(Block(c.type, np.zeros(n, np.int32), None, d))
            else:
                blocks.append(Block(
                    c.type, np.zeros(n, dtype=c.type.storage)))
        return Page(blocks, n)

    def is_finished(self) -> bool:
        return self.remaining <= 0


class _BhSink(ConnectorPageSink):
    def __init__(self):
        self.rows = 0

    def append_page(self, page: Page):
        self.rows += page.num_rows  # discarded

    def finish(self) -> dict:
        return {"rows": self.rows}


class BlackHoleMetadata(ConnectorMetadata):
    def __init__(self, conn: "BlackHoleConnector"):
        self.conn = conn

    def list_schemas(self) -> List[str]:
        return ["default"]

    def list_tables(self, schema: str) -> List[str]:
        return sorted(t for (s, t) in self.conn.tables if s == schema)

    def get_table_handle(self, schema, table) -> Optional[TableHandle]:
        if (schema, table) in self.conn.tables:
            return TableHandle(self.conn.catalog_name, schema, table)
        return None

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        return self.conn.tables[(table.schema, table.table)].columns

    def create_table(self, schema: str, table: str,
                     columns: List[ColumnHandle]) -> TableHandle:
        with self.conn.lock:
            self.conn.tables[(schema, table)] = _BhTable(
                list(columns), self.conn.rows_per_page,
                self.conn.pages_per_split, self.conn.split_count)
        return TableHandle(self.conn.catalog_name, schema, table)

    def drop_table(self, table: TableHandle):
        with self.conn.lock:
            self.conn.tables.pop((table.schema, table.table), None)


class BlackHoleConnector(Connector):
    name = "blackhole"

    def __init__(self, catalog_name: str = "blackhole",
                 rows_per_page: int = 0, pages_per_split: int = 1,
                 split_count: int = 1):
        self.catalog_name = catalog_name
        self.rows_per_page = rows_per_page
        self.pages_per_split = pages_per_split
        self.split_count = split_count
        self.tables: Dict[Tuple[str, str], _BhTable] = {}
        self.lock = threading.Lock()

    def metadata(self) -> ConnectorMetadata:
        return BlackHoleMetadata(self)

    def split_manager(self) -> ConnectorSplitManager:
        conn = self

        class _SM(ConnectorSplitManager):
            def get_splits(self, table, desired_splits):
                t = conn.tables[(table.schema, table.table)]
                return [ConnectorSplit(table, i, t.splits, 0, 0)
                        for i in range(t.splits)]

        return _SM()

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        t = self.tables[(split.table.schema, split.table.table)]
        return _BhPageSource(t, columns)

    def page_sink(self, table: TableHandle,
                  columns: Sequence[ColumnHandle]) -> ConnectorPageSink:
        return _BhSink()
