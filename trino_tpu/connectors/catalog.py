"""Catalog factory: config dict -> connector instances.

Reference analog: ``metadata/CatalogManager.java`` +
``connector/DefaultCatalogFactory.java`` — catalogs declared as
properties (``etc/catalog/*.properties``) instantiated through the
connector factories.  The config form here is a plain dict so it ships
to worker processes and (later) loads from files:
``{"tpch": {"connector": "tpch", "page_rows": 65536}}``.
"""

from __future__ import annotations

from typing import Dict

from ..types import TrinoError
from .spi import Connector


def create_catalog(name: str, config: dict) -> Connector:
    kind = config.get("connector", name)
    options = {k: v for k, v in config.items() if k != "connector"}
    if kind == "tpch":
        from .tpch import TpchConnector

        return TpchConnector(catalog_name=name, **options)
    if kind == "memory":
        from .memory import MemoryConnector

        return MemoryConnector(catalog_name=name, **options)
    if kind == "blackhole":
        from .blackhole import BlackHoleConnector

        return BlackHoleConnector(catalog_name=name, **options)
    if kind == "tpcds":
        from .tpcds import TpcdsConnector

        return TpcdsConnector(catalog_name=name, **options)
    raise TrinoError(f"unknown connector '{kind}' for catalog '{name}'",
                     "CATALOG_NOT_FOUND")


def create_catalogs(config: Dict[str, dict]) -> Dict[str, Connector]:
    return {name: create_catalog(name, c) for name, c in config.items()}
