"""In-memory table connector.

Reference analog: ``plugin/trino-memory`` (``MemoryConnector.java``,
``MemoryMetadata``, ``MemoryPagesStore``) — the engine's writable test
fixture and cache connector. Tables live as host Page lists per
(schema, table); writes append under a lock so scaled/parallel writers
can share one sink target.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..block import Page
from ..types import TrinoError
from .spi import (ColumnHandle, Connector, ConnectorMetadata,
                  ConnectorPageSink, ConnectorPageSource,
                  ConnectorSplit, ConnectorSplitManager, FixedPageSource,
                  TableHandle, TableStatistics)


class _TableData:
    def __init__(self, columns: List[ColumnHandle]):
        from ..block import Dictionary

        self.columns = columns
        self.pages: List[Page] = []
        self.lock = threading.Lock()
        # canonical per-column pools: appended pages re-encode into these
        # so scans present stable code spaces (group-by/join correctness)
        self.dicts = [Dictionary() if c.type.is_string else None
                      for c in columns]

    @property
    def row_count(self) -> int:
        return sum(p.num_rows for p in self.pages)

    def canonicalize(self, page: Page) -> Page:
        import numpy as np

        from ..block import Block

        blocks = []
        for i, c in enumerate(self.columns):
            b = page.block(i).numpy()
            if c.type.is_string and b.dictionary is not self.dicts[i]:
                d = self.dicts[i]
                remap = d.encode(b.dictionary.values) \
                    if len(b.dictionary) else np.empty(0, np.int32)
                data = remap[b.data] if len(remap) else b.data
                blocks.append(Block(c.type, data, b.nulls, d))
            else:
                blocks.append(b)
        return Page(blocks, page.num_rows)


class MemoryMetadata(ConnectorMetadata):
    def __init__(self, conn: "MemoryConnector"):
        self.conn = conn

    def list_schemas(self) -> List[str]:
        return sorted(self.conn.schemas)

    def list_tables(self, schema: str) -> List[str]:
        return sorted(t for (s, t) in self.conn.tables if s == schema)

    def get_table_handle(self, schema, table) -> Optional[TableHandle]:
        if (schema, table) in self.conn.tables:
            return TableHandle(self.conn.catalog_name, schema, table)
        return None

    def apply_filter(self, table: TableHandle, constraint):
        """Row-level enforcement over the stored pages (reference:
        ConnectorMetadata.applyFilter)."""
        from .spi import negotiate_constraint

        data = self.conn.tables.get((table.schema, table.table))
        if data is None:
            return None
        return negotiate_constraint(table, constraint,
                                    (c.name for c in data.columns))

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        return self.conn.tables[(table.schema, table.table)].columns

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        data = self.conn.tables[(table.schema, table.table)]
        return TableStatistics(row_count=float(data.row_count))

    def create_table(self, schema: str, table: str,
                     columns: List[ColumnHandle]) -> TableHandle:
        with self.conn.lock:
            if (schema, table) in self.conn.tables:
                raise TrinoError(f"Table '{schema}.{table}' already exists",
                                 "TABLE_ALREADY_EXISTS")
            self.conn.tables[(schema, table)] = _TableData(list(columns))
            self.conn.schemas.add(schema)
            self.conn._version += 1      # DDL invalidates cached plans
        return TableHandle(self.conn.catalog_name, schema, table)

    def drop_table(self, table: TableHandle):
        with self.conn.lock:
            self.conn.tables.pop((table.schema, table.table), None)
            self.conn._version += 1      # DDL invalidates cached plans


class MemorySplitManager(ConnectorSplitManager):
    def __init__(self, conn: "MemoryConnector"):
        self.conn = conn

    def get_splits(self, table: TableHandle,
                   desired_splits: int) -> List[ConnectorSplit]:
        data = self.conn.tables[(table.schema, table.table)]
        n = len(data.pages)
        k = max(1, min(desired_splits, n)) if n else 1
        return [ConnectorSplit(table, i, k, i, n, info={"stride": k})
                for i in range(k)]


class MemoryPageSink(ConnectorPageSink):
    def __init__(self, data: _TableData, conn: "MemoryConnector"):
        self.data = data
        self.rows = 0
        self.conn = conn

    def append_page(self, page: Page):
        page = self.data.canonicalize(page)
        with self.data.lock:
            self.data.pages.append(page)
            self.rows += page.num_rows
        # bump per page, not only at finish: a cached read overlapping a
        # half-complete write must already see a moved snapshot version
        self.conn.bump_version()

    def finish(self) -> dict:
        return {"rows": self.rows}


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self, catalog_name: str = "memory",
                 schemas: Sequence[str] = ("default",)):
        self.catalog_name = catalog_name
        self.schemas = set(schemas)
        self.tables: Dict[Tuple[str, str], _TableData] = {}
        self.lock = threading.Lock()
        self._version = 0

    def data_version(self) -> int:
        """Snapshot version for the plan/result caches: every DDL and
        every written page bumps it, so dependent cache entries miss."""
        return self._version

    def bump_version(self):
        with self.lock:
            self._version += 1

    def metadata(self) -> ConnectorMetadata:
        return MemoryMetadata(self)

    def split_manager(self) -> ConnectorSplitManager:
        return MemorySplitManager(self)

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        data = self.tables[(split.table.schema, split.table.table)]
        stride = (split.info or {}).get("stride", 1)
        with data.lock:
            mine = data.pages[split.row_start::stride] if data.pages else []
        ordinals = [c.ordinal for c in columns]
        cons = split.table.constraint
        if cons is not None:
            from .spi import enforce_constraint_page

            names = [c.name for c in data.columns]
            return FixedPageSource([
                enforce_constraint_page(p, names, cons, ordinals)
                for p in mine])
        return FixedPageSource([p.select_channels(ordinals) for p in mine])

    def page_sink(self, table: TableHandle,
                  columns: Sequence[ColumnHandle]) -> ConnectorPageSink:
        return MemoryPageSink(self.tables[(table.schema, table.table)],
                              self)
