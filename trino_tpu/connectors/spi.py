"""Connector SPI — the pluggable storage boundary.

Reference analog: ``core/trino-spi/src/main/java/io/trino/spi/connector/``
(~100 interfaces: ConnectorMetadata, ConnectorSplitManager,
ConnectorPageSource/Sink, ConnectorTableHandle, ...). Compressed to the
load-bearing surface: metadata CRUD, split enumeration, page sources with
column pruning + predicate pushdown hooks, page sinks for writes.

TPU-first notes: page sources yield host ``Page``s (numpy + dictionaries);
the scan operator moves them on device. Splits carry a deterministic
row-range so distributed scans are reproducible regardless of split count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence

from .. import types as T
from ..block import Page


@dataclass(frozen=True)
class ColumnHandle:
    name: str
    type: T.Type
    ordinal: int


@dataclass(frozen=True)
class TableHandle:
    catalog: str
    schema: str
    table: str

    @property
    def qualified_name(self) -> str:
        return f"{self.catalog}.{self.schema}.{self.table}"


@dataclass(frozen=True)
class ConnectorSplit:
    """A unit of scan parallelism (reference: spi/connector/ConnectorSplit).
    ``row_start``/``row_end`` give deterministic slicing for generators;
    file-backed connectors may carry opaque ``info`` instead."""

    table: TableHandle
    split_id: int
    total_splits: int
    row_start: int = 0
    row_end: int = 0
    info: Optional[dict] = None


class ConnectorPageSource:
    """Pull-based page iterator for one split (reference:
    spi/connector/ConnectorPageSource.java)."""

    def get_next_page(self) -> Optional[Page]:
        raise NotImplementedError

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


@dataclass
class TableStatistics:
    row_count: Optional[float] = None
    # per-column: distinct count, min, max, null fraction
    columns: dict = field(default_factory=dict)


@dataclass
class ColumnStatistics:
    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Optional[object] = None
    max_value: Optional[object] = None


class ConnectorMetadata:
    """Schema browsing + table resolution (reference:
    spi/connector/ConnectorMetadata.java)."""

    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        raise NotImplementedError

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        raise NotImplementedError

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        return TableStatistics()

    # -- DDL (reference: ConnectorMetadata createTable/dropTable) ------

    def create_table(self, schema: str, table: str,
                     columns: List[ColumnHandle]) -> TableHandle:
        raise T.TrinoError("connector does not support CREATE TABLE",
                           "NOT_SUPPORTED")

    def drop_table(self, table: TableHandle):
        raise T.TrinoError("connector does not support DROP TABLE",
                           "NOT_SUPPORTED")


class ConnectorSplitManager:
    """Split enumeration (reference: spi/connector/ConnectorSplitManager)."""

    def get_splits(self, table: TableHandle,
                   desired_splits: int) -> List[ConnectorSplit]:
        raise NotImplementedError


class ConnectorPageSink:
    """Write path (reference: spi/connector/ConnectorPageSink.java)."""

    def append_page(self, page: Page):
        raise NotImplementedError

    def finish(self) -> dict:
        return {}

    def abort(self):
        pass


class Connector:
    """One catalog's storage engine (reference: spi/connector/Connector.java).

    Subclasses provide metadata/splits/page-sources; ``page_sink`` is
    optional (read-only connectors raise)."""

    name = "base"

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def split_manager(self) -> ConnectorSplitManager:
        raise NotImplementedError

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        raise NotImplementedError

    def page_sink(self, table: TableHandle,
                  columns: Sequence[ColumnHandle]) -> ConnectorPageSink:
        raise T.TrinoError(f"connector {self.name} does not support writes",
                           "NOT_SUPPORTED")


class FixedPageSource(ConnectorPageSource):
    """Page source over a prebuilt page list (test fixture; reference:
    spi/connector/FixedPageSource.java)."""

    def __init__(self, pages: Sequence[Page]):
        self._pages: Iterator[Page] = iter(pages)
        self._done = False
        self._next: Optional[Page] = None

    def get_next_page(self) -> Optional[Page]:
        try:
            return next(self._pages)
        except StopIteration:
            self._done = True
            return None

    def is_finished(self) -> bool:
        return self._done
