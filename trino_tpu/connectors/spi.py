"""Connector SPI — the pluggable storage boundary.

Reference analog: ``core/trino-spi/src/main/java/io/trino/spi/connector/``
(~100 interfaces: ConnectorMetadata, ConnectorSplitManager,
ConnectorPageSource/Sink, ConnectorTableHandle, ...). Compressed to the
load-bearing surface: metadata CRUD, split enumeration, page sources with
column pruning + predicate pushdown hooks, page sinks for writes.

TPU-first notes: page sources yield host ``Page``s (numpy + dictionaries);
the scan operator moves them on device. Splits carry a deterministic
row-range so distributed scans are reproducible regardless of split count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple

from .. import types as T
from ..block import Page
from ..predicate import TupleDomain


@dataclass(frozen=True)
class ColumnHandle:
    name: str
    type: T.Type
    ordinal: int


@dataclass(frozen=True)
class TableHandle:
    catalog: str
    schema: str
    table: str
    #: the TupleDomain (over column NAMES) the connector agreed to
    #: enforce (apply_filter attaches it; page sources mask rows under
    #: it) — the typed analog of the reference's opaque
    #: ConnectorTableHandle carrying its enforced constraint
    constraint: Optional[TupleDomain] = None

    @property
    def qualified_name(self) -> str:
        return f"{self.catalog}.{self.schema}.{self.table}"


def negotiate_constraint(table: "TableHandle", constraint: TupleDomain,
                         names, enforceable=None
                         ) -> Optional[Tuple["TableHandle", TupleDomain]]:
    """The standard apply_filter body shared by the generator/memory
    connectors: accept the offered domains naming real columns the
    connector can enforce, intersect with any constraint already on the
    handle, and return the RESIDUAL TupleDomain the engine must keep
    filtering (reference: ConstraintApplicationResult.java with
    remainingFilter). ``enforceable`` limits acceptance to a column
    subset (None = every real column — full enforcement). Returns None
    when nothing new would be enforced (stops planner loops)."""
    from dataclasses import replace as _dc_replace

    if constraint.is_none or constraint.is_all:
        return None
    names = set(names)
    if enforceable is not None:
        names &= set(enforceable)
    offered = constraint.as_dict()
    accepted = {k: d for k, d in offered.items() if k in names}
    if not accepted:
        return None
    residual = TupleDomain.of({k: d for k, d in offered.items()
                               if k not in names})
    offer = TupleDomain.of(accepted)
    combined = table.constraint.intersect(offer) \
        if table.constraint is not None else offer
    if combined == table.constraint:
        return None
    return _dc_replace(table, constraint=combined), residual


def constrained_gen_columns(columns: Sequence[str],
                            constraint) -> List[str]:
    """Projected columns plus any constrained-but-pruned columns a
    generator must also produce so the row mask can be evaluated."""
    if constraint is None or constraint.is_all:
        return list(columns)
    have = set(columns)
    return list(columns) + [n for n, _ in (constraint.columns or ())
                            if n not in have]


def enforce_constraint_page(page: Page, names: Sequence[str], constraint,
                            project: Optional[Sequence[int]] = None
                            ) -> Page:
    """Shared row-level constraint enforcement for connectors: mask rows
    under a TupleDomain keyed by column NAME (evaluated positionally
    against ``names``), then optionally project to a channel subset.
    This is what an apply_filter acceptance promises the engine."""
    from ..block import Block
    from ..predicate import domain_mask

    if constraint is None or constraint.is_none:
        doms = {}
        empty = constraint is not None
    else:
        doms = constraint.as_dict()
        empty = False
    mask = None
    if empty:
        import numpy as np

        mask = np.zeros(page.num_rows, dtype=bool)
    else:
        for i, n in enumerate(names):
            d = doms.get(n)
            if d is None or d.is_all:
                continue
            b = page.block(i).numpy()
            m = domain_mask(b.data, b.nulls, b.dictionary, d)
            mask = m if mask is None else (mask & m)
    blocks = page.blocks if project is None \
        else [page.blocks[i] for i in project]
    if mask is None or mask.all():
        return page if project is None else Page(list(blocks),
                                                 page.num_rows)
    out = []
    for b in blocks:
        b = b.numpy()
        out.append(Block(b.type, b.data[mask],
                         b.nulls[mask] if b.nulls is not None else None,
                         b.dictionary))
    return Page(out, int(mask.sum()))


@dataclass(frozen=True)
class ConnectorSplit:
    """A unit of scan parallelism (reference: spi/connector/ConnectorSplit).
    ``row_start``/``row_end`` give deterministic slicing for generators;
    file-backed connectors may carry opaque ``info`` instead."""

    table: TableHandle
    split_id: int
    total_splits: int
    row_start: int = 0
    row_end: int = 0
    info: Optional[dict] = None


class ConnectorPageSource:
    """Pull-based page iterator for one split (reference:
    spi/connector/ConnectorPageSource.java)."""

    def get_next_page(self) -> Optional[Page]:
        raise NotImplementedError

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


@dataclass
class TableStatistics:
    row_count: Optional[float] = None
    # per-column: distinct count, min, max, null fraction
    columns: dict = field(default_factory=dict)


@dataclass
class ColumnStatistics:
    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Optional[object] = None
    max_value: Optional[object] = None


class ConnectorMetadata:
    """Schema browsing + table resolution (reference:
    spi/connector/ConnectorMetadata.java)."""

    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_handle(self, schema: str, table: str) -> Optional[TableHandle]:
        raise NotImplementedError

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        raise NotImplementedError

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        return TableStatistics()

    def apply_filter(self, table: TableHandle, constraint
                     ) -> Optional[Tuple[TableHandle, object]]:
        """Pushdown negotiation (reference:
        spi/connector/ConnectorMetadata.java applyFilter): offered a
        TupleDomain over column NAMES, return (new_handle,
        remaining_domain) — the handle carrying what the connector will
        enforce, and the part it cannot (TupleDomain.all_() when fully
        enforced) — or None to decline entirely."""
        return None

    # -- DDL (reference: ConnectorMetadata createTable/dropTable) ------

    def create_table(self, schema: str, table: str,
                     columns: List[ColumnHandle]) -> TableHandle:
        raise T.TrinoError("connector does not support CREATE TABLE",
                           "NOT_SUPPORTED")

    def drop_table(self, table: TableHandle):
        raise T.TrinoError("connector does not support DROP TABLE",
                           "NOT_SUPPORTED")


class ConnectorSplitManager:
    """Split enumeration (reference: spi/connector/ConnectorSplitManager)."""

    def get_splits(self, table: TableHandle,
                   desired_splits: int) -> List[ConnectorSplit]:
        raise NotImplementedError


class ConnectorPageSink:
    """Write path (reference: spi/connector/ConnectorPageSink.java)."""

    def append_page(self, page: Page):
        raise NotImplementedError

    def finish(self) -> dict:
        return {}

    def abort(self):
        pass


class Connector:
    """One catalog's storage engine (reference: spi/connector/Connector.java).

    Subclasses provide metadata/splits/page-sources; ``page_sink`` is
    optional (read-only connectors raise)."""

    name = "base"

    def data_version(self) -> Optional[int]:
        """Monotonic snapshot version of this catalog's data+metadata,
        or None when the connector cannot promise stability (live
        catalogs like ``system``).  The plan/result caches key on it:
        any DDL or write MUST move the version, and a None makes every
        statement touching the catalog uncacheable (reference analog:
        the connector ``getTableHandle`` snapshot id materialized-view
        staleness checks key on)."""
        return None

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def split_manager(self) -> ConnectorSplitManager:
        raise NotImplementedError

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        raise NotImplementedError

    def page_sink(self, table: TableHandle,
                  columns: Sequence[ColumnHandle]) -> ConnectorPageSink:
        raise T.TrinoError(f"connector {self.name} does not support writes",
                           "NOT_SUPPORTED")


class FixedPageSource(ConnectorPageSource):
    """Page source over a prebuilt page list (test fixture; reference:
    spi/connector/FixedPageSource.java)."""

    def __init__(self, pages: Sequence[Page]):
        self._pages: Iterator[Page] = iter(pages)
        self._done = False
        self._next: Optional[Page] = None

    def get_next_page(self) -> Optional[Page]:
        try:
            return next(self._pages)
        except StopIteration:
            self._done = True
            return None

    def is_finished(self) -> bool:
        return self._done
