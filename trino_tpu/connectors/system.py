"""System connector: the engine's live state as SQL tables.

Reference analog: ``core/trino-main/.../connector/system/`` —
``GlobalSystemConnector`` serving ``system.runtime.queries`` /
``system.runtime.tasks`` (QuerySystemTable, TaskSystemTable over the
coordinator's QueryManager) plus the jmx metrics tables.  Here one
connector instance is bound to its owning runner (the ``source``) and
materializes a snapshot page per scan:

- ``system.runtime.queries``: running queries (event-manager running
  set) + the completed-query ring buffer, with wall/rows/error;
- ``system.runtime.tasks``: tasks currently tracked by live workers
  (process runner) — empty for single-process runners;
- ``system.runtime.metrics``: the flattened metrics registry, one row
  per (name, labels) sample — the SQL view of ``GET /v1/metrics``;
- ``system.runtime.kernels``: the compiled-program profiler registry
  (telemetry.profiler) — one row per compiled program with trace/
  compile wall and XLA cost analysis; empty until profiling runs
  (``query_profiling_enabled`` or EXPLAIN ANALYZE VERBOSE).  Process-
  local: under the multi-process runner this is the COORDINATOR's
  registry (worker registries ride the heartbeat metrics piggyback).

System tables always execute at the coordinator: the process runner
routes statements touching this catalog to a local execution, so the
catalog never ships to worker processes.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from .. import types as T
from ..block import Page
from .spi import (ColumnHandle, Connector, ConnectorMetadata,
                  ConnectorPageSource, ConnectorSplit,
                  ConnectorSplitManager, FixedPageSource, TableHandle,
                  TableStatistics)

RUNTIME_SCHEMA = "runtime"

#: table -> ordered (column, type) schema
RUNTIME_TABLES = {
    "queries": (
        ("query_id", T.VARCHAR), ("state", T.VARCHAR),
        ("user", T.VARCHAR), ("query", T.VARCHAR),
        ("started", T.DOUBLE), ("wall_ms", T.DOUBLE),
        ("rows", T.BIGINT), ("error_code", T.VARCHAR),
        ("slow", T.VARCHAR)),
    "tasks": (
        ("task_id", T.VARCHAR), ("query_id", T.VARCHAR),
        ("worker", T.VARCHAR), ("state", T.VARCHAR),
        ("rows", T.BIGINT), ("error_type", T.VARCHAR)),
    "nodes": (
        ("node_id", T.VARCHAR), ("address", T.VARCHAR),
        ("state", T.VARCHAR), ("pid", T.BIGINT),
        ("generation", T.BIGINT), ("join_reason", T.VARCHAR),
        ("retire_reason", T.VARCHAR)),
    "metrics": (
        ("name", T.VARCHAR), ("labels", T.VARCHAR),
        ("kind", T.VARCHAR), ("value", T.DOUBLE)),
    "kernels": (
        ("name", T.VARCHAR), ("key", T.VARCHAR),
        ("compiles", T.BIGINT), ("calls", T.BIGINT),
        ("trace_ms", T.DOUBLE), ("compile_ms", T.DOUBLE),
        ("execute_ms", T.DOUBLE), ("flops", T.DOUBLE),
        ("bytes_accessed", T.DOUBLE), ("output_bytes", T.BIGINT),
        ("temp_bytes", T.BIGINT), ("code_bytes", T.BIGINT)),
    "plan_stats": (
        ("statement", T.VARCHAR), ("node", T.VARCHAR),
        ("name", T.VARCHAR), ("runs", T.BIGINT),
        ("rows", T.DOUBLE), ("bytes", T.DOUBLE),
        ("wall_ms", T.DOUBLE), ("flops", T.DOUBLE),
        ("peak_memory_bytes", T.DOUBLE)),
}


class _SystemMetadata(ConnectorMetadata):
    def __init__(self, conn: "SystemConnector"):
        self.conn = conn

    def list_schemas(self) -> List[str]:
        return [RUNTIME_SCHEMA]

    def list_tables(self, schema: str) -> List[str]:
        return sorted(RUNTIME_TABLES) if schema == RUNTIME_SCHEMA else []

    def get_table_handle(self, schema: str,
                         table: str) -> Optional[TableHandle]:
        if schema == RUNTIME_SCHEMA and table in RUNTIME_TABLES:
            return TableHandle(self.conn.catalog_name, schema, table)
        return None

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        return [ColumnHandle(name, type_, i) for i, (name, type_)
                in enumerate(RUNTIME_TABLES[table.table])]

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        return TableStatistics(row_count=64.0)


class SystemConnector(Connector):
    """``source`` is the owning runner (duck-typed): ``event_manager``
    backs the queries table, ``runtime_tasks()`` the tasks table,
    ``runtime_nodes()`` the nodes table (elastic membership ledger),
    and ``metrics_families()`` the metrics table; each is optional so
    any runner can host the catalog."""

    name = "system"

    def data_version(self) -> None:
        """Live catalog — every read reflects CURRENT runner state, so
        statements touching it are uncacheable (inherits the base None;
        spelled out because the plan/result caches depend on it)."""
        return None

    def __init__(self, catalog_name: str = "system", source=None,
                 history_limit: int = 200):
        self.catalog_name = catalog_name
        self.source = source
        self.history_limit = history_limit

    def metadata(self) -> ConnectorMetadata:
        return _SystemMetadata(self)

    def split_manager(self) -> ConnectorSplitManager:
        class _SM(ConnectorSplitManager):
            def get_splits(self, table, desired_splits):
                # coordinator-local state: exactly one split
                return [ConnectorSplit(table, 0, 1, 0, 0)]

        return _SM()

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]
                    ) -> ConnectorPageSource:
        rows = self._rows(split.table.table)
        types_ = [c.type for c in columns]
        data = [[row[c.ordinal] for row in rows] for c in columns]
        if not rows:
            return FixedPageSource([])
        return FixedPageSource([Page.from_pylists(types_, data)])

    # -- row builders ------------------------------------------------------

    def _rows(self, table: str) -> List[tuple]:
        try:
            if table == "queries":
                return self._query_rows()
            if table == "tasks":
                return self._task_rows()
            if table == "nodes":
                return self._node_rows()
            if table == "kernels":
                return self._kernel_rows()
            if table == "plan_stats":
                return self._plan_stats_rows()
            return self._metric_rows()
        except Exception:
            # introspection must never fail a query over it; a torn
            # snapshot surfaces as missing rows, not an engine error
            return []

    def _query_rows(self) -> List[tuple]:
        mgr = getattr(self.source, "event_manager", None)
        if mgr is None:
            return []
        rows = []
        now = time.time()
        for e in mgr.running():
            rows.append((e.query_id, "RUNNING", e.user, e.sql,
                         e.create_time,
                         round((now - e.create_time) * 1e3, 2),
                         None, None, None))
        for e in mgr.history(self.history_limit):
            slow = (e.stats or {}).get("slow_query")
            rows.append((e.query_id, e.state, e.user, e.sql,
                         e.create_time, round(e.wall_ms, 2),
                         e.output_rows, e.error_code,
                         self._slow_text(slow)))
        return rows

    @staticmethod
    def _slow_text(slow) -> Optional[str]:
        """Compact rendering of a slow-query record: critical path +
        top cost operators + the worst-misestimated plan node, one
        cell (the full dict stays on the event)."""
        if not slow:
            return None
        parts = [f"wall={slow.get('wall_ms', 0)}ms"]
        cp = slow.get("critical_path")
        if cp:
            parts.append("path=" + " > ".join(
                f"{s['name']} {s['ms']}ms" for s in cp))
        top = slow.get("top_operators")
        if top:
            parts.append("top=" + ", ".join(
                f"{o['name']} {o['busy_ms']}ms" for o in top))
        worst = slow.get("worst_misestimate")
        if worst:
            parts.append(
                f"misest={worst['name']} est {worst['est_rows']} "
                f"actual {worst['actual_rows']} q={worst['qerror']}")
        return "; ".join(parts)

    @staticmethod
    def _plan_stats_rows() -> List[tuple]:
        from ..telemetry import stats_store

        rows = []
        for e in stats_store.store().snapshot():
            rows.append((e["statement"], e["fp"], e["name"],
                         e["runs"], round(e["rows"], 2),
                         round(e["bytes"], 2), round(e["wall_ms"], 3),
                         round(e["flops"], 2),
                         round(e["peak_bytes"], 2)))
        return rows

    def _kernel_rows(self) -> List[tuple]:
        from ..telemetry import profiler

        rows = []
        for e in profiler.snapshot():
            rows.append((e["name"], e["key"], e["compiles"], e["calls"],
                         e["trace_ms"], e["compile_ms"],
                         e["execute_ms"], e["flops"],
                         e["bytes_accessed"], e["output_bytes"],
                         e["temp_bytes"], e["code_bytes"]))
        return rows

    def _task_rows(self) -> List[tuple]:
        fn = getattr(self.source, "runtime_tasks", None)
        return [tuple(r) for r in fn()] if callable(fn) else []

    def _node_rows(self) -> List[tuple]:
        fn = getattr(self.source, "runtime_nodes", None)
        return [tuple(r) for r in fn()] if callable(fn) else []

    def _metric_rows(self) -> List[tuple]:
        fn = getattr(self.source, "metrics_families", None)
        if not callable(fn):
            return []
        from ..telemetry.metrics import _fmt_labels

        rows = []
        for fam in fn():
            for labels, value in fam["samples"]:
                label_str = _fmt_labels(labels)
                if fam["type"] == "histogram":
                    rows.append((fam["name"] + "_count", label_str,
                                 "histogram", float(value["count"])))
                    rows.append((fam["name"] + "_sum", label_str,
                                 "histogram", float(value["sum"])))
                else:
                    rows.append((fam["name"], label_str, fam["type"],
                                 float(value)))
        return rows
