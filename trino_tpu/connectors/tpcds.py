"""TPC-DS synthetic data connector.

Reference analog: ``plugin/trino-tpcds`` (TpcdsConnectorFactory,
TpcdsMetadata wrapping the teradata dsdgen port).

Like the TPC-H connector this is a from-scratch, vectorized,
counter-based generator (every value a pure function of
(table, column, row) through splitmix64) — NOT a dsdgen port. Schemas
follow the TPC-DS v2 specification for the star-schema subset the
benchmark queries exercise (15 tables: the store/catalog sales channels
with their returns, inventory, and the shared dimensions). Value
distributions are plausible rather than dsdgen-exact; correctness
testing cross-checks queries against a sqlite oracle loaded with THIS
generator's data (same contract as the TPC-H oracle suite), and the
micro scale biases item color/price so the filter-heavy benchmark
queries (q64/q72) keep non-trivial selectivity.

Facts link the way the spec requires: store_returns rows derive from
their originating store_sales rows (join on item_sk + ticket_number),
catalog_returns from catalog_sales (item_sk + order_number), and
inventory covers every (week, item, warehouse) cell of the date range.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import types as T
from ..block import Block, Dictionary, Page
from ..expr.functions import days_from_civil_host
from .spi import (ColumnHandle, Connector, ConnectorMetadata,
                  ConnectorPageSource, ConnectorSplit, ConnectorSplitManager,
                  ColumnStatistics, TableHandle, TableStatistics)
from .tpch import COLORS, _TEXT_WORDS, _comment, h64, hmod

V = T.varchar_type
D72 = T.decimal_type(7, 2)
D52 = T.decimal_type(5, 2)

# -- spec value domains (TPC-DS v2 §3; shared constants, not dbgen output) --

BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
                 "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
GENDER = ["M", "F"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CREDIT_RATING = ["Low Risk", "Good", "High Risk", "Unknown"]
STREET_TYPES = ["Street", "Ave", "Blvd", "Way", "Ct", "Ln", "Dr", "Pkwy",
                "Road", "Circle"]
LOCATION_TYPES = ["apartment", "condo", "single family"]
STATES = ["AL", "CA", "GA", "IA", "IL", "KS", "MI", "MN", "MO", "NC",
          "NE", "NY", "OH", "OK", "OR", "TN", "TX", "VA", "WA", "WI"]
SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["accent", "accessories", "athletic", "classical", "custom",
           "dresses", "estate", "fiction", "fragrances", "pants"]
UNITS = ["Each", "Dozen", "Case", "Pallet", "Gross", "Box", "Carton",
         "Unknown"]
SIZES = ["small", "medium", "large", "extra large", "petite", "N/A"]
CONTAINERS = ["Unknown"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]
HOURS = ["8AM-4PM", "8AM-8PM", "8AM-12AM"]
#: the q64 filter colors — micro-scale bias keeps the query selective
#: but non-empty (see module docstring)
Q64_COLORS = ["purple", "burlywood", "indian", "spring", "floral", "medium"]

_DS_START = days_from_civil_host(1998, 1, 1)      # date_dim coverage
_DS_DAYS = days_from_civil_host(2002, 12, 31) - _DS_START + 1   # 1826
_SOLD_DAYS = days_from_civil_host(2001, 12, 31) - _DS_START + 1  # sales span
_SK0 = 2450815          # d_date_sk of the first covered day
_WEEK_SEQ0 = 5270       # arbitrary but stable week-sequence base

_SCHEMAS = {"micro": 0.001, "tiny": 0.01, "sf1": 1.0, "sf10": 10.0,
            "sf100": 100.0, "sf1000": 1000.0}


def _counts(sf: float) -> Dict[str, int]:
    c = {
        "date_dim": _DS_DAYS,
        "income_band": 20,
        "item": max(1000, int(18_000 * sf)),
        "customer": max(200, int(100_000 * sf)),
        "customer_address": max(100, int(50_000 * sf)),
        "customer_demographics": max(400, min(1_920_800,
                                              int(1_920_800 * sf))),
        "household_demographics": max(72, min(7_200, int(7_200 * sf))),
        "promotion": max(10, int(300 * sf)),
        "store": max(2, int(12 * sf)),
        "warehouse": max(2, int(5 * sf)),
        "store_sales": max(100, int(2_880_000 * sf)),
        "catalog_sales": max(100, int(1_440_000 * sf)),
    }
    c["store_returns"] = c["store_sales"] // 2
    c["catalog_returns"] = c["catalog_sales"] // 3
    c["inventory"] = ((_DS_DAYS + 6) // 7) * c["warehouse"] \
        * min(c["item"], max(200, int(c["item"] * 0.2)))
    # web channel + remaining dimensions (full 24-table schema)
    c["time_dim"] = 86_400
    c["reason"] = 35
    c["ship_mode"] = 20
    c["call_center"] = max(2, int(6 * sf))
    c["catalog_page"] = max(100, int(11_718 * sf))
    c["web_site"] = max(2, int(30 * sf))
    c["web_page"] = max(10, int(60 * sf))
    c["web_sales"] = max(100, int(720_000 * sf))
    c["web_returns"] = c["web_sales"] // 3
    return c


def _inv_items(sf: float) -> int:
    """Items covered by inventory (a dense prefix of item_sk)."""
    c = _counts(sf)
    return min(c["item"], max(200, int(c["item"] * 0.2)))


_TABLE_COLUMNS: Dict[str, List] = {
    "date_dim": [
        ("d_date_sk", T.BIGINT), ("d_date_id", V(16)), ("d_date", T.DATE),
        ("d_month_seq", T.BIGINT), ("d_week_seq", T.BIGINT),
        ("d_quarter_seq", T.BIGINT), ("d_year", T.BIGINT),
        ("d_dow", T.BIGINT), ("d_moy", T.BIGINT), ("d_dom", T.BIGINT),
        ("d_qoy", T.BIGINT), ("d_fy_year", T.BIGINT),
        ("d_fy_quarter_seq", T.BIGINT), ("d_fy_week_seq", T.BIGINT),
        ("d_day_name", V(9)), ("d_quarter_name", V(6)), ("d_holiday", V(1)),
        ("d_weekend", V(1)), ("d_following_holiday", V(1)),
        ("d_first_dom", T.BIGINT), ("d_last_dom", T.BIGINT),
        ("d_same_day_ly", T.BIGINT), ("d_same_day_lq", T.BIGINT),
        ("d_current_day", V(1)), ("d_current_week", V(1)),
        ("d_current_month", V(1)), ("d_current_quarter", V(1)),
        ("d_current_year", V(1))],
    "item": [
        ("i_item_sk", T.BIGINT), ("i_item_id", V(16)),
        ("i_rec_start_date", T.DATE), ("i_rec_end_date", T.DATE),
        ("i_item_desc", V(200)), ("i_current_price", D72),
        ("i_wholesale_cost", D72), ("i_brand_id", T.BIGINT),
        ("i_brand", V(50)), ("i_class_id", T.BIGINT), ("i_class", V(50)),
        ("i_category_id", T.BIGINT), ("i_category", V(50)),
        ("i_manufact_id", T.BIGINT), ("i_manufact", V(50)),
        ("i_size", V(20)), ("i_formulation", V(20)), ("i_color", V(20)),
        ("i_units", V(10)), ("i_container", V(10)),
        ("i_manager_id", T.BIGINT), ("i_product_name", V(50))],
    "customer": [
        ("c_customer_sk", T.BIGINT), ("c_customer_id", V(16)),
        ("c_current_cdemo_sk", T.BIGINT), ("c_current_hdemo_sk", T.BIGINT),
        ("c_current_addr_sk", T.BIGINT),
        ("c_first_shipto_date_sk", T.BIGINT),
        ("c_first_sales_date_sk", T.BIGINT), ("c_salutation", V(10)),
        ("c_first_name", V(20)), ("c_last_name", V(30)),
        ("c_preferred_cust_flag", V(1)), ("c_birth_day", T.BIGINT),
        ("c_birth_month", T.BIGINT), ("c_birth_year", T.BIGINT),
        ("c_birth_country", V(20)), ("c_login", V(13)),
        ("c_email_address", V(50)), ("c_last_review_date_sk", T.BIGINT)],
    "customer_address": [
        ("ca_address_sk", T.BIGINT), ("ca_address_id", V(16)),
        ("ca_street_number", V(10)), ("ca_street_name", V(60)),
        ("ca_street_type", V(15)), ("ca_suite_number", V(10)),
        ("ca_city", V(60)), ("ca_county", V(30)), ("ca_state", V(2)),
        ("ca_zip", V(10)), ("ca_country", V(20)), ("ca_gmt_offset", D52),
        ("ca_location_type", V(20))],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT), ("cd_gender", V(1)),
        ("cd_marital_status", V(1)), ("cd_education_status", V(20)),
        ("cd_purchase_estimate", T.BIGINT), ("cd_credit_rating", V(10)),
        ("cd_dep_count", T.BIGINT), ("cd_dep_employed_count", T.BIGINT),
        ("cd_dep_college_count", T.BIGINT)],
    "household_demographics": [
        ("hd_demo_sk", T.BIGINT), ("hd_income_band_sk", T.BIGINT),
        ("hd_buy_potential", V(15)), ("hd_dep_count", T.BIGINT),
        ("hd_vehicle_count", T.BIGINT)],
    "income_band": [
        ("ib_income_band_sk", T.BIGINT), ("ib_lower_bound", T.BIGINT),
        ("ib_upper_bound", T.BIGINT)],
    "promotion": [
        ("p_promo_sk", T.BIGINT), ("p_promo_id", V(16)),
        ("p_start_date_sk", T.BIGINT), ("p_end_date_sk", T.BIGINT),
        ("p_item_sk", T.BIGINT), ("p_cost", T.decimal_type(15, 2)),
        ("p_response_target", T.BIGINT), ("p_promo_name", V(50)),
        ("p_channel_dmail", V(1)), ("p_channel_email", V(1)),
        ("p_channel_catalog", V(1)), ("p_channel_tv", V(1)),
        ("p_channel_radio", V(1)), ("p_channel_press", V(1)),
        ("p_channel_event", V(1)), ("p_channel_demo", V(1)),
        ("p_channel_details", V(100)), ("p_purpose", V(15)),
        ("p_discount_active", V(1))],
    "store": [
        ("s_store_sk", T.BIGINT), ("s_store_id", V(16)),
        ("s_rec_start_date", T.DATE), ("s_rec_end_date", T.DATE),
        ("s_closed_date_sk", T.BIGINT), ("s_store_name", V(50)),
        ("s_number_employees", T.BIGINT), ("s_floor_space", T.BIGINT),
        ("s_hours", V(20)), ("s_manager", V(40)), ("s_market_id", T.BIGINT),
        ("s_geography_class", V(100)), ("s_market_desc", V(100)),
        ("s_market_manager", V(40)), ("s_division_id", T.BIGINT),
        ("s_division_name", V(50)), ("s_company_id", T.BIGINT),
        ("s_company_name", V(50)), ("s_street_number", V(10)),
        ("s_street_name", V(60)), ("s_street_type", V(15)),
        ("s_suite_number", V(10)), ("s_city", V(60)), ("s_county", V(30)),
        ("s_state", V(2)), ("s_zip", V(10)), ("s_country", V(20)),
        ("s_gmt_offset", D52), ("s_tax_precentage", D52)],
    "warehouse": [
        ("w_warehouse_sk", T.BIGINT), ("w_warehouse_id", V(16)),
        ("w_warehouse_name", V(20)), ("w_warehouse_sq_ft", T.BIGINT),
        ("w_street_number", V(10)), ("w_street_name", V(60)),
        ("w_street_type", V(15)), ("w_suite_number", V(10)),
        ("w_city", V(60)), ("w_county", V(30)), ("w_state", V(2)),
        ("w_zip", V(10)), ("w_country", V(20)), ("w_gmt_offset", D52)],
    "inventory": [
        ("inv_date_sk", T.BIGINT), ("inv_item_sk", T.BIGINT),
        ("inv_warehouse_sk", T.BIGINT),
        ("inv_quantity_on_hand", T.BIGINT)],
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT), ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT), ("ss_customer_sk", T.BIGINT),
        ("ss_cdemo_sk", T.BIGINT), ("ss_hdemo_sk", T.BIGINT),
        ("ss_addr_sk", T.BIGINT), ("ss_store_sk", T.BIGINT),
        ("ss_promo_sk", T.BIGINT), ("ss_ticket_number", T.BIGINT),
        ("ss_quantity", T.BIGINT), ("ss_wholesale_cost", D72),
        ("ss_list_price", D72), ("ss_sales_price", D72),
        ("ss_ext_discount_amt", D72), ("ss_ext_sales_price", D72),
        ("ss_ext_wholesale_cost", D72), ("ss_ext_list_price", D72),
        ("ss_ext_tax", D72), ("ss_coupon_amt", D72), ("ss_net_paid", D72),
        ("ss_net_paid_inc_tax", D72), ("ss_net_profit", D72)],
    "store_returns": [
        ("sr_returned_date_sk", T.BIGINT), ("sr_return_time_sk", T.BIGINT),
        ("sr_item_sk", T.BIGINT), ("sr_customer_sk", T.BIGINT),
        ("sr_cdemo_sk", T.BIGINT), ("sr_hdemo_sk", T.BIGINT),
        ("sr_addr_sk", T.BIGINT), ("sr_store_sk", T.BIGINT),
        ("sr_reason_sk", T.BIGINT), ("sr_ticket_number", T.BIGINT),
        ("sr_return_quantity", T.BIGINT), ("sr_return_amt", D72),
        ("sr_return_tax", D72), ("sr_return_amt_inc_tax", D72),
        ("sr_fee", D72), ("sr_return_ship_cost", D72),
        ("sr_refunded_cash", D72), ("sr_reversed_charge", D72),
        ("sr_store_credit", D72), ("sr_net_loss", D72)],
    "catalog_sales": [
        ("cs_sold_date_sk", T.BIGINT), ("cs_sold_time_sk", T.BIGINT),
        ("cs_ship_date_sk", T.BIGINT), ("cs_bill_customer_sk", T.BIGINT),
        ("cs_bill_cdemo_sk", T.BIGINT), ("cs_bill_hdemo_sk", T.BIGINT),
        ("cs_bill_addr_sk", T.BIGINT), ("cs_ship_customer_sk", T.BIGINT),
        ("cs_ship_cdemo_sk", T.BIGINT), ("cs_ship_hdemo_sk", T.BIGINT),
        ("cs_ship_addr_sk", T.BIGINT), ("cs_call_center_sk", T.BIGINT),
        ("cs_catalog_page_sk", T.BIGINT), ("cs_ship_mode_sk", T.BIGINT),
        ("cs_warehouse_sk", T.BIGINT), ("cs_item_sk", T.BIGINT),
        ("cs_promo_sk", T.BIGINT), ("cs_order_number", T.BIGINT),
        ("cs_quantity", T.BIGINT), ("cs_wholesale_cost", D72),
        ("cs_list_price", D72), ("cs_sales_price", D72),
        ("cs_ext_discount_amt", D72), ("cs_ext_sales_price", D72),
        ("cs_ext_wholesale_cost", D72), ("cs_ext_list_price", D72),
        ("cs_ext_tax", D72), ("cs_coupon_amt", D72),
        ("cs_ext_ship_cost", D72), ("cs_net_paid", D72),
        ("cs_net_paid_inc_tax", D72), ("cs_net_paid_inc_ship", D72),
        ("cs_net_paid_inc_ship_tax", D72), ("cs_net_profit", D72)],
    "time_dim": [
        ("t_time_sk", T.BIGINT), ("t_time_id", V(16)),
        ("t_time", T.BIGINT), ("t_hour", T.BIGINT),
        ("t_minute", T.BIGINT), ("t_second", T.BIGINT),
        ("t_am_pm", V(2)), ("t_shift", V(20)), ("t_sub_shift", V(20)),
        ("t_meal_time", V(20))],
    "reason": [
        ("r_reason_sk", T.BIGINT), ("r_reason_id", V(16)),
        ("r_reason_desc", V(100))],
    "ship_mode": [
        ("sm_ship_mode_sk", T.BIGINT), ("sm_ship_mode_id", V(16)),
        ("sm_type", V(30)), ("sm_code", V(10)), ("sm_carrier", V(20)),
        ("sm_contract", V(20))],
    "call_center": [
        ("cc_call_center_sk", T.BIGINT), ("cc_call_center_id", V(16)),
        ("cc_rec_start_date", T.DATE), ("cc_rec_end_date", T.DATE),
        ("cc_closed_date_sk", T.BIGINT), ("cc_open_date_sk", T.BIGINT),
        ("cc_name", V(50)), ("cc_class", V(50)),
        ("cc_employees", T.BIGINT), ("cc_sq_ft", T.BIGINT),
        ("cc_hours", V(20)), ("cc_manager", V(40)),
        ("cc_mkt_id", T.BIGINT), ("cc_mkt_class", V(50)),
        ("cc_mkt_desc", V(100)), ("cc_market_manager", V(40)),
        ("cc_division", T.BIGINT), ("cc_division_name", V(50)),
        ("cc_company", T.BIGINT), ("cc_company_name", V(50)),
        ("cc_street_number", V(10)), ("cc_street_name", V(60)),
        ("cc_street_type", V(15)), ("cc_suite_number", V(10)),
        ("cc_city", V(60)), ("cc_county", V(30)), ("cc_state", V(2)),
        ("cc_zip", V(10)), ("cc_country", V(20)),
        ("cc_gmt_offset", D52), ("cc_tax_percentage", D52)],
    "catalog_page": [
        ("cp_catalog_page_sk", T.BIGINT), ("cp_catalog_page_id", V(16)),
        ("cp_start_date_sk", T.BIGINT), ("cp_end_date_sk", T.BIGINT),
        ("cp_department", V(50)), ("cp_catalog_number", T.BIGINT),
        ("cp_catalog_page_number", T.BIGINT), ("cp_description", V(100)),
        ("cp_type", V(100))],
    "web_site": [
        ("web_site_sk", T.BIGINT), ("web_site_id", V(16)),
        ("web_rec_start_date", T.DATE), ("web_rec_end_date", T.DATE),
        ("web_name", V(50)), ("web_open_date_sk", T.BIGINT),
        ("web_close_date_sk", T.BIGINT), ("web_class", V(50)),
        ("web_manager", V(40)), ("web_mkt_id", T.BIGINT),
        ("web_mkt_class", V(50)), ("web_mkt_desc", V(100)),
        ("web_market_manager", V(40)), ("web_company_id", T.BIGINT),
        ("web_company_name", V(50)), ("web_street_number", V(10)),
        ("web_street_name", V(60)), ("web_street_type", V(15)),
        ("web_suite_number", V(10)), ("web_city", V(60)),
        ("web_county", V(30)), ("web_state", V(2)), ("web_zip", V(10)),
        ("web_country", V(20)), ("web_gmt_offset", D52),
        ("web_tax_percentage", D52)],
    "web_page": [
        ("wp_web_page_sk", T.BIGINT), ("wp_web_page_id", V(16)),
        ("wp_rec_start_date", T.DATE), ("wp_rec_end_date", T.DATE),
        ("wp_creation_date_sk", T.BIGINT), ("wp_access_date_sk", T.BIGINT),
        ("wp_autogen_flag", V(1)), ("wp_customer_sk", T.BIGINT),
        ("wp_url", V(100)), ("wp_type", V(50)),
        ("wp_char_count", T.BIGINT), ("wp_link_count", T.BIGINT),
        ("wp_image_count", T.BIGINT), ("wp_max_ad_count", T.BIGINT)],
    "web_sales": [
        ("ws_sold_date_sk", T.BIGINT), ("ws_sold_time_sk", T.BIGINT),
        ("ws_ship_date_sk", T.BIGINT), ("ws_item_sk", T.BIGINT),
        ("ws_bill_customer_sk", T.BIGINT), ("ws_bill_cdemo_sk", T.BIGINT),
        ("ws_bill_hdemo_sk", T.BIGINT), ("ws_bill_addr_sk", T.BIGINT),
        ("ws_ship_customer_sk", T.BIGINT), ("ws_ship_cdemo_sk", T.BIGINT),
        ("ws_ship_hdemo_sk", T.BIGINT), ("ws_ship_addr_sk", T.BIGINT),
        ("ws_web_page_sk", T.BIGINT), ("ws_web_site_sk", T.BIGINT),
        ("ws_ship_mode_sk", T.BIGINT), ("ws_warehouse_sk", T.BIGINT),
        ("ws_promo_sk", T.BIGINT), ("ws_order_number", T.BIGINT),
        ("ws_quantity", T.BIGINT), ("ws_wholesale_cost", D72),
        ("ws_list_price", D72), ("ws_sales_price", D72),
        ("ws_ext_discount_amt", D72), ("ws_ext_sales_price", D72),
        ("ws_ext_wholesale_cost", D72), ("ws_ext_list_price", D72),
        ("ws_ext_tax", D72), ("ws_coupon_amt", D72),
        ("ws_ext_ship_cost", D72), ("ws_net_paid", D72),
        ("ws_net_paid_inc_tax", D72), ("ws_net_paid_inc_ship", D72),
        ("ws_net_paid_inc_ship_tax", D72), ("ws_net_profit", D72)],
    "web_returns": [
        ("wr_returned_date_sk", T.BIGINT),
        ("wr_returned_time_sk", T.BIGINT), ("wr_item_sk", T.BIGINT),
        ("wr_refunded_customer_sk", T.BIGINT),
        ("wr_refunded_cdemo_sk", T.BIGINT),
        ("wr_refunded_hdemo_sk", T.BIGINT),
        ("wr_refunded_addr_sk", T.BIGINT),
        ("wr_returning_customer_sk", T.BIGINT),
        ("wr_returning_cdemo_sk", T.BIGINT),
        ("wr_returning_hdemo_sk", T.BIGINT),
        ("wr_returning_addr_sk", T.BIGINT),
        ("wr_web_page_sk", T.BIGINT), ("wr_reason_sk", T.BIGINT),
        ("wr_order_number", T.BIGINT), ("wr_return_quantity", T.BIGINT),
        ("wr_return_amt", D72), ("wr_return_tax", D72),
        ("wr_return_amt_inc_tax", D72), ("wr_fee", D72),
        ("wr_return_ship_cost", D72), ("wr_refunded_cash", D72),
        ("wr_reversed_charge", D72), ("wr_account_credit", D72),
        ("wr_net_loss", D72)],
    "catalog_returns": [
        ("cr_returned_date_sk", T.BIGINT),
        ("cr_returned_time_sk", T.BIGINT), ("cr_item_sk", T.BIGINT),
        ("cr_refunded_customer_sk", T.BIGINT),
        ("cr_refunded_cdemo_sk", T.BIGINT),
        ("cr_refunded_hdemo_sk", T.BIGINT),
        ("cr_refunded_addr_sk", T.BIGINT),
        ("cr_returning_customer_sk", T.BIGINT),
        ("cr_returning_cdemo_sk", T.BIGINT),
        ("cr_returning_hdemo_sk", T.BIGINT),
        ("cr_returning_addr_sk", T.BIGINT),
        ("cr_call_center_sk", T.BIGINT),
        ("cr_catalog_page_sk", T.BIGINT), ("cr_ship_mode_sk", T.BIGINT),
        ("cr_warehouse_sk", T.BIGINT), ("cr_reason_sk", T.BIGINT),
        ("cr_order_number", T.BIGINT), ("cr_return_quantity", T.BIGINT),
        ("cr_return_amount", D72), ("cr_return_tax", D72),
        ("cr_return_amt_inc_tax", D72), ("cr_fee", D72),
        ("cr_return_ship_cost", D72), ("cr_refunded_cash", D72),
        ("cr_reversed_charge", D72), ("cr_store_credit", D72),
        ("cr_net_loss", D72)],
}


def _pick(rows, tag, values):
    """(codes, pool) fast path for a word-list column."""
    return (hmod(rows, tag, len(values)), values)


def _yn(rows, tag, yes_pct=50):
    return (np.where(hmod(rows, tag, 100) < yes_pct, 0, 1), ["Y", "N"])


def _words(rows, tag, n=2):
    picks = [hmod(rows, f"{tag}.{i}", len(_TEXT_WORDS)) for i in range(n)]
    w = np.asarray(_TEXT_WORDS, dtype=object)
    cols = [w[p] for p in picks]
    return [" ".join(c[i] for c in cols) for i in range(len(rows))]


def _civil(days: np.ndarray):
    d64 = (np.asarray(days, dtype="int64")).astype("M8[D]")
    y = d64.astype("M8[Y]").astype(np.int64) + 1970
    m = (d64.astype("M8[M]") - d64.astype("M8[Y]")).astype(np.int64) + 1
    dom = (d64 - d64.astype("M8[M]")).astype(np.int64) + 1
    return y, m, dom


def _week_seq(days: np.ndarray) -> np.ndarray:
    # 1998-01-01 is a Thursday; align week boundaries to Monday
    return (days - _DS_START + 3) // 7 + _WEEK_SEQ0


class _DsTable:
    def __init__(self, conn: "TpcdsConnector", name: str):
        self.conn = conn
        self.name = name
        self.columns = _TABLE_COLUMNS[name]
        self.dicts: Dict[str, Dictionary] = {}
        for cname, ctype in self.columns:
            if ctype.is_string:
                self.dicts[cname] = Dictionary()

    def row_count(self, sf: float) -> int:
        return _counts(sf)[self.name]

    def generate(self, sf: float, start: int, end: int,
                 columns: Sequence[str]) -> Page:
        rows = np.arange(start, end, dtype=np.int64)
        gen = getattr(self, f"_gen_{self.name}")
        data = gen(sf, rows, set(columns))
        blocks = []
        for cname in columns:
            ctype = dict(self.columns)[cname]
            vals = data[cname]
            nulls = None
            if isinstance(vals, tuple) and len(vals) == 2 \
                    and isinstance(vals[1], np.ndarray) \
                    and vals[1].dtype == bool:
                vals, nulls = vals  # (values, null_mask)
            if ctype.is_string:
                d = self.dicts[cname]
                if isinstance(vals, tuple):
                    codes_in, pool = vals
                    remap = d.encode(pool)
                    codes = remap[np.asarray(codes_in, dtype=np.int64)]
                else:
                    codes = d.encode(vals)
                blocks.append(Block(ctype, codes.astype(np.int32), nulls, d))
            else:
                blocks.append(Block(
                    ctype, np.asarray(vals, dtype=ctype.storage), nulls))
        n = len(blocks[0]) if blocks else end - start
        return Page(blocks, n)

    # -- dimensions ----------------------------------------------------

    def _gen_date_dim(self, sf, rows, cols):
        days = _DS_START + rows
        y, m, dom = _civil(days)
        dow = (days + 3) % 7  # Mon=0 .. Sun=6
        q = (m - 1) // 3 + 1
        out = {}
        out["d_date_sk"] = _SK0 + rows
        out["d_date_id"] = [f"AAAAAAAA{_SK0 + r:08d}" for r in rows]
        out["d_date"] = days.astype(np.int32)
        out["d_month_seq"] = (y - 1998) * 12 + m - 1 + 1176
        out["d_week_seq"] = _week_seq(days)
        out["d_quarter_seq"] = (y - 1998) * 4 + q - 1 + 392
        out["d_year"] = y
        out["d_dow"] = dow
        out["d_moy"] = m
        out["d_dom"] = dom
        out["d_qoy"] = q
        out["d_fy_year"] = y
        out["d_fy_quarter_seq"] = out["d_quarter_seq"]
        out["d_fy_week_seq"] = out["d_week_seq"]
        out["d_day_name"] = (dow, DAY_NAMES)
        out["d_quarter_name"] = [f"{yy}Q{qq}" for yy, qq in zip(y, q)]
        out["d_holiday"] = (np.where((m == 12) & (dom == 25), 0, 1),
                            ["Y", "N"])
        out["d_weekend"] = (np.where(dow >= 5, 0, 1), ["Y", "N"])
        out["d_following_holiday"] = (np.where((m == 12) & (dom == 26),
                                               0, 1), ["Y", "N"])
        first = days - (dom - 1)
        out["d_first_dom"] = _SK0 + (first - _DS_START)
        out["d_last_dom"] = out["d_first_dom"] + 27
        out["d_same_day_ly"] = _SK0 + rows - 365
        out["d_same_day_lq"] = _SK0 + rows - 91
        n = ["N"] * len(rows)
        for c in ("d_current_day", "d_current_week", "d_current_month",
                  "d_current_quarter", "d_current_year"):
            out[c] = list(n)
        return out

    def _gen_income_band(self, sf, rows, cols):
        k = rows + 1
        return {"ib_income_band_sk": k,
                "ib_lower_bound": (k - 1) * 10_000,
                "ib_upper_bound": k * 10_000}

    def _gen_item(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["i_item_sk"] = k
        out["i_item_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        start = _DS_START + hmod(rows, "i.rec", 365)
        out["i_rec_start_date"] = start.astype(np.int32)
        end_null = hmod(rows, "i.recend.null", 2) == 0
        out["i_rec_end_date"] = ((start + 730).astype(np.int32), end_null)
        out["i_item_desc"] = _comment(rows, "i.desc", 12)
        # price biased to [55, 85): keeps q64's BETWEEN window populated
        price = 5_500 + hmod(rows, "i.price", 3_000)  # cents
        out["i_current_price"] = price
        out["i_wholesale_cost"] = (price * 6) // 10
        brand = hmod(rows, "i.brand", 10) + 1
        cat = hmod(rows, "i.cat", len(CATEGORIES))
        cls = hmod(rows, "i.class", len(CLASSES))
        out["i_brand_id"] = brand * 1_001
        out["i_brand"] = [f"brand#{b}" for b in brand]
        out["i_class_id"] = cls + 1
        out["i_class"] = (cls, CLASSES)
        out["i_category_id"] = cat + 1
        out["i_category"] = (cat, CATEGORIES)
        man = hmod(rows, "i.man", 100) + 1
        out["i_manufact_id"] = man
        out["i_manufact"] = [f"manufact#{v}" for v in man]
        out["i_size"] = _pick(rows, "i.size", SIZES)
        out["i_formulation"] = [f"{v:014d}" for v in h64(rows, "i.form")
                                % np.uint64(10 ** 14)]
        # a third of items wear a q64 filter color, the rest uniform
        biased = hmod(rows, "i.colorbias", 3) == 0
        cq = hmod(rows, "i.colorq", len(Q64_COLORS))
        cu = hmod(rows, "i.coloru", len(COLORS))
        qidx = np.asarray([COLORS.index(c) for c in Q64_COLORS])
        out["i_color"] = (np.where(biased, qidx[cq], cu), COLORS)
        out["i_units"] = _pick(rows, "i.units", UNITS)
        out["i_container"] = _pick(rows, "i.cont", CONTAINERS)
        out["i_manager_id"] = hmod(rows, "i.mgr", 100) + 1
        out["i_product_name"] = _words(rows, "i.pname", 3)
        return out

    def _gen_customer_demographics(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["cd_demo_sk"] = k
        out["cd_gender"] = _pick(rows, "cd.gender", GENDER)
        out["cd_marital_status"] = _pick(rows, "cd.marital", MARITAL)
        out["cd_education_status"] = _pick(rows, "cd.edu", EDUCATION)
        out["cd_purchase_estimate"] = (hmod(rows, "cd.purch", 12) + 1) * 500
        out["cd_credit_rating"] = _pick(rows, "cd.credit", CREDIT_RATING)
        out["cd_dep_count"] = hmod(rows, "cd.dep", 7)
        out["cd_dep_employed_count"] = hmod(rows, "cd.depe", 7)
        out["cd_dep_college_count"] = hmod(rows, "cd.depc", 7)
        return out

    def _gen_household_demographics(self, sf, rows, cols):
        out = {}
        out["hd_demo_sk"] = rows + 1
        out["hd_income_band_sk"] = hmod(rows, "hd.ib", 20) + 1
        out["hd_buy_potential"] = _pick(rows, "hd.buy", BUY_POTENTIAL)
        out["hd_dep_count"] = hmod(rows, "hd.dep", 10)
        out["hd_vehicle_count"] = hmod(rows, "hd.veh", 5)
        return out

    def _gen_customer_address(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["ca_address_sk"] = k
        out["ca_address_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["ca_street_number"] = [str(v) for v in
                                   hmod(rows, "ca.stno", 999) + 1]
        out["ca_street_name"] = _words(rows, "ca.stname", 2)
        out["ca_street_type"] = _pick(rows, "ca.sttype", STREET_TYPES)
        out["ca_suite_number"] = [f"Suite {v}" for v in
                                  hmod(rows, "ca.suite", 99)]
        out["ca_city"] = _words(rows, "ca.city", 1)
        out["ca_county"] = _words(rows, "ca.county", 2)
        out["ca_state"] = _pick(rows, "ca.state", STATES)
        out["ca_zip"] = [f"{v:05d}" for v in hmod(rows, "ca.zip", 99_999)]
        out["ca_country"] = ["United States"] * len(rows)
        out["ca_gmt_offset"] = -(hmod(rows, "ca.gmt", 4) + 5) * 100
        out["ca_location_type"] = _pick(rows, "ca.loc", LOCATION_TYPES)
        return out

    def _gen_customer(self, sf, rows, cols):
        c = _counts(sf)
        k = rows + 1
        out = {}
        out["c_customer_sk"] = k
        out["c_customer_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["c_current_cdemo_sk"] = hmod(
            rows, "c.cdemo", c["customer_demographics"]) + 1
        out["c_current_hdemo_sk"] = hmod(
            rows, "c.hdemo", c["household_demographics"]) + 1
        out["c_current_addr_sk"] = hmod(
            rows, "c.addr", c["customer_address"]) + 1
        out["c_first_shipto_date_sk"] = _SK0 + hmod(rows, "c.shipto",
                                                    _DS_DAYS)
        out["c_first_sales_date_sk"] = _SK0 + hmod(rows, "c.firstsale",
                                                   _DS_DAYS)
        out["c_salutation"] = _pick(rows, "c.salut", SALUTATIONS)
        out["c_first_name"] = _words(rows, "c.fname", 1)
        out["c_last_name"] = _words(rows, "c.lname", 1)
        out["c_preferred_cust_flag"] = _yn(rows, "c.pref")
        out["c_birth_day"] = hmod(rows, "c.bday", 28) + 1
        out["c_birth_month"] = hmod(rows, "c.bmon", 12) + 1
        out["c_birth_year"] = 1930 + hmod(rows, "c.byear", 63)
        out["c_birth_country"] = _words(rows, "c.bcountry", 1)
        out["c_login"] = [f"user{v}" for v in k]
        out["c_email_address"] = [f"user{v}@example.com" for v in k]
        out["c_last_review_date_sk"] = _SK0 + hmod(rows, "c.review",
                                                   _DS_DAYS)
        return out

    def _gen_promotion(self, sf, rows, cols):
        c = _counts(sf)
        k = rows + 1
        start = hmod(rows, "p.start", _DS_DAYS - 120)
        out = {}
        out["p_promo_sk"] = k
        out["p_promo_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["p_start_date_sk"] = _SK0 + start
        out["p_end_date_sk"] = _SK0 + start + 30 + hmod(rows, "p.len", 90)
        out["p_item_sk"] = hmod(rows, "p.item", c["item"]) + 1
        out["p_cost"] = (hmod(rows, "p.cost", 900) + 100) * 100
        out["p_response_target"] = np.ones(len(rows), dtype=np.int64)
        out["p_promo_name"] = _words(rows, "p.name", 2)
        for ch in ("dmail", "email", "catalog", "tv", "radio", "press",
                   "event", "demo"):
            out[f"p_channel_{ch}"] = _yn(rows, f"p.ch.{ch}")
        out["p_channel_details"] = _comment(rows, "p.details", 8)
        out["p_purpose"] = ["Unknown"] * len(rows)
        out["p_discount_active"] = _yn(rows, "p.disc", 30)
        return out

    def _gen_store(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["s_store_sk"] = k
        out["s_store_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["s_rec_start_date"] = np.full(len(rows), _DS_START,
                                          dtype=np.int32)
        end_null = np.ones(len(rows), dtype=bool)
        out["s_rec_end_date"] = (np.zeros(len(rows), dtype=np.int32),
                                 end_null)
        out["s_closed_date_sk"] = (np.zeros(len(rows), dtype=np.int64),
                                   np.ones(len(rows), dtype=bool))
        out["s_store_name"] = _words(rows, "s.name", 1)
        out["s_number_employees"] = 200 + hmod(rows, "s.emp", 100)
        out["s_floor_space"] = 5_000_000 + hmod(rows, "s.floor", 5_000_000)
        out["s_hours"] = _pick(rows, "s.hours", HOURS)
        out["s_manager"] = _words(rows, "s.mgr", 2)
        out["s_market_id"] = hmod(rows, "s.mktid", 10) + 1
        out["s_geography_class"] = ["Unknown"] * len(rows)
        out["s_market_desc"] = _comment(rows, "s.mktdesc", 8)
        out["s_market_manager"] = _words(rows, "s.mktmgr", 2)
        out["s_division_id"] = np.ones(len(rows), dtype=np.int64)
        out["s_division_name"] = ["Unknown"] * len(rows)
        out["s_company_id"] = np.ones(len(rows), dtype=np.int64)
        out["s_company_name"] = ["Unknown"] * len(rows)
        out["s_street_number"] = [str(v) for v in
                                  hmod(rows, "s.stno", 999) + 1]
        out["s_street_name"] = _words(rows, "s.stname", 2)
        out["s_street_type"] = _pick(rows, "s.sttype", STREET_TYPES)
        out["s_suite_number"] = [f"Suite {v}" for v in
                                 hmod(rows, "s.suite", 99)]
        out["s_city"] = _words(rows, "s.city", 1)
        out["s_county"] = _words(rows, "s.county", 2)
        out["s_state"] = _pick(rows, "s.state", STATES)
        out["s_zip"] = [f"{v:05d}" for v in hmod(rows, "s.zip", 99_999)]
        out["s_country"] = ["United States"] * len(rows)
        out["s_gmt_offset"] = -(hmod(rows, "s.gmt", 4) + 5) * 100
        out["s_tax_precentage"] = hmod(rows, "s.tax", 12)
        return out

    def _gen_warehouse(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["w_warehouse_sk"] = k
        out["w_warehouse_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["w_warehouse_name"] = _words(rows, "w.name", 2)
        out["w_warehouse_sq_ft"] = 50_000 + hmod(rows, "w.sqft", 950_000)
        out["w_street_number"] = [str(v) for v in
                                  hmod(rows, "w.stno", 999) + 1]
        out["w_street_name"] = _words(rows, "w.stname", 2)
        out["w_street_type"] = _pick(rows, "w.sttype", STREET_TYPES)
        out["w_suite_number"] = [f"Suite {v}" for v in
                                 hmod(rows, "w.suite", 99)]
        out["w_city"] = _words(rows, "w.city", 1)
        out["w_county"] = _words(rows, "w.county", 2)
        out["w_state"] = _pick(rows, "w.state", STATES)
        out["w_zip"] = [f"{v:05d}" for v in hmod(rows, "w.zip", 99_999)]
        out["w_country"] = ["United States"] * len(rows)
        out["w_gmt_offset"] = -(hmod(rows, "w.gmt", 4) + 5) * 100
        return out

    def _gen_time_dim(self, sf, rows, cols):
        sec = rows  # one row per second of day
        h = sec // 3600
        out = {}
        out["t_time_sk"] = sec
        out["t_time_id"] = [f"AAAAAAAA{v:08d}" for v in sec]
        out["t_time"] = sec
        out["t_hour"] = h
        out["t_minute"] = (sec // 60) % 60
        out["t_second"] = sec % 60
        out["t_am_pm"] = (np.where(h < 12, 0, 1), ["AM", "PM"])
        out["t_shift"] = (np.where(h < 8, 0, np.where(h < 16, 1, 2)),
                          ["third", "first", "second"])
        out["t_sub_shift"] = (np.where(h < 6, 0, np.where(
            h < 12, 1, np.where(h < 18, 2, 3))),
            ["night", "morning", "afternoon", "evening"])
        out["t_meal_time"] = ((np.where(
            (h >= 6) & (h < 9), 1, np.where(
                (h >= 11) & (h < 14), 2, np.where(
                    (h >= 17) & (h < 20), 3, 0)))),
            ["", "breakfast", "lunch", "dinner"])
        return out

    def _gen_reason(self, sf, rows, cols):
        k = rows + 1
        return {"r_reason_sk": k,
                "r_reason_id": [f"AAAAAAAA{v:08d}" for v in k],
                "r_reason_desc": _words(rows, "r.desc", 3)}

    def _gen_ship_mode(self, sf, rows, cols):
        k = rows + 1
        types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"]
        carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS",
                    "ZHOU", "ZOUROS", "MSC", "LATVIAN"]
        out = {}
        out["sm_ship_mode_sk"] = k
        out["sm_ship_mode_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["sm_type"] = (rows % len(types), types)
        out["sm_code"] = (rows % 4, ["AIR", "SURFACE", "SEA", "RAIL"])
        out["sm_carrier"] = (rows % len(carriers), carriers)
        out["sm_contract"] = [f"{v:015d}" for v in
                              h64(rows, "sm.contract")
                              % np.uint64(10 ** 15)]
        return out

    def _gen_call_center(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["cc_call_center_sk"] = k
        out["cc_call_center_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["cc_rec_start_date"] = np.full(len(rows), _DS_START,
                                           dtype=np.int32)
        out["cc_rec_end_date"] = (np.zeros(len(rows), dtype=np.int32),
                                  np.ones(len(rows), dtype=bool))
        out["cc_closed_date_sk"] = (np.zeros(len(rows), dtype=np.int64),
                                    np.ones(len(rows), dtype=bool))
        out["cc_open_date_sk"] = _SK0 + hmod(rows, "cc.open", 365)
        out["cc_name"] = [f"call center {v}" for v in k]
        out["cc_class"] = (hmod(rows, "cc.class", 3),
                           ["small", "medium", "large"])
        out["cc_employees"] = 100 + hmod(rows, "cc.emp", 600)
        out["cc_sq_ft"] = 10_000 + hmod(rows, "cc.sqft", 90_000)
        out["cc_hours"] = _pick(rows, "cc.hours", HOURS)
        out["cc_manager"] = _words(rows, "cc.mgr", 2)
        out["cc_mkt_id"] = hmod(rows, "cc.mktid", 6) + 1
        out["cc_mkt_class"] = _comment(rows, "cc.mktclass", 4)
        out["cc_mkt_desc"] = _comment(rows, "cc.mktdesc", 8)
        out["cc_market_manager"] = _words(rows, "cc.mktmgr", 2)
        out["cc_division"] = hmod(rows, "cc.div", 6) + 1
        out["cc_division_name"] = _words(rows, "cc.divname", 1)
        out["cc_company"] = hmod(rows, "cc.co", 6) + 1
        out["cc_company_name"] = _words(rows, "cc.coname", 1)
        out["cc_street_number"] = [str(v) for v in
                                   hmod(rows, "cc.stno", 999) + 1]
        out["cc_street_name"] = _words(rows, "cc.stname", 2)
        out["cc_street_type"] = _pick(rows, "cc.sttype", STREET_TYPES)
        out["cc_suite_number"] = [f"Suite {v}" for v in
                                  hmod(rows, "cc.suite", 99)]
        out["cc_city"] = _words(rows, "cc.city", 1)
        out["cc_county"] = _words(rows, "cc.county", 2)
        out["cc_state"] = _pick(rows, "cc.state", STATES)
        out["cc_zip"] = [f"{v:05d}" for v in hmod(rows, "cc.zip", 99_999)]
        out["cc_country"] = ["United States"] * len(rows)
        out["cc_gmt_offset"] = -(hmod(rows, "cc.gmt", 4) + 5) * 100
        out["cc_tax_percentage"] = hmod(rows, "cc.tax", 12)
        return out

    def _gen_catalog_page(self, sf, rows, cols):
        k = rows + 1
        start = hmod(rows, "cp.start", _DS_DAYS - 90)
        out = {}
        out["cp_catalog_page_sk"] = k
        out["cp_catalog_page_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["cp_start_date_sk"] = _SK0 + start
        out["cp_end_date_sk"] = _SK0 + start + 30 + hmod(rows, "cp.len",
                                                         60)
        out["cp_department"] = ["DEPARTMENT"] * len(rows)
        out["cp_catalog_number"] = rows // 100 + 1
        out["cp_catalog_page_number"] = rows % 100 + 1
        out["cp_description"] = _comment(rows, "cp.desc", 8)
        out["cp_type"] = (hmod(rows, "cp.type", 3),
                          ["bi-annual", "quarterly", "monthly"])
        return out

    def _gen_web_site(self, sf, rows, cols):
        k = rows + 1
        out = {}
        out["web_site_sk"] = k
        out["web_site_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["web_rec_start_date"] = np.full(len(rows), _DS_START,
                                            dtype=np.int32)
        out["web_rec_end_date"] = (np.zeros(len(rows), dtype=np.int32),
                                   np.ones(len(rows), dtype=bool))
        out["web_name"] = [f"site_{v}" for v in rows % 15]
        out["web_open_date_sk"] = _SK0 + hmod(rows, "web.open", 365)
        out["web_close_date_sk"] = (np.zeros(len(rows), dtype=np.int64),
                                    np.ones(len(rows), dtype=bool))
        out["web_class"] = ["Unknown"] * len(rows)
        out["web_manager"] = _words(rows, "web.mgr", 2)
        out["web_mkt_id"] = hmod(rows, "web.mktid", 6) + 1
        out["web_mkt_class"] = _comment(rows, "web.mktclass", 4)
        out["web_mkt_desc"] = _comment(rows, "web.mktdesc", 8)
        out["web_market_manager"] = _words(rows, "web.mktmgr", 2)
        out["web_company_id"] = hmod(rows, "web.co", 6) + 1
        out["web_company_name"] = (hmod(rows, "web.coname", 6),
                                   ["pri", "able", "ought", "bar",
                                    "cally", "ation"])
        out["web_street_number"] = [str(v) for v in
                                    hmod(rows, "web.stno", 999) + 1]
        out["web_street_name"] = _words(rows, "web.stname", 2)
        out["web_street_type"] = _pick(rows, "web.sttype", STREET_TYPES)
        out["web_suite_number"] = [f"Suite {v}" for v in
                                   hmod(rows, "web.suite", 99)]
        out["web_city"] = _words(rows, "web.city", 1)
        out["web_county"] = _words(rows, "web.county", 2)
        out["web_state"] = _pick(rows, "web.state", STATES)
        out["web_zip"] = [f"{v:05d}" for v in
                          hmod(rows, "web.zip", 99_999)]
        out["web_country"] = ["United States"] * len(rows)
        out["web_gmt_offset"] = -(hmod(rows, "web.gmt", 4) + 5) * 100
        out["web_tax_percentage"] = hmod(rows, "web.tax", 12)
        return out

    def _gen_web_page(self, sf, rows, cols):
        c = _counts(sf)
        k = rows + 1
        out = {}
        out["wp_web_page_sk"] = k
        out["wp_web_page_id"] = [f"AAAAAAAA{v:08d}" for v in k]
        out["wp_rec_start_date"] = np.full(len(rows), _DS_START,
                                           dtype=np.int32)
        out["wp_rec_end_date"] = (np.zeros(len(rows), dtype=np.int32),
                                  np.ones(len(rows), dtype=bool))
        out["wp_creation_date_sk"] = _SK0 + hmod(rows, "wp.create", 365)
        out["wp_access_date_sk"] = _SK0 + 365 + hmod(rows, "wp.access",
                                                     365)
        out["wp_autogen_flag"] = _yn(rows, "wp.autogen")
        out["wp_customer_sk"] = hmod(rows, "wp.cust",
                                     c["customer"]) + 1
        out["wp_url"] = ["http://www.foo.com"] * len(rows)
        out["wp_type"] = (hmod(rows, "wp.type", 7),
                          ["ad", "bio", "dynamic", "feedback",
                           "general", "order", "welcome"])
        out["wp_char_count"] = 100 + hmod(rows, "wp.chars", 8_000)
        out["wp_link_count"] = 2 + hmod(rows, "wp.links", 23)
        out["wp_image_count"] = 1 + hmod(rows, "wp.imgs", 6)
        out["wp_max_ad_count"] = hmod(rows, "wp.ads", 5)
        return out

    def _ws_values(self, sf, rows):
        """web_sales column streams (shared with web_returns)."""
        c = _counts(sf)
        ni = _inv_items(sf)
        out = {}
        sold = hmod(rows, "ws.sold", _SOLD_DAYS)
        out["ws_sold_date_sk"] = _SK0 + sold
        out["ws_sold_time_sk"] = hmod(rows, "ws.time", 86_400)
        ship = np.minimum(sold + 2 + hmod(rows, "ws.shiplag", 58),
                          _DS_DAYS - 1)
        out["ws_ship_date_sk"] = _SK0 + ship
        out["ws_item_sk"] = np.where(
            hmod(rows, "ws.itempick", 4) < 3,
            hmod(rows, "ws.itemA", ni) + 1,
            hmod(rows, "ws.itemB", c["item"]) + 1)
        cust = hmod(rows, "ws.cust", c["customer"]) + 1
        out["ws_bill_customer_sk"] = cust
        out["ws_bill_cdemo_sk"] = hmod(rows, "ws.cdemo",
                                       c["customer_demographics"]) + 1
        out["ws_bill_hdemo_sk"] = hmod(rows, "ws.hdemo",
                                       c["household_demographics"]) + 1
        out["ws_bill_addr_sk"] = hmod(rows, "ws.addr",
                                      c["customer_address"]) + 1
        out["ws_ship_customer_sk"] = cust
        out["ws_ship_cdemo_sk"] = out["ws_bill_cdemo_sk"]
        out["ws_ship_hdemo_sk"] = out["ws_bill_hdemo_sk"]
        out["ws_ship_addr_sk"] = out["ws_bill_addr_sk"]
        out["ws_web_page_sk"] = hmod(rows, "ws.page",
                                     c["web_page"]) + 1
        out["ws_web_site_sk"] = hmod(rows, "ws.site",
                                     c["web_site"]) + 1
        out["ws_ship_mode_sk"] = hmod(rows, "ws.shipmode",
                                      c["ship_mode"]) + 1
        out["ws_warehouse_sk"] = hmod(rows, "ws.wh",
                                      c["warehouse"]) + 1
        promo_null = hmod(rows, "ws.promo.null", 5) == 0
        out["ws_promo_sk"] = (hmod(rows, "ws.promo",
                                   c["promotion"]) + 1, promo_null)
        out["ws_order_number"] = rows // 4 + 1
        qty = hmod(rows, "ws.qty", 100) + 1
        out["ws_quantity"] = qty
        whole = 100 + hmod(rows, "ws.whole", 9_900)
        lst = whole + (whole * (20 + hmod(rows, "ws.markup", 80))) // 100
        disc = hmod(rows, "ws.disc", 30)
        sales = (lst * (100 - disc)) // 100
        out["ws_wholesale_cost"] = whole
        out["ws_list_price"] = lst
        out["ws_sales_price"] = sales
        out["ws_ext_discount_amt"] = qty * (lst - sales)
        out["ws_ext_sales_price"] = qty * sales
        out["ws_ext_wholesale_cost"] = qty * whole
        out["ws_ext_list_price"] = qty * lst
        tax = (qty * sales * hmod(rows, "ws.tax", 9)) // 100
        out["ws_ext_tax"] = tax
        coupon = np.where(hmod(rows, "ws.coup", 10) == 0,
                          (qty * sales) // 10, 0)
        out["ws_coupon_amt"] = coupon
        shipc = qty * hmod(rows, "ws.shipc", 1_000)
        out["ws_ext_ship_cost"] = shipc
        net = qty * sales - coupon
        out["ws_net_paid"] = net
        out["ws_net_paid_inc_tax"] = net + tax
        out["ws_net_paid_inc_ship"] = net + shipc
        out["ws_net_paid_inc_ship_tax"] = net + shipc + tax
        out["ws_net_profit"] = net - qty * whole
        return out

    def _gen_web_sales(self, sf, rows, cols):
        return self._ws_values(sf, rows)

    def _gen_web_returns(self, sf, rows, cols):
        parent = rows * 3
        ws = self._ws_values(sf, parent)
        out = {}
        sold = ws["ws_sold_date_sk"] - _SK0
        ret = np.minimum(sold + 1 + hmod(rows, "wr.lag", 60),
                         _DS_DAYS - 1)
        out["wr_returned_date_sk"] = _SK0 + ret
        out["wr_returned_time_sk"] = hmod(rows, "wr.time", 86_400)
        out["wr_item_sk"] = ws["ws_item_sk"]
        out["wr_refunded_customer_sk"] = ws["ws_bill_customer_sk"]
        out["wr_refunded_cdemo_sk"] = ws["ws_bill_cdemo_sk"]
        out["wr_refunded_hdemo_sk"] = ws["ws_bill_hdemo_sk"]
        out["wr_refunded_addr_sk"] = ws["ws_bill_addr_sk"]
        out["wr_returning_customer_sk"] = ws["ws_bill_customer_sk"]
        out["wr_returning_cdemo_sk"] = ws["ws_bill_cdemo_sk"]
        out["wr_returning_hdemo_sk"] = ws["ws_bill_hdemo_sk"]
        out["wr_returning_addr_sk"] = ws["ws_bill_addr_sk"]
        out["wr_web_page_sk"] = ws["ws_web_page_sk"]
        out["wr_reason_sk"] = hmod(rows, "wr.reason", 35) + 1
        out["wr_order_number"] = ws["ws_order_number"]
        rqty = 1 + hmod(rows, "wr.qty", 100) % ws["ws_quantity"]
        out["wr_return_quantity"] = rqty
        amt = rqty * ws["ws_sales_price"]
        out["wr_return_amt"] = amt
        tax = (amt * hmod(rows, "wr.tax", 9)) // 100
        out["wr_return_tax"] = tax
        out["wr_return_amt_inc_tax"] = amt + tax
        out["wr_fee"] = hmod(rows, "wr.fee", 10_000)
        out["wr_return_ship_cost"] = hmod(rows, "wr.shipc", 5_000)
        third = amt // 3
        out["wr_refunded_cash"] = third
        out["wr_reversed_charge"] = third
        out["wr_account_credit"] = amt - 2 * third
        out["wr_net_loss"] = hmod(rows, "wr.loss", 10_000)
        return out

    # -- facts ---------------------------------------------------------

    def _gen_inventory(self, sf, rows, cols):
        c = _counts(sf)
        ni = _inv_items(sf)
        nw = c["warehouse"]
        # row -> (week, warehouse, item): every cell of the lattice, so
        # q72's inventory-by-week join always has its partner row
        week = rows // (ni * nw)
        rem = rows % (ni * nw)
        out = {}
        # Monday of that week (clamped into the covered range)
        day = np.minimum(week * 7 + 4, _DS_DAYS - 1)
        out["inv_date_sk"] = _SK0 + day
        out["inv_item_sk"] = rem % ni + 1
        out["inv_warehouse_sk"] = rem // ni + 1
        out["inv_quantity_on_hand"] = hmod(rows, "inv.qty", 101)
        return out

    def _ss_values(self, sf, rows):
        """store_sales column streams for absolute fact rows (shared with
        store_returns, which re-derives its parent sale's values)."""
        c = _counts(sf)
        ni = _inv_items(sf)
        out = {}
        # store sales concentrate in 1999-2000 (the consecutive-year
        # window q64's self-join pairs up)
        y99 = days_from_civil_host(1999, 1, 1) - _DS_START
        out["ss_sold_date_sk"] = _SK0 + y99 + hmod(rows, "ss.sold", 730)
        out["ss_sold_time_sk"] = hmod(rows, "ss.time", 86_400)
        # bias items toward the inventory-covered prefix
        out["ss_item_sk"] = np.where(
            hmod(rows, "ss.itempick", 2) == 0,
            hmod(rows, "ss.itemA", ni) + 1,
            hmod(rows, "ss.itemB", c["item"]) + 1)
        out["ss_customer_sk"] = hmod(rows, "ss.cust", c["customer"]) + 1
        out["ss_cdemo_sk"] = hmod(rows, "ss.cdemo",
                                  c["customer_demographics"]) + 1
        out["ss_hdemo_sk"] = hmod(rows, "ss.hdemo",
                                  c["household_demographics"]) + 1
        out["ss_addr_sk"] = hmod(rows, "ss.addr",
                                 c["customer_address"]) + 1
        out["ss_store_sk"] = hmod(rows, "ss.store", c["store"]) + 1
        promo_null = hmod(rows, "ss.promo.null", 5) == 0
        out["ss_promo_sk"] = (hmod(rows, "ss.promo",
                                   c["promotion"]) + 1, promo_null)
        out["ss_ticket_number"] = rows // 3 + 1
        qty = hmod(rows, "ss.qty", 100) + 1
        out["ss_quantity"] = qty
        whole = 100 + hmod(rows, "ss.whole", 9_900)       # cents
        lst = whole + (whole * (20 + hmod(rows, "ss.markup", 80))) // 100
        disc = hmod(rows, "ss.disc", 30)                   # percent
        sales = (lst * (100 - disc)) // 100
        out["ss_wholesale_cost"] = whole
        out["ss_list_price"] = lst
        out["ss_sales_price"] = sales
        out["ss_ext_discount_amt"] = qty * (lst - sales)
        out["ss_ext_sales_price"] = qty * sales
        out["ss_ext_wholesale_cost"] = qty * whole
        out["ss_ext_list_price"] = qty * lst
        tax = (qty * sales * hmod(rows, "ss.tax", 9)) // 100
        out["ss_ext_tax"] = tax
        coupon = np.where(hmod(rows, "ss.coup", 10) == 0,
                          (qty * sales) // 10, 0)
        out["ss_coupon_amt"] = coupon
        net = qty * sales - coupon
        out["ss_net_paid"] = net
        out["ss_net_paid_inc_tax"] = net + tax
        out["ss_net_profit"] = net - qty * whole
        return out

    def _gen_store_sales(self, sf, rows, cols):
        return self._ss_values(sf, rows)

    def _gen_store_returns(self, sf, rows, cols):
        parent = rows * 2  # every second sale is returned
        ss = self._ss_values(sf, parent)
        c = _counts(sf)
        out = {}
        sold = ss["ss_sold_date_sk"] - _SK0
        ret = np.minimum(sold + 1 + hmod(rows, "sr.lag", 60), _DS_DAYS - 1)
        out["sr_returned_date_sk"] = _SK0 + ret
        out["sr_return_time_sk"] = hmod(rows, "sr.time", 86_400)
        out["sr_item_sk"] = ss["ss_item_sk"]
        out["sr_customer_sk"] = ss["ss_customer_sk"]
        out["sr_cdemo_sk"] = ss["ss_cdemo_sk"]
        out["sr_hdemo_sk"] = ss["ss_hdemo_sk"]
        out["sr_addr_sk"] = ss["ss_addr_sk"]
        out["sr_store_sk"] = ss["ss_store_sk"]
        out["sr_reason_sk"] = hmod(rows, "sr.reason", 35) + 1
        out["sr_ticket_number"] = ss["ss_ticket_number"]
        rqty = 1 + hmod(rows, "sr.qty", 100) % ss["ss_quantity"]
        out["sr_return_quantity"] = rqty
        amt = rqty * ss["ss_sales_price"]
        out["sr_return_amt"] = amt
        tax = (amt * hmod(rows, "sr.tax", 9)) // 100
        out["sr_return_tax"] = tax
        out["sr_return_amt_inc_tax"] = amt + tax
        out["sr_fee"] = hmod(rows, "sr.fee", 10_000)
        out["sr_return_ship_cost"] = hmod(rows, "sr.shipc", 5_000)
        third = amt // 3
        out["sr_refunded_cash"] = third
        out["sr_reversed_charge"] = third
        out["sr_store_credit"] = amt - 2 * third
        out["sr_net_loss"] = hmod(rows, "sr.loss", 10_000)
        return out

    def _cs_values(self, sf, rows):
        c = _counts(sf)
        ni = _inv_items(sf)
        out = {}
        # a quarter of catalog orders are REPURCHASES: they reuse the
        # (customer, item) of a returned store sale and sell 1-3 months
        # after it, so the cross-channel chain queries (q25/q29:
        # sale -> return -> catalog re-purchase) find join partners
        echo = hmod(rows, "cs.echo", 4) == 0
        ss_parent = (rows % np.int64(max(c["store_sales"] // 2, 1))) * 2
        y99 = days_from_civil_host(1999, 1, 1) - _DS_START
        parent_sold = y99 + hmod(ss_parent, "ss.sold", 730)
        echo_sold = np.minimum(parent_sold + 30 + hmod(rows, "cs.relag",
                                                       60),
                               _SOLD_DAYS - 1)
        sold = np.where(echo, echo_sold,
                        hmod(rows, "cs.sold", _SOLD_DAYS))
        out["cs_sold_date_sk"] = _SK0 + sold
        out["cs_sold_time_sk"] = hmod(rows, "cs.time", 86_400)
        ship = np.minimum(sold + 2 + hmod(rows, "cs.shiplag", 58),
                          _DS_DAYS - 1)
        out["cs_ship_date_sk"] = _SK0 + ship
        echo_cust = hmod(ss_parent, "ss.cust", c["customer"]) + 1
        cust = np.where(echo, echo_cust,
                        hmod(rows, "cs.cust", c["customer"]) + 1)
        out["cs_bill_customer_sk"] = cust
        out["cs_bill_cdemo_sk"] = hmod(rows, "cs.cdemo",
                                       c["customer_demographics"]) + 1
        out["cs_bill_hdemo_sk"] = hmod(rows, "cs.hdemo",
                                       c["household_demographics"]) + 1
        out["cs_bill_addr_sk"] = hmod(rows, "cs.addr",
                                      c["customer_address"]) + 1
        out["cs_ship_customer_sk"] = cust
        out["cs_ship_cdemo_sk"] = out["cs_bill_cdemo_sk"]
        out["cs_ship_hdemo_sk"] = out["cs_bill_hdemo_sk"]
        out["cs_ship_addr_sk"] = out["cs_bill_addr_sk"]
        out["cs_call_center_sk"] = hmod(rows, "cs.cc",
                                        c["call_center"]) + 1
        out["cs_catalog_page_sk"] = hmod(rows, "cs.page",
                                         c["catalog_page"]) + 1
        out["cs_ship_mode_sk"] = hmod(rows, "cs.shipmode",
                                      c["ship_mode"]) + 1
        out["cs_warehouse_sk"] = hmod(rows, "cs.wh", c["warehouse"]) + 1
        # bias toward inventory-covered items (q72 joins inventory);
        # repurchase rows reuse the parent store sale's item
        echo_item = np.where(
            hmod(ss_parent, "ss.itempick", 2) == 0,
            hmod(ss_parent, "ss.itemA", ni) + 1,
            hmod(ss_parent, "ss.itemB", c["item"]) + 1)
        out["cs_item_sk"] = np.where(
            echo, echo_item, np.where(
                hmod(rows, "cs.itempick", 4) < 3,
                hmod(rows, "cs.itemA", ni) + 1,
                hmod(rows, "cs.itemB", c["item"]) + 1))
        promo_null = hmod(rows, "cs.promo.null", 5) == 0
        out["cs_promo_sk"] = (hmod(rows, "cs.promo",
                                   c["promotion"]) + 1, promo_null)
        out["cs_order_number"] = rows // 4 + 1
        qty = hmod(rows, "cs.qty", 100) + 1
        out["cs_quantity"] = qty
        whole = 100 + hmod(rows, "cs.whole", 9_900)
        lst = whole + (whole * (20 + hmod(rows, "cs.markup", 80))) // 100
        disc = hmod(rows, "cs.disc", 30)
        sales = (lst * (100 - disc)) // 100
        out["cs_wholesale_cost"] = whole
        out["cs_list_price"] = lst
        out["cs_sales_price"] = sales
        out["cs_ext_discount_amt"] = qty * (lst - sales)
        out["cs_ext_sales_price"] = qty * sales
        out["cs_ext_wholesale_cost"] = qty * whole
        out["cs_ext_list_price"] = qty * lst
        tax = (qty * sales * hmod(rows, "cs.tax", 9)) // 100
        out["cs_ext_tax"] = tax
        coupon = np.where(hmod(rows, "cs.coup", 10) == 0,
                          (qty * sales) // 10, 0)
        out["cs_coupon_amt"] = coupon
        shipc = qty * hmod(rows, "cs.shipc", 1_000)
        out["cs_ext_ship_cost"] = shipc
        net = qty * sales - coupon
        out["cs_net_paid"] = net
        out["cs_net_paid_inc_tax"] = net + tax
        out["cs_net_paid_inc_ship"] = net + shipc
        out["cs_net_paid_inc_ship_tax"] = net + shipc + tax
        out["cs_net_profit"] = net - qty * whole
        return out

    def _gen_catalog_sales(self, sf, rows, cols):
        return self._cs_values(sf, rows)

    def _gen_catalog_returns(self, sf, rows, cols):
        parent = rows * 3
        cs = self._cs_values(sf, parent)
        out = {}
        sold = cs["cs_sold_date_sk"] - _SK0
        ret = np.minimum(sold + 1 + hmod(rows, "cr.lag", 60), _DS_DAYS - 1)
        out["cr_returned_date_sk"] = _SK0 + ret
        out["cr_returned_time_sk"] = hmod(rows, "cr.time", 86_400)
        out["cr_item_sk"] = cs["cs_item_sk"]
        out["cr_refunded_customer_sk"] = cs["cs_bill_customer_sk"]
        out["cr_refunded_cdemo_sk"] = cs["cs_bill_cdemo_sk"]
        out["cr_refunded_hdemo_sk"] = cs["cs_bill_hdemo_sk"]
        out["cr_refunded_addr_sk"] = cs["cs_bill_addr_sk"]
        out["cr_returning_customer_sk"] = cs["cs_bill_customer_sk"]
        out["cr_returning_cdemo_sk"] = cs["cs_bill_cdemo_sk"]
        out["cr_returning_hdemo_sk"] = cs["cs_bill_hdemo_sk"]
        out["cr_returning_addr_sk"] = cs["cs_bill_addr_sk"]
        out["cr_call_center_sk"] = cs["cs_call_center_sk"]
        out["cr_catalog_page_sk"] = cs["cs_catalog_page_sk"]
        out["cr_ship_mode_sk"] = cs["cs_ship_mode_sk"]
        out["cr_warehouse_sk"] = cs["cs_warehouse_sk"]
        out["cr_reason_sk"] = hmod(rows, "cr.reason", 35) + 1
        out["cr_order_number"] = cs["cs_order_number"]
        rqty = 1 + hmod(rows, "cr.qty", 100) % cs["cs_quantity"]
        out["cr_return_quantity"] = rqty
        amt = rqty * cs["cs_sales_price"]
        out["cr_return_amount"] = amt
        tax = (amt * hmod(rows, "cr.tax", 9)) // 100
        out["cr_return_tax"] = tax
        out["cr_return_amt_inc_tax"] = amt + tax
        out["cr_fee"] = hmod(rows, "cr.fee", 10_000)
        out["cr_return_ship_cost"] = hmod(rows, "cr.shipc", 5_000)
        # refund components sum BELOW the sale price so q64's cs_ui
        # HAVING (sale > 2*refund) keeps most items
        sixth = amt // 6
        out["cr_refunded_cash"] = sixth
        out["cr_reversed_charge"] = sixth
        out["cr_store_credit"] = sixth
        out["cr_net_loss"] = hmod(rows, "cr.loss", 10_000)
        return out


class TpcdsPageSource(ConnectorPageSource):
    def __init__(self, table: _DsTable, sf: float, split: ConnectorSplit,
                 columns: Sequence[ColumnHandle], page_rows: int):
        self.table = table
        self.sf = sf
        self.columns = [c.name for c in columns]
        self.pos = split.row_start
        self.end = split.row_end
        self.page_rows = page_rows
        from .spi import constrained_gen_columns

        self.constraint = split.table.constraint
        self.gen_columns = constrained_gen_columns(self.columns,
                                                   self.constraint)

    def get_next_page(self) -> Optional[Page]:
        if self.pos >= self.end:
            return None
        end = min(self.pos + self.page_rows, self.end)
        page = self.table.generate(self.sf, self.pos, end,
                                   self.gen_columns)
        self.pos = end
        if self.constraint is not None:
            from .spi import enforce_constraint_page

            page = enforce_constraint_page(
                page, self.gen_columns, self.constraint,
                project=range(len(self.columns)))
        return page

    def is_finished(self) -> bool:
        return self.pos >= self.end


class TpcdsMetadata(ConnectorMetadata):
    def __init__(self, conn: "TpcdsConnector"):
        self.conn = conn

    def list_schemas(self) -> List[str]:
        return list(_SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return list(_TABLE_COLUMNS)

    def get_table_handle(self, schema, table) -> Optional[TableHandle]:
        if schema in _SCHEMAS and table in _TABLE_COLUMNS:
            return TableHandle(self.conn.catalog_name, schema, table)
        return None

    def apply_filter(self, table: TableHandle, constraint):
        """Full row-level enforcement at generation, like the TPC-H
        connector (reference: ConnectorMetadata.applyFilter)."""
        from .spi import negotiate_constraint

        return negotiate_constraint(
            table, constraint,
            (n for n, _ in _TABLE_COLUMNS[table.table]))

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        return [ColumnHandle(n, t, i) for i, (n, t)
                in enumerate(_TABLE_COLUMNS[table.table])]

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        sf = _SCHEMAS[table.schema]
        rows = _counts(sf)[table.table]
        cols = {}
        for cname, _ in _TABLE_COLUMNS[table.table]:
            if cname.endswith("_sk"):
                cols[cname] = ColumnStatistics(distinct_count=rows * 0.9)
        return TableStatistics(row_count=float(rows), columns=cols)


class TpcdsSplitManager(ConnectorSplitManager):
    def __init__(self, conn: "TpcdsConnector"):
        self.conn = conn

    def get_splits(self, table: TableHandle,
                   desired_splits: int) -> List[ConnectorSplit]:
        sf = _SCHEMAS[table.schema]
        n = _counts(sf)[table.table]
        k = max(1, min(desired_splits, (n + 1023) // 1024))
        bounds = np.linspace(0, n, k + 1).astype(int)
        return [ConnectorSplit(table, i, k, int(bounds[i]),
                               int(bounds[i + 1]))
                for i in range(k) if bounds[i] < bounds[i + 1]]


class TpcdsConnector(Connector):
    name = "tpcds"

    def data_version(self) -> int:
        return 0    # deterministic generator: data never changes

    def __init__(self, catalog_name: str = "tpcds",
                 page_rows: int = 65536):
        self.catalog_name = catalog_name
        self.page_rows = page_rows
        self._tables: Dict[str, _DsTable] = {}

    def table(self, name: str) -> _DsTable:
        t = self._tables.get(name)
        if t is None:
            t = _DsTable(self, name)
            self._tables[name] = t
        return t

    def metadata(self) -> ConnectorMetadata:
        return TpcdsMetadata(self)

    def split_manager(self) -> ConnectorSplitManager:
        return TpcdsSplitManager(self)

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        sf = _SCHEMAS[split.table.schema]
        return TpcdsPageSource(self.table(split.table.table), sf, split,
                               columns, self.page_rows)
