"""TPC-H synthetic data connector.

Reference analog: ``plugin/trino-tpch`` (TpchConnectorFactory, TpchMetadata,
TpchRecordSetProvider — itself wrapping an airlift port of dbgen).

This is a from-scratch, vectorized, *counter-based* generator: every value
is a pure function of (table, column, row index) through splitmix64, so a
split can generate any row range independently — deterministic regardless
of split count or worker placement. Schema, cardinalities and value
distributions follow the TPC-H specification (v3.0 §4.2); the RNG streams
are NOT dbgen's, so rows differ from dbgen output while matching its
distributions. Correctness testing cross-checks queries against a sqlite
oracle loaded with THIS generator's data (SURVEY.md §4's H2QueryRunner
analog), so bit-parity with dbgen is not required.

Schemas: tiny (SF 0.01), sf1, sf10, sf100, sf1000.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import types as T
from ..block import Block, Dictionary, Page
from ..expr.functions import days_from_civil_host
from .spi import (ColumnHandle, Connector, ConnectorMetadata,
                  ConnectorPageSource, ConnectorSplit, ConnectorSplitManager,
                  ColumnStatistics, TableHandle, TableStatistics)

# ---------------------------------------------------------------------------
# counter-based RNG: splitmix64, vectorized

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        z = (x + _GOLDEN).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _tag(name: str) -> np.uint64:
    h = np.uint64(1469598103934665603)
    for ch in name.encode():
        with np.errstate(over="ignore"):
            h = (h ^ np.uint64(ch)) * np.uint64(1099511628211)
    return h


def h64(rows: np.ndarray, tag: str) -> np.ndarray:
    """Deterministic uint64 stream for a column over row indices."""
    with np.errstate(over="ignore"):
        return _splitmix64(rows.astype(np.uint64) * _GOLDEN + _tag(tag))


def hmod(rows: np.ndarray, tag: str, n: int) -> np.ndarray:
    return (h64(rows, tag) % np.uint64(n)).astype(np.int64)


# ---------------------------------------------------------------------------
# spec word lists (TPC-H v3.0 §4.2.2.13)

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIP_INSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                 "TAKE BACK RETURN"]
SHIP_MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod", "green",
    "grey", "honeydew", "hot", "indian", "ivory", "khaki", "lace", "lavender",
    "lawn", "lemon", "light", "lime", "linen", "magenta", "maroon", "medium",
    "metallic", "midnight", "mint", "misty", "moccasin", "navajo", "navy",
    "olive", "orange", "orchid", "pale", "papaya", "peach", "peru", "pink",
    "plum", "powder", "puff", "purple", "red", "rose", "rosy", "royal",
    "saddle", "salmon", "sandy", "seashell", "sienna", "sky", "slate",
    "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato",
    "turquoise", "violet", "wheat", "white", "yellow",
]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("RUSSIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_TEXT_WORDS = [
    "packages", "requests", "accounts", "deposits", "foxes", "ideas",
    "theodolites", "instructions", "dependencies", "excuses", "platelets",
    "asymptotes", "courts", "dolphins", "multipliers", "sauternes",
    "warthogs", "frets", "dinos", "attainments", "somas", "braids",
    "frays", "warhorses", "dugouts", "notornis", "epitaphs", "pearls",
    "tithes", "waters", "orbits", "gifts", "sheaves", "patterns", "forges",
    "realms", "pains", "pinto", "beans", "hockey", "players", "about",
    "carefully", "quickly", "furiously", "slyly", "blithely", "daringly",
    "fluffily", "express", "regular", "special", "pending", "ironic",
    "final", "bold", "unusual", "even", "silent", "against", "along",
    "among", "around", "believe", "detect", "integrate", "sleep", "nag",
    "use", "wake", "above", "after", "boost", "cajole", "haggle", "the",
]

_START = days_from_civil_host(1992, 1, 1)
_END = days_from_civil_host(1998, 12, 31)
_CURRENT = days_from_civil_host(1995, 6, 17)
_ORDER_DATE_SPAN = _END - _START - 151

D12_2 = T.decimal_type(12, 2)

_SCHEMAS = {"micro": 0.001, "tiny": 0.01, "sf1": 1.0, "sf10": 10.0,
            "sf100": 100.0, "sf1000": 1000.0}


def _counts(sf: float) -> Dict[str, int]:
    return {
        "region": 5,
        "nation": 25,
        "supplier": max(1, int(10_000 * sf)),
        "customer": max(1, int(150_000 * sf)),
        "part": max(1, int(200_000 * sf)),
        "partsupp": max(1, int(200_000 * sf)) * 4,
        "orders": max(1, int(1_500_000 * sf)),
        # lineitem count is derived (avg ~4 lines/order)
    }


_TABLE_COLUMNS: Dict[str, List] = {
    "region": [("r_regionkey", T.BIGINT), ("r_name", T.varchar_type(25)),
               ("r_comment", T.varchar_type(152))],
    "nation": [("n_nationkey", T.BIGINT), ("n_name", T.varchar_type(25)),
               ("n_regionkey", T.BIGINT), ("n_comment", T.varchar_type(152))],
    "supplier": [("s_suppkey", T.BIGINT), ("s_name", T.varchar_type(25)),
                 ("s_address", T.varchar_type(40)),
                 ("s_nationkey", T.BIGINT), ("s_phone", T.varchar_type(15)),
                 ("s_acctbal", D12_2), ("s_comment", T.varchar_type(101))],
    "customer": [("c_custkey", T.BIGINT), ("c_name", T.varchar_type(25)),
                 ("c_address", T.varchar_type(40)),
                 ("c_nationkey", T.BIGINT), ("c_phone", T.varchar_type(15)),
                 ("c_acctbal", D12_2),
                 ("c_mktsegment", T.varchar_type(10)),
                 ("c_comment", T.varchar_type(117))],
    "part": [("p_partkey", T.BIGINT), ("p_name", T.varchar_type(55)),
             ("p_mfgr", T.varchar_type(25)), ("p_brand", T.varchar_type(10)),
             ("p_type", T.varchar_type(25)), ("p_size", T.BIGINT),
             ("p_container", T.varchar_type(10)), ("p_retailprice", D12_2),
             ("p_comment", T.varchar_type(23))],
    "partsupp": [("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
                 ("ps_availqty", T.BIGINT), ("ps_supplycost", D12_2),
                 ("ps_comment", T.varchar_type(199))],
    "orders": [("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
               ("o_orderstatus", T.varchar_type(1)), ("o_totalprice", D12_2),
               ("o_orderdate", T.DATE),
               ("o_orderpriority", T.varchar_type(15)),
               ("o_clerk", T.varchar_type(15)), ("o_shippriority", T.BIGINT),
               ("o_comment", T.varchar_type(79))],
    "lineitem": [("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT),
                 ("l_suppkey", T.BIGINT), ("l_linenumber", T.BIGINT),
                 ("l_quantity", D12_2), ("l_extendedprice", D12_2),
                 ("l_discount", D12_2), ("l_tax", D12_2),
                 ("l_returnflag", T.varchar_type(1)),
                 ("l_linestatus", T.varchar_type(1)),
                 ("l_shipdate", T.DATE), ("l_commitdate", T.DATE),
                 ("l_receiptdate", T.DATE),
                 ("l_shipinstruct", T.varchar_type(25)),
                 ("l_shipmode", T.varchar_type(10)),
                 ("l_comment", T.varchar_type(44))],
}


def _comment(rows: np.ndarray, tag: str, max_words: int = 8) -> List[str]:
    nw = 3 + hmod(rows, tag + ".n", max_words - 2)
    picks = [hmod(rows, f"{tag}.{i}", len(_TEXT_WORDS)) for i in range(max_words)]
    words = np.asarray(_TEXT_WORDS, dtype=object)
    cols = [words[p] for p in picks]
    return [" ".join(cols[j][i] for j in range(nw[i]))
            for i in range(len(rows))]


def _alnum(rows: np.ndarray, tag: str, lo: int, hi: int) -> List[str]:
    alphabet = np.asarray(list(
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789 ,"),
        dtype=object)
    ln = lo + hmod(rows, tag + ".len", hi - lo + 1)
    mx = hi
    chars = [alphabet[hmod(rows, f"{tag}.{i}", len(alphabet))]
             for i in range(mx)]
    return ["".join(chars[j][i] for j in range(ln[i]))
            for i in range(len(rows))]


def _phone(nationkey: np.ndarray, rows: np.ndarray, tag: str) -> List[str]:
    a = nationkey + 10
    b = hmod(rows, tag + ".b", 900) + 100
    c = hmod(rows, tag + ".c", 900) + 100
    d = hmod(rows, tag + ".d", 9000) + 1000
    return [f"{a[i]}-{b[i]}-{c[i]}-{d[i]}" for i in range(len(rows))]


def _acctbal(rows: np.ndarray, tag: str) -> np.ndarray:
    # [-999.99, 9999.99] as scaled int64
    return hmod(rows, tag, 999_99 + 999_999 + 1) - 999_99


def _nonzero_mod3_key(idx: np.ndarray) -> np.ndarray:
    """Map dense index -> the idx-th positive integer not divisible by 3
    (spec: a third of customers never place orders)."""
    return 3 * (idx // 2) + 1 + (idx % 2)


class _Table:
    """Generates column arrays for a row range. Dictionaries for string
    columns live on the connector so code spaces are stable across splits
    and pages (group-by/join correctness relies on this)."""

    def __init__(self, conn: "TpchConnector", name: str):
        self.conn = conn
        self.name = name
        self.columns = _TABLE_COLUMNS[name]
        self.dicts: Dict[str, Dictionary] = {}
        for cname, ctype in self.columns:
            if ctype.is_string:
                self.dicts[cname] = Dictionary()

    def row_count(self, sf: float) -> int:
        if self.name == "lineitem":
            orders = _counts(sf)["orders"]
            return int(_lines_per_order(np.arange(orders)).sum())
        return _counts(sf)[self.name]

    def generate(self, sf: float, start: int, end: int,
                 columns: Sequence[str]) -> Page:
        rows = np.arange(start, end, dtype=np.int64)
        gen = getattr(self, f"_gen_{self.name}")
        data = gen(sf, rows, set(columns))
        blocks = []
        for cname in columns:
            ctype = dict(self.columns)[cname]
            vals = data[cname]
            if ctype.is_string:
                d = self.dicts[cname]
                if isinstance(vals, tuple):
                    # fast path: (codes into pool, pool) — vectorized remap
                    codes_in, pool = vals
                    remap = d.encode(pool)
                    codes = remap[np.asarray(codes_in, dtype=np.int64)]
                else:
                    codes = d.encode(vals)
                blocks.append(Block(ctype, codes.astype(np.int32), None, d))
            else:
                blocks.append(Block(ctype, np.asarray(vals, dtype=ctype.storage)))
        n = len(blocks[0]) if blocks else end - start
        return Page(blocks, n)

    # -- per-table generators ------------------------------------------

    def _gen_region(self, sf, rows, cols):
        out = {}
        out["r_regionkey"] = rows
        out["r_name"] = [REGIONS[i] for i in rows]
        out["r_comment"] = _comment(rows, "r.comment")
        return out

    def _gen_nation(self, sf, rows, cols):
        out = {}
        out["n_nationkey"] = rows
        out["n_name"] = [NATIONS[i][0] for i in rows]
        out["n_regionkey"] = np.asarray([NATIONS[i][1] for i in rows])
        out["n_comment"] = _comment(rows, "n.comment")
        return out

    def _gen_supplier(self, sf, rows, cols):
        out = {}
        key = rows + 1
        out["s_suppkey"] = key
        if "s_name" in cols:
            out["s_name"] = [f"Supplier#{k:09d}" for k in key]
        if "s_address" in cols:
            out["s_address"] = _alnum(rows, "s.addr", 10, 40)
        nat = hmod(rows, "s.nation", 25)
        out["s_nationkey"] = nat
        if "s_phone" in cols:
            out["s_phone"] = _phone(nat, rows, "s.phone")
        if "s_acctbal" in cols:
            out["s_acctbal"] = _acctbal(rows, "s.acctbal")
        if "s_comment" in cols:
            comments = _comment(rows, "s.comment")
            # spec 4.2.3: ~5 per 10k suppliers get Customer...Complaints,
            # ~5 get Customer...Recommends
            flag = h64(rows, "s.cmplnt") % np.uint64(2000)
            for i in np.nonzero(flag == 0)[0]:
                comments[i] = comments[i] + " Customer Complaints"
            for i in np.nonzero(flag == 1)[0]:
                comments[i] = comments[i] + " Customer Recommends"
            out["s_comment"] = comments
        return out

    def _gen_customer(self, sf, rows, cols):
        out = {}
        key = rows + 1
        out["c_custkey"] = key
        if "c_name" in cols:
            out["c_name"] = [f"Customer#{k:09d}" for k in key]
        if "c_address" in cols:
            out["c_address"] = _alnum(rows, "c.addr", 10, 40)
        nat = hmod(rows, "c.nation", 25)
        out["c_nationkey"] = nat
        if "c_phone" in cols:
            out["c_phone"] = _phone(nat, rows, "c.phone")
        if "c_acctbal" in cols:
            out["c_acctbal"] = _acctbal(rows, "c.acctbal")
        if "c_mktsegment" in cols:
            out["c_mktsegment"] = (hmod(rows, "c.segment", 5), SEGMENTS)
        if "c_comment" in cols:
            out["c_comment"] = _comment(rows, "c.comment", 10)
        return out

    def _gen_part(self, sf, rows, cols):
        out = {}
        key = rows + 1
        out["p_partkey"] = key
        if "p_name" in cols:
            picks = [hmod(rows, f"p.name.{i}", len(COLORS)) for i in range(5)]
            out["p_name"] = [" ".join(COLORS[picks[j][i]] for j in range(5))
                             for i in range(len(rows))]
        m = 1 + hmod(rows, "p.mfgr", 5)
        if "p_mfgr" in cols:
            out["p_mfgr"] = (m - 1, [f"Manufacturer#{v}" for v in range(1, 6)])
        if "p_brand" in cols:
            n = 1 + hmod(rows, "p.brand", 5)
            pool = [f"Brand#{a}{b}" for a in range(1, 6) for b in range(1, 6)]
            out["p_brand"] = ((m - 1) * 5 + (n - 1), pool)
        if "p_type" in cols:
            t1 = hmod(rows, "p.type1", 6)
            t2 = hmod(rows, "p.type2", 5)
            t3 = hmod(rows, "p.type3", 5)
            pool = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2
                    for c in TYPE_S3]
            out["p_type"] = (t1 * 25 + t2 * 5 + t3, pool)
        if "p_size" in cols:
            out["p_size"] = 1 + hmod(rows, "p.size", 50)
        if "p_container" in cols:
            c1 = hmod(rows, "p.cont1", 5)
            c2 = hmod(rows, "p.cont2", 8)
            pool = [f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2]
            out["p_container"] = (c1 * 8 + c2, pool)
        if "p_retailprice" in cols:
            out["p_retailprice"] = _retail_price(key)
        if "p_comment" in cols:
            out["p_comment"] = _comment(rows, "p.comment", 5)
        return out

    def _gen_partsupp(self, sf, rows, cols):
        out = {}
        scount = _counts(sf)["supplier"]
        p = rows // 4 + 1
        i = rows % 4
        out["ps_partkey"] = p
        out["ps_suppkey"] = _supp_for_part(p, i, scount)
        if "ps_availqty" in cols:
            out["ps_availqty"] = 1 + hmod(rows, "ps.avail", 9999)
        if "ps_supplycost" in cols:
            out["ps_supplycost"] = 100 + hmod(rows, "ps.cost", 99_901)
        if "ps_comment" in cols:
            out["ps_comment"] = _comment(rows, "ps.comment", 12)
        return out

    def _gen_orders(self, sf, rows, cols):
        out = {}
        ccount = _counts(sf)["customer"]
        key = rows + 1
        out["o_orderkey"] = key
        if "o_custkey" in cols:
            idx = hmod(rows, "o.cust", max(1, ccount // 3 * 2))
            out["o_custkey"] = np.minimum(_nonzero_mod3_key(idx), ccount)
        od = _START + hmod(rows, "o.date", _ORDER_DATE_SPAN)
        out["o_orderdate"] = od.astype(np.int32)
        if "o_orderstatus" in cols or "o_totalprice" in cols:
            status, total = _order_rollup(rows, od, sf)
            smap = {"F": 0, "O": 1, "P": 2}
            out["o_orderstatus"] = (
                np.asarray([smap[str(s)] for s in status]), ["F", "O", "P"])
            out["o_totalprice"] = total
        if "o_orderpriority" in cols:
            out["o_orderpriority"] = (hmod(rows, "o.prio", 5), PRIORITIES)
        if "o_clerk" in cols:
            nclerk = max(1, int(1000 * sf))
            ck = 1 + hmod(rows, "o.clerk", nclerk)
            out["o_clerk"] = [f"Clerk#{v:09d}" for v in ck]
        if "o_shippriority" in cols:
            out["o_shippriority"] = np.zeros(len(rows), dtype=np.int64)
        if "o_comment" in cols:
            comments = _comment(rows, "o.comment", 10)
            # q13 relies on '%special%requests%' appearing in ~1% of comments
            flag = h64(rows, "o.spreq") % np.uint64(100)
            for i in np.nonzero(flag == 0)[0]:
                comments[i] = comments[i] + " special requests"
            out["o_comment"] = comments
        return out

    def _gen_lineitem(self, sf, rows, cols):
        # `rows` here are ORDER indices; lines expand within
        order_idx = rows
        nlines = _lines_per_order(order_idx)
        o = np.repeat(order_idx, nlines)
        ln = _ranges(nlines)  # 0-based line number within order
        g = o * np.int64(8) + ln  # global line tag (order, line)
        out = {}
        out["l_orderkey"] = o + 1
        pcount = _counts(sf)["part"]
        scount = _counts(sf)["supplier"]
        p = 1 + hmod(g, "l.part", pcount)
        out["l_partkey"] = p
        out["l_suppkey"] = _supp_for_part(p, hmod(g, "l.supp", 4), scount)
        out["l_linenumber"] = ln + 1
        qty = 1 + hmod(g, "l.qty", 50)
        out["l_quantity"] = qty * 100
        out["l_extendedprice"] = qty * _retail_price(p)
        out["l_discount"] = hmod(g, "l.disc", 11)
        out["l_tax"] = hmod(g, "l.tax", 9)
        od = _START + hmod(o, "o.date", _ORDER_DATE_SPAN)
        ship = od + 1 + hmod(g, "l.ship", 121)
        commit = od + 30 + hmod(g, "l.commit", 61)
        receipt = ship + 1 + hmod(g, "l.rcpt", 30)
        out["l_shipdate"] = ship.astype(np.int32)
        out["l_commitdate"] = commit.astype(np.int32)
        out["l_receiptdate"] = receipt.astype(np.int32)
        if "l_returnflag" in cols:
            r = hmod(g, "l.rflag", 2)
            codes = np.where(receipt <= _CURRENT, np.where(r == 0, 0, 1), 2)
            out["l_returnflag"] = (codes, ["R", "A", "N"])
        if "l_linestatus" in cols:
            out["l_linestatus"] = (np.where(ship > _CURRENT, 0, 1),
                                   ["O", "F"])
        if "l_shipinstruct" in cols:
            out["l_shipinstruct"] = (hmod(g, "l.instr", 4), SHIP_INSTRUCT)
        if "l_shipmode" in cols:
            out["l_shipmode"] = (hmod(g, "l.mode", 7), SHIP_MODES)
        if "l_comment" in cols:
            out["l_comment"] = _comment(g, "l.comment", 6)
        return out


def _retail_price(partkey: np.ndarray) -> np.ndarray:
    """decimal(12,2) raw cents (spec 4.2.3: 90000+((pk/10)%20001)+100*(pk%1000))."""
    return (90_000 + ((partkey // 10) % 20_001) + 100 * (partkey % 1_000))


def _supp_for_part(partkey: np.ndarray, i: np.ndarray, scount: int) -> np.ndarray:
    """Spec 4.2.3 partsupp formula: the 4 suppliers of a part; lineitem uses
    the same so l_partkey/l_suppkey pairs exist in partsupp."""
    s = np.int64(scount)
    return ((partkey + i * (s // 4 + (partkey - 1) // s)) % s) + 1


def _lines_per_order(order_idx: np.ndarray) -> np.ndarray:
    return 1 + hmod(np.asarray(order_idx, dtype=np.int64), "o.nlines", 7)


def _ranges(counts: np.ndarray) -> np.ndarray:
    """[0,1,..c0-1, 0,1,..c1-1, ...] for counts c."""
    total = int(counts.sum())
    idx = np.arange(total, dtype=np.int64)
    starts = np.repeat(np.cumsum(counts) - counts, counts)
    return idx - starts


def _order_rollup(order_idx: np.ndarray, od: np.ndarray, sf: float):
    """Per-order status + total price, derived from its lineitems by
    recomputing each line's counter-based values with the same tags as
    ``_gen_lineitem`` (spec: status F if all lines F, O if all O, else P;
    total = sum of extprice*(1+tax)*(1-disc))."""
    pcount = _counts(sf)["part"]
    n = len(order_idx)
    nlines = _lines_per_order(order_idx)
    all_f = np.ones(n, dtype=bool)
    all_o = np.ones(n, dtype=bool)
    total = np.zeros(n, dtype=np.int64)
    for line in range(7):
        has = nlines > line
        g = order_idx * np.int64(8) + line
        ship = od + 1 + hmod(g, "l.ship", 121)
        is_o = ship > _CURRENT
        all_f &= ~has | ~is_o
        all_o &= ~has | is_o
        qty = 1 + hmod(g, "l.qty", 50)
        p = 1 + hmod(g, "l.part", pcount)
        ext = qty * _retail_price(p)          # cents
        disc = hmod(g, "l.disc", 11)          # hundredths
        tax = hmod(g, "l.tax", 9)
        # ext*(1+tax)*(1-disc) at scale 2: divide the scale-6 product
        prod = ext * (100 + tax) * (100 - disc)
        line_total = (prod + 5_000) // 10_000  # round half up (positive)
        total += np.where(has, line_total, 0)
    status = np.where(all_f, "F", np.where(all_o, "O", "P"))
    return status, total


class TpchPageSource(ConnectorPageSource):
    def __init__(self, table: _Table, sf: float, split: ConnectorSplit,
                 columns: Sequence[ColumnHandle], page_rows: int):
        self.table = table
        self.sf = sf
        self.columns = [c.name for c in columns]
        self.pos = split.row_start
        self.end = split.row_end
        self.page_rows = page_rows
        # pushed-down constraint: also generate the constrained columns
        # (they may have been pruned from the projection), mask, then
        # project back down
        from .spi import constrained_gen_columns

        self.constraint = split.table.constraint
        self.gen_columns = constrained_gen_columns(self.columns,
                                                   self.constraint)

    def get_next_page(self) -> Optional[Page]:
        if self.pos >= self.end:
            return None
        end = min(self.pos + self.page_rows, self.end)
        page = self.table.generate(self.sf, self.pos, end,
                                   self.gen_columns)
        self.pos = end
        if self.constraint is not None:
            from .spi import enforce_constraint_page

            page = enforce_constraint_page(
                page, self.gen_columns, self.constraint,
                project=range(len(self.columns)))
        return page

    def is_finished(self) -> bool:
        return self.pos >= self.end


class TpchMetadata(ConnectorMetadata):
    def __init__(self, conn: "TpchConnector"):
        self.conn = conn

    def list_schemas(self) -> List[str]:
        return list(_SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return list(_TABLE_COLUMNS)

    def get_table_handle(self, schema, table) -> Optional[TableHandle]:
        if schema in _SCHEMAS and table in _TABLE_COLUMNS:
            return TableHandle(self.conn.catalog_name, schema, table)
        return None

    def get_columns(self, table: TableHandle) -> List[ColumnHandle]:
        return [ColumnHandle(n, t, i) for i, (n, t)
                in enumerate(_TABLE_COLUMNS[table.table])]

    def apply_filter(self, table: TableHandle, constraint):
        """Accept any domain over real columns for FULL row-level
        enforcement at page generation (reference:
        plugin/trino-tpch/.../TpchMetadata.java applyFilter; there only
        orderstatus/type/container prune, here the generator masks any
        column)."""
        from .spi import negotiate_constraint

        return negotiate_constraint(
            table, constraint,
            (n for n, _ in _TABLE_COLUMNS[table.table]))

    def get_statistics(self, table: TableHandle) -> TableStatistics:
        """Row counts plus the per-column ndv / min-max the cost model
        feeds on (reference: TpchMetadata.getTableStatistics serving
        cost/ScanStatsRule). Values follow the generator's formulas."""
        sf = _SCHEMAS[table.schema]
        c = _counts(sf)
        t = self.conn.table(table.table)
        rows = t.row_count(sf)
        cols: Dict[str, ColumnStatistics] = {}

        def put(name, ndv=None, lo=None, hi=None):
            cols[name] = ColumnStatistics(distinct_count=ndv,
                                          min_value=lo, max_value=hi)

        tb = table.table
        if tb == "lineitem":
            put("l_orderkey", c["orders"], 1, c["orders"])
            put("l_partkey", c["part"], 1, c["part"])
            put("l_suppkey", c["supplier"], 1, c["supplier"])
            put("l_linenumber", 7, 1, 7)
            # decimal columns: raw scaled units (cents for scale 2 —
            # IR literals carry raw values)
            put("l_quantity", 50, 100, 5000)
            put("l_discount", 11, 0, 10)
            put("l_tax", 9, 0, 8)
            put("l_returnflag", 3)
            put("l_linestatus", 2)
            put("l_shipdate", 2526, _START + 1, _END)
            put("l_commitdate", 2466, _START + 30, _END)
            put("l_receiptdate", 2554, _START + 2, _END + 30)
            put("l_shipmode", 7)
            put("l_shipinstruct", 4)
        elif tb == "orders":
            put("o_orderkey", c["orders"], 1, c["orders"])
            put("o_custkey", c["customer"] * 2 // 3, 1, c["customer"])
            put("o_orderstatus", 3)
            put("o_orderdate", _ORDER_DATE_SPAN, _START,
                _START + _ORDER_DATE_SPAN)
            put("o_orderpriority", 5)
            put("o_shippriority", 1, 0, 0)
        elif tb == "customer":
            put("c_custkey", c["customer"], 1, c["customer"])
            put("c_nationkey", 25, 0, 24)
            put("c_mktsegment", 5)
            put("c_acctbal", rows * 0.9, -99_999, 999_999)
        elif tb == "supplier":
            put("s_suppkey", c["supplier"], 1, c["supplier"])
            put("s_nationkey", 25, 0, 24)
            put("s_acctbal", rows * 0.9, -99_999, 999_999)
        elif tb == "part":
            put("p_partkey", c["part"], 1, c["part"])
            put("p_size", 50, 1, 50)
            put("p_brand", 25)
            put("p_type", 150)
            put("p_container", 40)
        elif tb == "partsupp":
            put("ps_partkey", c["part"], 1, c["part"])
            put("ps_suppkey", c["supplier"], 1, c["supplier"])
            put("ps_availqty", 9999, 1, 9999)
        elif tb == "nation":
            put("n_nationkey", 25, 0, 24)
            put("n_regionkey", 5, 0, 4)
            put("n_name", 25)
        elif tb == "region":
            put("r_regionkey", 5, 0, 4)
            put("r_name", 5)
        return TableStatistics(row_count=float(rows), columns=cols)


class TpchSplitManager(ConnectorSplitManager):
    def __init__(self, conn: "TpchConnector"):
        self.conn = conn

    def get_splits(self, table: TableHandle,
                   desired_splits: int) -> List[ConnectorSplit]:
        sf = _SCHEMAS[table.schema]
        t = self.conn.table(table.table)
        # lineitem splits range over ORDERS (lines expand inside the split)
        n = _counts(sf)["orders"] if table.table == "lineitem" \
            else t.row_count(sf)
        k = max(1, min(desired_splits, (n + 1023) // 1024))
        bounds = np.linspace(0, n, k + 1).astype(int)
        return [ConnectorSplit(table, i, k, int(bounds[i]), int(bounds[i + 1]))
                for i in range(k) if bounds[i] < bounds[i + 1]]


class TpchConnector(Connector):
    name = "tpch"

    def data_version(self) -> int:
        return 0    # deterministic generator: data never changes

    def __init__(self, catalog_name: str = "tpch", page_rows: int = 65536):
        self.catalog_name = catalog_name
        self.page_rows = page_rows
        self._tables: Dict[str, _Table] = {}

    def table(self, name: str) -> _Table:
        t = self._tables.get(name)
        if t is None:
            t = _Table(self, name)
            self._tables[name] = t
        return t

    def metadata(self) -> ConnectorMetadata:
        return TpchMetadata(self)

    def split_manager(self) -> ConnectorSplitManager:
        return TpchSplitManager(self)

    def page_source(self, split: ConnectorSplit,
                    columns: Sequence[ColumnHandle]) -> ConnectorPageSource:
        sf = _SCHEMAS[split.table.schema]
        return TpchPageSource(self.table(split.table.table), sf, split,
                              columns, self.page_rows)
