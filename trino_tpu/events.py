"""Query event listeners.

Reference analog: ``core/trino-spi/.../eventlistener/`` (EventListener,
QueryCreatedEvent, QueryCompletedEvent) + ``event/QueryMonitor.java``
building the payloads and ``EventListenerManager`` fanning them out.
Listener failures are swallowed (an observability plugin must not fail
queries) — the reference contract.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional


@dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    user: str
    sql: str
    create_time: float


@dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    user: str
    sql: str
    create_time: float
    end_time: float
    state: str                      # FINISHED | FAILED
    output_rows: int = 0
    error_code: Optional[str] = None
    error_message: Optional[str] = None
    #: execution statistics payload (reference: the QueryStatistics
    #: half of QueryCompletedEvent): peak memory, recovery counters,
    #: wall breakdown — whatever the runner observed, as a plain dict
    stats: Optional[dict] = None

    @property
    def wall_ms(self) -> float:
        return (self.end_time - self.create_time) * 1e3


@dataclass(frozen=True)
class WorkerReplacedEvent:
    """A dead worker was detected and a replacement spawned, registered
    and re-synced (the self-healing seam of the process runtime)."""

    worker_index: int
    old_pid: Optional[int]
    new_pid: int
    reason: str                     # heartbeat | on-demand
    time: float


@dataclass(frozen=True)
class MemoryKillEvent:
    """The cluster memory manager killed a query to relieve blocked
    worker pools (or a query_max_total_memory breach)."""

    query_id: str
    policy: str                     # killer policy name
    reserved_bytes: int             # victim's cluster-wide reservation
    time: float


@dataclass(frozen=True)
class NodeJoinedEvent:
    """A worker process joined the cluster (initial spawn, heal
    replacement, or elastic scale-up) — the membership half of the
    self-healing/elasticity seam."""

    node_id: str
    worker_index: int
    pid: int
    generation: int                 # cluster generation at join
    reason: str                     # initial | heal | scale-up | ...
    time: float


@dataclass(frozen=True)
class NodeRetiredEvent:
    """A worker process left the cluster (drain-based retire, autoscale
    scale-down, or replacement of a dead worker)."""

    node_id: str
    pid: Optional[int]
    generation: int                 # cluster generation after retire
    reason: str                     # scale-down | replaced | ...
    drained: bool                   # True when it drained gracefully
    time: float


@dataclass(frozen=True)
class TaskRetryEvent:
    """A task or query attempt was retried (or speculatively
    re-dispatched) after a classified failure."""

    task_id: str
    error_type: str                 # fault.ERROR_TYPES
    attempt: int
    speculative: bool
    query_level: bool
    time: float


class EventListener:
    """Subclass hooks (reference: spi/eventlistener/EventListener.java)."""

    def query_created(self, event: QueryCreatedEvent):
        pass

    def query_completed(self, event: QueryCompletedEvent):
        pass

    def worker_replaced(self, event: WorkerReplacedEvent):
        pass

    def node_joined(self, event: NodeJoinedEvent):
        pass

    def node_retired(self, event: NodeRetiredEvent):
        pass

    def task_retry(self, event: TaskRetryEvent):
        pass

    def memory_kill(self, event: MemoryKillEvent):
        pass


class QueryHistoryListener(EventListener):
    """Ring-buffer listener retaining the last N completed queries plus
    the currently-running set (reference: QueryTracker's history kept
    for ``/v1/query`` + ``system.runtime.queries``).  A lock guards
    both sides: readers snapshot while protocol-server executor
    threads complete queries concurrently (iterating a live deque/dict
    would raise RuntimeError mid-scrape)."""

    def __init__(self, capacity: int = 256):
        import threading

        self._lock = threading.Lock()
        self.completed: Deque[QueryCompletedEvent] = deque(
            maxlen=capacity)
        self.running: Dict[str, QueryCreatedEvent] = {}

    def query_created(self, event: QueryCreatedEvent):
        with self._lock:
            self.running[event.query_id] = event

    def query_completed(self, event: QueryCompletedEvent):
        with self._lock:
            self.running.pop(event.query_id, None)
            self.completed.append(event)

    def snapshot_completed(self) -> List[QueryCompletedEvent]:
        with self._lock:
            return list(self.completed)

    def snapshot_running(self) -> List[QueryCreatedEvent]:
        with self._lock:
            return list(self.running.values())


@dataclass
class EventListenerManager:
    listeners: List[EventListener] = field(default_factory=list)
    _counter: int = 0
    history_capacity: int = 256

    def __post_init__(self):
        # the built-in ring buffer backs system.runtime.queries and
        # /v1/query/{id}; user listeners ride alongside it
        self.history_listener = QueryHistoryListener(
            self.history_capacity)
        self.listeners = list(self.listeners) + [self.history_listener]

    def add(self, listener: EventListener):
        self.listeners.append(listener)

    def history(self, n: int = 100) -> List[QueryCompletedEvent]:
        """The most recent completed-query events, oldest first."""
        return self.history_listener.snapshot_completed()[-n:]

    def running(self) -> List[QueryCreatedEvent]:
        """Currently-executing queries (created, not yet completed)."""
        return sorted(self.history_listener.snapshot_running(),
                      key=lambda e: e.create_time)

    def next_query_id(self) -> str:
        self._counter += 1
        return f"query_{self._counter}"

    def fire_created(self, event: QueryCreatedEvent):
        for listener in self.listeners:
            try:
                listener.query_created(event)
            except Exception:
                pass

    def fire_completed(self, event: QueryCompletedEvent):
        for listener in self.listeners:
            try:
                listener.query_completed(event)
            except Exception:
                pass

    def fire_worker_replaced(self, event: WorkerReplacedEvent):
        for listener in self.listeners:
            try:
                listener.worker_replaced(event)
            except Exception:
                pass

    def fire_node_joined(self, event: NodeJoinedEvent):
        for listener in self.listeners:
            try:
                listener.node_joined(event)
            except Exception:
                pass

    def fire_node_retired(self, event: NodeRetiredEvent):
        for listener in self.listeners:
            try:
                listener.node_retired(event)
            except Exception:
                pass

    def fire_task_retry(self, event: TaskRetryEvent):
        for listener in self.listeners:
            try:
                listener.task_retry(event)
            except Exception:
                pass

    def fire_memory_kill(self, event: MemoryKillEvent):
        for listener in self.listeners:
            try:
                listener.memory_kill(event)
            except Exception:
                pass


class QueryMonitor:
    """Builds + fires the event pair around one query execution
    (reference: event/QueryMonitor.java)."""

    def __init__(self, manager: EventListenerManager, user: str,
                 sql: str):
        self.manager = manager
        self.user = user
        self.sql = sql
        self.query_id = manager.next_query_id()
        self.create_time = time.time()

    def created(self):
        self.manager.fire_created(QueryCreatedEvent(
            self.query_id, self.user, self.sql, self.create_time))

    def completed(self, output_rows: int, stats: Optional[dict] = None):
        self.manager.fire_completed(QueryCompletedEvent(
            self.query_id, self.user, self.sql, self.create_time,
            time.time(), "FINISHED", output_rows, stats=stats))

    def failed(self, error: Exception):
        self.manager.fire_completed(QueryCompletedEvent(
            self.query_id, self.user, self.sql, self.create_time,
            time.time(), "FAILED",
            error_code=getattr(error, "code", type(error).__name__),
            error_message=str(error)))
