from .driver import Driver, Pipeline  # noqa: F401
