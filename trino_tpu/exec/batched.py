"""Single-launch batched execution (rounds 16-17): run a same-shape
admission burst through ONE vmapped device launch per pipeline stage.

The serial batch path executes B same-shape statements as B separate
walks of the shared compiled programs — B launches per stage where the
programs differ only in the literal scalars they were called with.
With plan templates (``cache.PlanTemplate``) the literals are opaque
``ParamRef`` slots, so the per-stage program is ONE function of a
parameter vector; stacking the burst's literal vectors on a leading
``(B,)`` axis and ``vmap``-ing the stage (DrJAX-style lifting of the
map over statements into the compiled program) executes the whole
burst per scan page in a single launch, then demuxes member pages by
slicing the batch axis.

Round 17 extends the vmappable stage set past filter/project:

- **masked execution**: filtered rows are never compacted per lane
  (compaction would break the shape uniformity vmap needs); each stage
  carries a ``(B, n)`` validity mask and the only compaction happens at
  the final host demux (``DevicePage.to_page``).
- **aggregation** (``HashAggregationOperator``, step ``single``): the
  raw GroupByHash/sort-reduce kernels already mask invalid rows to a
  sentinel slot, so per-page partials, the concat merge, and the final
  projection all run as ``jit(vmap(...))`` lane programs. Per-lane
  dense group ids and counts demux on the host like any other column.
- **joins** (``LookupJoinOperator`` — the matmul strategy's sorted
  fallback kernels are byte-identical, so the batched path always uses
  the sorted-index probes): the build side is literal-independent by
  template construction (the aux pipelines are proved param-free), so
  ONE serial build serves all B lanes with its arrays broadcast
  (``in_axes=None``); probes mask invalid probe rows. inner/left
  expand at a lane capacity unified across the batch; semi/anti are
  pure mask updates.
- **per-lane overflow falls back alone**: a lane whose join expansion
  exceeds the unified capacity (or whose agg hash table exhausts its
  probe budget) is marked spilled — the runner re-runs that member
  (only) serially; the other lanes' results stay byte-equal and are
  served from the batch.

Lane capacities unify via ``KERNEL_SIZING`` pow2 fast-up so a repeat
burst compiles ZERO new programs: the kernel cache below is keyed by
value-level stage config (never operator identity — each burst replans
the template into fresh operators).

Eligibility is still narrower than template eligibility: a template
whose plan holds an unsupported stage (limits, full-outer joins,
residual join filters, exchanges, partial-step aggregations) EXECUTES
correctly through the shared template serially — zero retraces, B
launches. ``BatchIneligible.reason`` feeds the fallback taxonomy
counters either way, so the gap is loud, not silent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, Dictionary, Page, padded_size
from ..expr.compiler import pad_lut, param_raw
from ..ops.aggregation import (HashAggregationOperator, _final_project,
                               _group_reduce_impl, _init_states,
                               _merge_states, _rank_and_inverse,
                               _ranks_to_codes, _state_plan)
from ..ops.hashtable import (_hash_group_ids_impl,
                             _hash_segment_reduce_impl, hashable_key_types)
from ..ops.join import (LookupJoinOperator, _expand_verified_impl,
                        _finalize_join_impl, _key_u64, _probe_counts_impl,
                        _semi_matched_impl)
from ..ops.kernel_sizing import KERNEL_SIZING
from ..ops.operator import (FilterProjectOperator, OutputCollectorOperator,
                            TableScanOperator)
from ..ops.sortkeys import group_operands
from ..telemetry.profiler import instrument


class BatchIneligible(Exception):
    """This plan/batch cannot ride the vmapped path; ``reason`` is one
    of the fallback-taxonomy tags documented in COMPONENTS.md."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class BatchResult:
    """One batched execution's demuxed output.

    pages:        host pages per member (spilled members get none here)
    spilled:      member positions that overflowed a per-lane capacity
                  and must re-run serially (counted by the runner)
    dispositions: what actually ran beyond filter/project stages
                  (``agg_stage_vmapped`` / ``join_stage_vmapped``) —
                  feeds the same taxonomy counters as the fallbacks
    stage_rows:   per HBO-fingerprinted stage: exact per-lane output
                  row counts from the mask popcounts (rows key is a
                  ``(D,)`` host array over the PADDED batch; the runner
                  records real, non-spilled lanes only)
    scan_rows:    rows the shared scan produced (lane-invariant)
    """

    pages: List[List[Page]]
    spilled: Set[int]
    dispositions: List[str]
    stage_rows: List[dict]
    scan_rows: int


def vmappable_stages(plan) -> Tuple[List, TableScanOperator, List[Tuple],
                                    List[str]]:
    """Classify a plan for batching: returns (aux_pipelines, scan,
    stages, dispositions) or raises ``BatchIneligible`` with the
    taxonomy reason.

    ``stages`` is the main pipeline's interior as ("fp" | "agg" |
    "join", operator) pairs; ``aux_pipelines`` (join builds) are proved
    param-free so one serial run serves every lane."""
    pipelines = list(plan.pipelines)
    mains = [p for p in pipelines
             if p.operators and isinstance(p.operators[-1],
                                           OutputCollectorOperator)]
    if len(mains) != 1:
        raise BatchIneligible("no_collect_tail")
    main = mains[0].operators
    aux = [p for p in pipelines if p is not mains[0]]
    for p in aux:
        for op in p.operators:
            if isinstance(op, FilterProjectOperator) \
                    and op.processor.param_indices:
                # a literal reaching a build pipeline would break the
                # one-build-serves-all-lanes invariant
                raise BatchIneligible("unsupported_stage")
    if not main or not isinstance(main[0], TableScanOperator):
        raise BatchIneligible("no_scan_head")
    stages: List[Tuple] = []
    dispositions: List[str] = []
    seen_param = False
    for op in main[1:-1]:
        if isinstance(op, FilterProjectOperator):
            if op.processor.param_indices:
                seen_param = True
            stages.append(("fp", op))
        elif isinstance(op, HashAggregationOperator):
            # the batch axis must exist before a masked stage can demux
            # per lane; step single only (partial/final splits belong
            # to the exchange plans the template path never takes)
            if op.step != "single" or not seen_param:
                raise BatchIneligible("unsupported_stage")
            stages.append(("agg", op))
            if "agg_stage_vmapped" not in dispositions:
                dispositions.append("agg_stage_vmapped")
        elif isinstance(op, LookupJoinOperator):
            if op.join_type not in ("inner", "left", "semi", "anti") \
                    or op.filter_fn is not None or not seen_param:
                raise BatchIneligible("unsupported_stage")
            stages.append(("join", op))
            if "join_stage_vmapped" not in dispositions:
                dispositions.append("join_stage_vmapped")
        else:
            raise BatchIneligible("unsupported_stage")
    return aux, main[0], stages, dispositions


def check_params_consumed(fps: Sequence[FilterProjectOperator],
                          num_params: int):
    """Every literal slot of the shape must reach a compiled stage:
    an unconsumed slot would mean two members with different literals
    produce identical (wrong for one of them) results."""
    consumed = set()
    for fp in fps:
        consumed.update(fp.processor.param_indices)
    if consumed != set(range(num_params)):
        raise BatchIneligible("params_unconsumed")


def stack_bindings(fps: Sequence[FilterProjectOperator], param_types,
                   bindings: Sequence[Tuple]) -> List[Tuple]:
    """Per-stage stacked parameter tensors: for each stage, a tuple
    (one entry per consumed slot, in ``param_indices`` order) of
    ``(D,)`` arrays over the padded batch ``bindings`` (python literal
    values per global slot, one tuple per batch lane)."""
    out = []
    for fp in fps:
        idxs = fp.processor.param_indices
        out.append(tuple(
            np.stack([np.asarray(param_raw(param_types[i], vals[i]))
                      for vals in bindings])
            for i in idxs))
    return out


# ---------------------------------------------------------------------------
# the vmapped lane-kernel cache
#
# One jit(vmap(lane)) program per (kernel, value-config) pair, cached
# module-wide: a repeat burst replans the template into FRESH operator
# objects, so keying by operator identity would retrace every burst.
# Lane statics close over the factory args; runtime arrays (columns,
# LUTs, the shared build index) are traced operands.

_KERNEL_CACHE: Dict = {}


def _batched_kernel(name: str, cfg: Tuple, build_lane):
    key = (name, cfg)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:
        fn = instrument(name,
                        jax.jit(jax.vmap(build_lane(), in_axes=(0, None))),
                        key=key)
        _KERNEL_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# aggregation lanes


def _agg_group_lane(aggs: Tuple, key_channels: Tuple, key_types: Tuple,
                    key_pooled: Tuple, kinds: Tuple, str_state: Tuple,
                    hash_path: bool, intermediate: bool):
    """One lane of a masked GROUP BY page: the vmappable mirror of
    ``HashAggregationOperator._aggregate_page`` built from the raw
    kernel impls. Invalid rows hash to the sentinel slot (hash path)
    or sort last into the dump segment (sort path); pooled-key rank
    LUTs and string-state inverse LUTs arrive as traced operands so no
    host pool walk runs inside the trace.

    The segment reduce always runs the lax segment-op path
    (``pallas=""``): it is vmap-safe everywhere and byte-identical to
    the host path on CPU, where the batch-equality oracle runs."""
    nkeys = len(key_channels)

    def lane(batched, shared):
        cols, nulls, valid = batched
        key_luts, state_luts, inv_luts = shared
        state_cols: List = []
        if intermediate:
            idx, k = nkeys, 0
            for a in aggs:
                m = len(_state_plan(a))
                raws = [cols[idx + j] for j in range(m)]
                luts = [state_luts[k + j] for j in range(m)]
                idx += m
                k += m
                state_cols.extend(_merge_states(a, raws, valid,
                                                rank_luts=luts))
        else:
            k = 0
            for a in aggs:
                state_cols.extend(_init_states(a, cols, nulls, valid,
                                               rank_lut=state_luts[k]))
                k += len(_state_plan(a))
        key_ops: List = []
        key_raws: List = []
        for c, t, pooled, lut in zip(key_channels, key_types, key_pooled,
                                     key_luts):
            col = cols[c]
            if pooled:
                ops = group_operands(lut[col], nulls[c], T.BIGINT)
            else:
                ops = group_operands(col, nulls[c], t)
            key_ops.extend(ops)
            key_raws.append(col)
        key_nulls = tuple(nulls[c] for c in key_channels)
        if hash_path:
            gid, group_rows, ngroups, overflow = _hash_group_ids_impl(
                tuple(key_ops), valid, exact=True)
            out_keys, out_key_nulls, reduced, out_valid = \
                _hash_segment_reduce_impl(
                    gid, group_rows, ngroups, tuple(key_raws), key_nulls,
                    tuple(state_cols), kinds, pallas="")
        else:
            overflow = jnp.zeros((), dtype=bool)
            out_keys, out_key_nulls, reduced, out_valid = \
                _group_reduce_impl(
                    tuple(key_ops), tuple(key_raws), tuple(state_cols),
                    valid, num_keys=nkeys, num_states=len(state_cols),
                    kinds=kinds, pallas="")
        reduced = _ranks_to_codes(list(reduced), str_state, inv_luts)
        zero_null = jnp.zeros_like(out_valid)
        out_cols = tuple(out_keys) + tuple(reduced)
        out_nulls = tuple(jnp.asarray(n) for n in out_key_nulls) \
            + tuple(zero_null for _ in reduced)
        return out_cols, out_nulls, out_valid, overflow

    return lane


def _agg_finalize_lane(aggs: Tuple, nkeys: int):
    """One lane of ``HashAggregationOperator._finalize``: final
    projections over the merged intermediate layout."""

    def lane(batched, shared):
        del shared
        cols, nulls, valid = batched
        if nkeys == 0:
            # global aggregation emits exactly one row, even over zero
            # input rows (lane 0 then holds empty-input states)
            valid = valid | (jnp.arange(valid.shape[0]) == 0)
        out_cols = list(cols[:nkeys])
        out_nulls = list(nulls[:nkeys])
        idx = nkeys
        for a in aggs:
            m = len(_state_plan(a))
            states = [cols[idx + j] for j in range(m)]
            idx += m
            raw, null = _final_project(a, states)
            out_cols.append(raw.astype(a.output_type.storage))
            out_nulls.append(null | ~valid)
        return tuple(out_cols), tuple(out_nulls), valid

    return lane


def _agg_cfg(op: HashAggregationOperator) -> Tuple:
    """Value-level kernel-cache key for an aggregation stage (repr'd
    types keep the tuple hashable)."""
    return (tuple((a.function, a.arg_channel, repr(a.arg_type),
                   repr(a.output_type), a.distinct)
                  for a in op.aggregates),
            tuple(op.group_channels),
            tuple(repr(t) for t in op.input_types),
            op.hash_grouping)


class _AggAccumulator:
    """Barrier state of one vmapped aggregation stage: per-page masked
    partials accumulate, then merge + finalize once the scan drains —
    the stacked mirror of the serial partials list / ``_merge_partials``
    / ``_finalize`` walk, kept call-for-call equivalent so every lane
    is byte-equal to its serial oracle."""

    def __init__(self, op: HashAggregationOperator, depth: int):
        self.op = op
        self.depth = depth
        self.parts: List[Tuple] = []   # (cols, nulls, valid), (D, cap)
        self.caps: List[int] = []
        self.overflow = jnp.zeros((depth,), dtype=bool)
        key_types = [op.input_types[c] for c in op.group_channels]
        self.hash_path = op.hash_grouping and hashable_key_types(key_types)
        self.nkeys = len(op.group_channels)

    def _capture_dicts(self, page: "_BatchPage"):
        # mirrors add_input's capture; lanes share pages, so pools are
        # lane-invariant by construction (stability is asserted on the
        # serial path these same pages would take)
        op = self.op
        for i, c in enumerate(op.group_channels):
            d = page.dicts[c]
            if d is not None:
                op._group_dicts[i] = d
        k = 0
        for a in op.aggregates:
            for _ in _state_plan(a):
                if op._str_state[k]:
                    d = page.dicts[a.arg_channel]
                    if d is not None:
                        op._state_dicts[k] = d
                k += 1

    def _luts(self) -> Tuple:
        """(key_rank_luts, state_rank_luts, inverse_luts) as traced
        operands. Rank LUTs pad to pow2 (codes never index past the
        real pool, so padding is unread and the shape bucket is
        stable); inverse LUTs keep their EXACT pool length — the
        rank->code clamp bound must match the host path bit-for-bit."""
        op = self.op
        key_luts = []
        for i, c in enumerate(op.group_channels):
            if getattr(op.input_types[c], "is_pooled", False):
                rank, _ = _rank_and_inverse(op._group_dicts[i])
                key_luts.append(jnp.asarray(pad_lut(rank)))
            else:
                key_luts.append(None)
        state_luts: List = []
        inv_luts: List = []
        for k, is_str in enumerate(op._str_state):
            if is_str:
                rank, inv = _rank_and_inverse(op._state_dicts[k])
                state_luts.append(jnp.asarray(pad_lut(rank)))
                inv_luts.append(jnp.asarray(inv))
            else:
                state_luts.append(None)
                inv_luts.append(None)
        return tuple(key_luts), tuple(state_luts), tuple(inv_luts)

    def feed(self, page: "_BatchPage"):
        self._capture_dicts(page)
        op = self.op
        key_types = tuple(op.input_types[c] for c in op.group_channels)
        pooled = tuple(getattr(t, "is_pooled", False) for t in key_types)
        kern = _batched_kernel(
            "batched_agg_partial", ("partial", _agg_cfg(op), pooled),
            lambda: _agg_group_lane(
                tuple(op.aggregates), tuple(op.group_channels), key_types,
                pooled, op._kinds, tuple(op._str_state), self.hash_path,
                intermediate=False))
        out_cols, out_nulls, out_valid, overflow = kern(
            (page.cols, page.nulls, page.valid), self._luts())
        self.overflow = self.overflow | overflow
        self.parts.append((out_cols, out_nulls, out_valid))
        self.caps.append(int(out_valid.shape[-1]))

    def finalize(self) -> "_BatchPage":
        op = self.op
        types = op._intermediate_types()
        nkeys = self.nkeys
        for i in range(nkeys):
            # a scan that saw no input never captured key dictionaries;
            # string outputs still need (empty) pools
            if op._group_dicts[i] is None and types[i].is_pooled:
                op._group_dicts[i] = Dictionary()
        if not self.parts:
            # no input: zero groups — except global aggregation, which
            # emits one group of empty-input states (serial-identical
            # cap-16 zero page, broadcast across the batch)
            cap = 16
            cols = tuple(jnp.broadcast_to(jnp.zeros(cap, dtype=t.storage),
                                          (self.depth, cap))
                         for t in types)
            nulls = tuple(jnp.zeros((self.depth, cap), dtype=bool)
                          for _ in types)
            valid = jnp.zeros((self.depth, cap), dtype=bool)
            if nkeys == 0:
                valid = valid.at[:, 0].set(True)
            merged = (cols, nulls, valid)
        elif len(self.parts) == 1:
            # single partial: merged output IS the partial (the serial
            # path returns parts[0] unchanged for a non-partial step)
            merged = self.parts[0]
        else:
            total = sum(self.caps)
            # the serial merge concatenates at padded_size(total);
            # KERNEL_SIZING only ever grows the capacity, and a larger
            # table changes neither gid first-occurrence order nor the
            # reduced values — masked padding rows are dead lanes
            cap = KERNEL_SIZING.suggest(
                ("batched_agg_merge", _agg_cfg(op)), padded_size(total))
            ncols = len(self.parts[0][0])
            cols2, nulls2 = [], []
            for i in range(ncols):
                cols2.append(_pad_lanes(jnp.concatenate(
                    [p[0][i] for p in self.parts], axis=-1), cap))
                nulls2.append(_pad_lanes(jnp.concatenate(
                    [p[1][i] for p in self.parts], axis=-1), cap))
            valid = _pad_lanes(jnp.concatenate(
                [p[2] for p in self.parts], axis=-1), cap)
            inter_key_types = tuple(types[:nkeys])
            pooled = tuple(getattr(t, "is_pooled", False)
                           for t in inter_key_types)
            kern = _batched_kernel(
                "batched_agg_merge", ("merge", _agg_cfg(op), pooled),
                lambda: _agg_group_lane(
                    tuple(op.aggregates), tuple(range(nkeys)),
                    inter_key_types, pooled, op._kinds,
                    tuple(op._str_state), self.hash_path,
                    intermediate=True))
            out_cols, out_nulls, out_valid, overflow = kern(
                (tuple(cols2), tuple(nulls2), valid), self._luts())
            self.overflow = self.overflow | overflow
            merged = (out_cols, out_nulls, out_valid)
        fin = _batched_kernel(
            "batched_agg_finalize", ("finalize", _agg_cfg(op)),
            lambda: _agg_finalize_lane(tuple(op.aggregates), nkeys))
        f_cols, f_nulls, f_valid = fin(merged, None)
        agg_dicts = []
        k = 0
        for a in op.aggregates:
            agg_dicts.append(op._state_dicts[k]
                             if op._str_state[k] else None)
            k += len(_state_plan(a))
        dicts = list(op._group_dicts) + agg_dicts
        return _BatchPage(list(op.output_types), f_cols, f_nulls, f_valid,
                          dicts, True)


# ---------------------------------------------------------------------------
# join lanes


def _join_probe_lane(key_channels: Tuple, key_pooled: Tuple,
                     key_types: Tuple, key_mode: str):
    """One lane's candidate ranges against the SHARED sorted build
    index (build arrays broadcast via ``in_axes=None``). Pooled probe
    keys remap into the build's code space through the same LUT the
    serial ``_probe_key_cols`` builds; masked probe rows count 0."""

    def lane(batched, shared):
        cols, nulls, valid = batched
        remap_luts, bkeys, busable = shared
        pkey_cols = [remap_luts[i][cols[c]] if key_pooled[i] else cols[c]
                     for i, c in enumerate(key_channels)]
        pkey, panynull = _key_u64(
            pkey_cols, [nulls[c] for c in key_channels], list(key_types),
            key_mode)
        pusable = valid & ~panynull if panynull is not None else valid
        lo, count = _probe_counts_impl(bkeys, busable, pkey, pusable)
        return lo, count

    return lane


def _join_expand_lane(key_channels: Tuple, key_pooled: Tuple,
                      out_cap: int, left: bool):
    """One inner/left lane: expand candidates at the unified capacity,
    verify raw keys, gather the joined output (left appends the
    unmatched-probe lanes at the end, exactly like the serial path —
    output row order is capacity-independent, so a grown capacity
    stays byte-equal after compaction)."""

    def lane(batched, shared):
        cols, nulls, valid, lo, count = batched
        remap_luts, bkey_cols, bcols, bnulls = shared
        pkey_cols = [remap_luts[i][cols[c]] if key_pooled[i] else cols[c]
                     for i, c in enumerate(key_channels)]
        probe_idx, build_idx, keep = _expand_verified_impl(
            lo, count, tuple(pkey_cols), bkey_cols, out_cap=out_cap)
        return _finalize_join_impl(
            tuple(cols), tuple(nulls), valid, bcols, bnulls,
            probe_idx, build_idx, keep, left=left)

    return lane


def _join_semi_lane(key_channels: Tuple, key_pooled: Tuple, out_cap: int,
                    anti: bool):
    """One semi/anti lane: a pure mask update over the probe page."""

    def lane(batched, shared):
        cols, valid, lo, count = batched
        remap_luts, bkey_cols = shared
        pkey_cols = [remap_luts[i][cols[c]] if key_pooled[i] else cols[c]
                     for i, c in enumerate(key_channels)]
        matched = _semi_matched_impl(
            lo, count, tuple(pkey_cols), bkey_cols,
            probe_cap=valid.shape[0], out_cap=out_cap)
        return valid & ~matched if anti else valid & matched

    return lane


# ---------------------------------------------------------------------------
# the batched driver


@dataclass
class _BatchPage:
    """One page mid-pipeline: columns either shared (param-free prefix,
    1-D) or stacked over the batch axis (2-D, ``batched=True``)."""

    types: List
    cols: Tuple
    nulls: Tuple
    valid: "jax.Array"
    dicts: List
    batched: bool


def _pad_lanes(arr, cap: int):
    """Pad the row (last) axis to ``cap`` with zeros/False."""
    n = arr.shape[-1]
    if n == cap:
        return arr
    pad = jnp.zeros(arr.shape[:-1] + (cap - n,), dtype=arr.dtype)
    return jnp.concatenate([arr, pad], axis=-1)


def execute_batched(plan, param_types, bindings: Sequence[Tuple],
                    num_members: int) -> BatchResult:
    """Drive the plan with the whole padded batch in one launch per
    stage per scan page.

    ``bindings`` is the PADDED batch (length D >= num_members); result
    pages demux positionally for the first ``num_members`` lanes only.
    Returns host pages per member, byte-equal to running each member
    through the serial path (same raw kernels, same rawness — padding
    lanes compute and are discarded), plus the spilled-lane set, the
    stage dispositions, and the mask-popcount row actuals."""
    aux, scan, stages, dispositions = vmappable_stages(plan)
    fps = [op for kind, op in stages if kind == "fp"]
    check_params_consumed(fps, len(param_types))
    fp_params = iter(stack_bindings(fps, param_types, bindings))
    stage_params = [next(fp_params) if kind == "fp" else None
                    for kind, _op in stages]

    # the shared build side(s): literal-independent by template
    # construction (vmappable_stages proved the aux pipelines are
    # param-free), so ONE serial run serves every lane
    from .driver import Driver

    for p in aux:
        Driver(p.operators).run_to_completion()

    depth = len(bindings)
    spill = np.zeros(depth, dtype=bool)
    agg_accs: Dict[int, _AggAccumulator] = {
        k: _AggAccumulator(op, depth)
        for k, (kind, op) in enumerate(stages) if kind == "agg"}
    rows_acc: Dict[int, object] = {}
    scan_rows_acc: Optional[object] = None
    final: List[_BatchPage] = []

    def note_rows(k: int, op, valid):
        if getattr(op, "_hbo_fp", None) is None:
            return
        r = jnp.sum(valid, axis=-1) if valid.ndim == 2 \
            else jnp.full((depth,), jnp.sum(valid))
        rows_acc[k] = r if k not in rows_acc else rows_acc[k] + r

    def apply_fp(k: int, op, page: _BatchPage) -> _BatchPage:
        proc = op.processor
        params = stage_params[k]
        if not page.batched and not params:
            # param-free prefix stage: members are identical here —
            # one UNBATCHED launch shared by the whole burst
            dp = proc.process(DevicePage(list(page.types), list(page.cols),
                                         list(page.nulls), page.valid,
                                         list(page.dicts)))
            return _BatchPage(proc.output_types, tuple(dp.cols),
                              tuple(dp.nulls), dp.valid,
                              list(dp.dictionaries), False)
        mode = "carried" if page.batched else "shared"
        cols, nulls, valid, dicts = proc.process_batched(
            page.cols, page.nulls, page.valid, page.dicts, params or (),
            mode)
        return _BatchPage(proc.output_types, tuple(cols), tuple(nulls),
                          valid, list(dicts), True)

    def apply_join(k: int, op, page: _BatchPage) -> _BatchPage:
        b = op.bridge.build
        assert b is not None, "probe started before build finished"
        hs = getattr(op.bridge, "hybrid", None)
        if hs is not None and hs.spilled_build:
            # the vmapped probe only sees the resident index; a build
            # that went hybrid under memory pressure must not silently
            # drop its cold partitions — fail the batch loudly (the
            # caller re-runs lanes serially on lane_overflow fallbacks,
            # and batched templates never run memory-governed anyway)
            raise RuntimeError(
                "batched probe over a hybrid-spilled build")
        kc = tuple(op.probe_keys)
        pooled = tuple(op.probe_types[c].is_pooled for c in kc)
        key_types = tuple(T.BIGINT if p else op.probe_types[c]
                          for c, p in zip(kc, pooled))
        # probe-pool -> build-pool code remaps: host LUT work once per
        # pool pair (the operator caches it); padding is unread (codes
        # never index past the real pool)
        remap_luts = tuple(
            jnp.asarray(pad_lut(np.asarray(
                op._remap(page.dicts[c], b.dictionaries[bc]))))
            if p else None
            for c, bc, p in zip(kc, b.key_channels, pooled))
        cfg = (kc, pooled, tuple(repr(t) for t in key_types), b.key_mode)
        probe = _batched_kernel(
            "batched_join_probe", ("probe",) + cfg,
            lambda: _join_probe_lane(kc, pooled, key_types, b.key_mode))
        lo, count = probe((page.cols, page.nulls, page.valid),
                          (remap_luts, b.key_sorted, b.usable_sorted))
        # ONE deliberate host sync per probe page: the unified lane
        # capacity must be a static shape. Already-spilled lanes are
        # excluded so their (re-run serially anyway) fan-out cannot
        # inflate the shared capacity.
        totals = np.where(spill, 0, np.asarray(jnp.sum(count, axis=-1)))
        need = int(totals.max()) if totals.size else 16
        lane_cap = KERNEL_SIZING.suggest(
            ("batched_join_expand",) + cfg,
            max(min(need, op.max_lanes), 16))
        while lane_cap > op.max_lanes and lane_cap > 16:
            lane_cap >>= 1  # budget checked POST-padding, like every path
        over = totals > lane_cap
        if over.any():
            spill[:] = spill | over
        bkey_cols = tuple(b.cols[c] for c in b.key_channels)
        if op.join_type in ("semi", "anti"):
            kern = _batched_kernel(
                "batched_join_semi",
                ("semi", op.join_type, lane_cap) + cfg,
                lambda: _join_semi_lane(kc, pooled, lane_cap,
                                        op.join_type == "anti"))
            new_valid = kern((page.cols, page.valid, lo, count),
                             (remap_luts, bkey_cols))
            return _BatchPage(page.types, page.cols, page.nulls,
                              new_valid, page.dicts, True)
        left = op.join_type == "left"
        kern = _batched_kernel(
            "batched_join_expand", ("expand", left, lane_cap) + cfg,
            lambda: _join_expand_lane(kc, pooled, lane_cap, left))
        out_cols, out_nulls, out_valid = kern(
            (page.cols, page.nulls, page.valid, lo, count),
            (remap_luts, bkey_cols, b.cols, b.nulls))
        return _BatchPage(list(op.output_types), out_cols, out_nulls,
                          out_valid, list(page.dicts) + list(b.dictionaries),
                          True)

    def run_from(i: int, page: _BatchPage):
        for k in range(i, len(stages)):
            kind, op = stages[k]
            if kind == "fp":
                page = apply_fp(k, op, page)
            elif kind == "join":
                page = apply_join(k, op, page)
            else:
                agg_accs[k].feed(page)
                return
            note_rows(k, op, page.valid)
        if not page.batched:
            # cannot happen after check_params_consumed with
            # param_types non-empty; guard for the zero-literal case
            raise BatchIneligible("params_unconsumed")
        final.append(page)

    while True:
        dpage = scan.get_output()
        if dpage is None:
            if scan.is_finished():
                break
            continue
        cnt = jnp.sum(dpage.valid)
        scan_rows_acc = cnt if scan_rows_acc is None \
            else scan_rows_acc + cnt
        run_from(0, _BatchPage(list(dpage.types), tuple(dpage.cols),
                               tuple(dpage.nulls), dpage.valid,
                               list(dpage.dictionaries), False))

    # agg barriers drain in stage order: each finalize feeds the
    # remaining stages (which may include another barrier downstream)
    for k in sorted(agg_accs):
        acc = agg_accs[k]
        page = acc.finalize()
        spill[:] = spill | np.asarray(acc.overflow)
        note_rows(k, stages[k][1], page.valid)
        run_from(k + 1, page)

    spilled = {m for m in range(num_members) if spill[m]}
    out_pages: List[List[Page]] = [[] for _ in range(num_members)]
    for page in final:
        for m in range(num_members):
            if m in spilled:
                continue
            member = DevicePage(list(page.types),
                                [c[m] for c in page.cols],
                                [n[m] for n in page.nulls],
                                page.valid[m], list(page.dicts))
            host = member.to_page()
            if host.num_rows:
                out_pages[m].append(host)
    scan_rows = int(np.asarray(scan_rows_acc)) \
        if scan_rows_acc is not None else 0
    stage_rows = [
        {"fp": getattr(stages[k][1], "_hbo_fp", None),
         "name": type(stages[k][1]).__name__,
         "rows": np.asarray(rows_acc[k])}
        for k in sorted(rows_acc)]
    if getattr(scan, "_hbo_fp", None) is not None:
        # the shared scan is lane-invariant: every lane observed it
        stage_rows.insert(0, {"fp": scan._hbo_fp,
                              "name": type(scan).__name__,
                              "rows": np.full(depth, scan_rows)})
    return BatchResult(out_pages, spilled, dispositions, stage_rows,
                       scan_rows)
