"""Single-launch batched execution (round 16): run a same-shape
admission burst through ONE vmapped device launch per pipeline stage.

The serial batch path executes B same-shape statements as B separate
walks of the shared compiled programs — B launches per stage where the
programs differ only in the literal scalars they were called with.
With plan templates (``cache.PlanTemplate``) the literals are opaque
``ParamRef`` slots, so the per-stage program is ONE function of a
parameter vector; stacking the burst's literal vectors on a leading
``(B,)`` axis and ``vmap``-ing the stage (DrJAX-style lifting of the
map over statements into the compiled program) executes the whole
burst per scan page in a single launch, then demuxes member pages by
slicing the batch axis.

Eligibility here is narrower than template eligibility on purpose: a
template whose local plan is anything richer than
``scan -> filter/project* -> collect`` (joins, aggregations, limits,
exchanges) still EXECUTES correctly through the shared template
serially — zero retraces, B launches — it just doesn't vmap yet.
``BatchIneligible.reason`` feeds the fallback taxonomy counters either
way, so the gap is loud, not silent.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..block import DevicePage, Page
from ..expr.compiler import param_raw
from ..ops.operator import (FilterProjectOperator, OutputCollectorOperator,
                            TableScanOperator)


class BatchIneligible(Exception):
    """This plan/batch cannot ride the vmapped path; ``reason`` is one
    of the fallback-taxonomy tags documented in COMPONENTS.md."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def vmappable_stages(plan) -> Tuple[TableScanOperator,
                                    List[FilterProjectOperator]]:
    """The (scan, filter/project stages) of a plan that can batch, or
    raise ``BatchIneligible`` with the taxonomy reason."""
    if len(plan.pipelines) != 1:
        raise BatchIneligible("multi_pipeline")
    ops = plan.pipelines[0].operators
    if not ops or not isinstance(ops[0], TableScanOperator):
        raise BatchIneligible("no_scan_head")
    if not isinstance(ops[-1], OutputCollectorOperator):
        raise BatchIneligible("no_collect_tail")
    fps = ops[1:-1]
    if not all(isinstance(o, FilterProjectOperator) for o in fps):
        raise BatchIneligible("non_fp_stage")
    return ops[0], list(fps)


def check_params_consumed(fps: Sequence[FilterProjectOperator],
                          num_params: int):
    """Every literal slot of the shape must reach a compiled stage:
    an unconsumed slot would mean two members with different literals
    produce identical (wrong for one of them) results."""
    consumed = set()
    for fp in fps:
        consumed.update(fp.processor.param_indices)
    if consumed != set(range(num_params)):
        raise BatchIneligible("params_unconsumed")


def stack_bindings(fps: Sequence[FilterProjectOperator], param_types,
                   bindings: Sequence[Tuple]) -> List[Tuple]:
    """Per-stage stacked parameter tensors: for each stage, a tuple
    (one entry per consumed slot, in ``param_indices`` order) of
    ``(D,)`` arrays over the padded batch ``bindings`` (python literal
    values per global slot, one tuple per batch lane)."""
    out = []
    for fp in fps:
        idxs = fp.processor.param_indices
        out.append(tuple(
            np.stack([np.asarray(param_raw(param_types[i], vals[i]))
                      for vals in bindings])
            for i in idxs))
    return out


def execute_batched(plan, param_types, bindings: Sequence[Tuple],
                    num_members: int) -> List[List[Page]]:
    """Drive the plan's single scan->fp*->collect pipeline with the
    whole padded batch in one launch per stage per scan page.

    ``bindings`` is the PADDED batch (length D >= num_members); result
    pages demux positionally for the first ``num_members`` lanes only.
    Returns host pages per member, byte-equal to running each member
    through the serial path (same programs, same rawness — the padding
    lanes compute and are discarded)."""
    scan, fps = vmappable_stages(plan)
    check_params_consumed(fps, len(param_types))
    stage_params = stack_bindings(fps, param_types, bindings)
    out_pages: List[List[Page]] = [[] for _ in range(num_members)]
    while True:
        dpage = scan.get_output()
        if dpage is None:
            if scan.is_finished():
                break
            continue
        cols = tuple(dpage.cols)
        nulls = tuple(dpage.nulls)
        valid = dpage.valid
        dicts = dpage.dictionaries
        batched = False
        out_types = dpage.types
        for fp, params in zip(fps, stage_params):
            proc = fp.processor
            if not batched and not params:
                # param-free prefix stage: members are identical here —
                # one UNBATCHED launch shared by the whole burst
                dp = proc.process(DevicePage(list(out_types), list(cols),
                                             list(nulls), valid,
                                             list(dicts)))
                cols, nulls, valid = (tuple(dp.cols), tuple(dp.nulls),
                                      dp.valid)
                dicts = dp.dictionaries
            else:
                mode = "carried" if batched else "shared"
                cols, nulls, valid, dicts = proc.process_batched(
                    cols, nulls, valid, dicts, params, mode)
                batched = True
            out_types = proc.output_types
        if not batched:
            # cannot happen after check_params_consumed with
            # param_types non-empty; guard for the zero-literal case
            raise BatchIneligible("params_unconsumed")
        for b in range(num_members):
            member = DevicePage(
                list(out_types), [c[b] for c in cols],
                [n[b] for n in nulls], valid[b], list(dicts))
            host = member.to_page()
            if host.num_rows:
                out_pages[b].append(host)
    return out_pages
