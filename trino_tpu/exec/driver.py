"""Driver: the inner execution loop moving pages through an operator chain.

Reference analog: ``operator/Driver.java:380-486`` (processInternal) — walk
adjacent operator pairs, move one page per iteration, finish-propagate.
Synchronous for now; the task executor adds cooperative quanta on top
(reference: execution/executor/TaskExecutor.java).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from .. import jit_stats
from ..connectors.spi import ConnectorSplit
from ..telemetry import profiler
from ..ops.operator import Operator, SourceOperator


@dataclass
class OperatorStats:
    """Per-operator execution stats (reference:
    operator/OperatorStats.java — wall/cpu nanos, rows/pages in+out).
    ``compile_count`` is the number of jit traces (XLA cache misses)
    attributed to this operator's calls: after warmup it must stay flat
    for same-shape pages — silent retracing is the classic JAX perf
    bug, and this counter makes it assertable."""

    name: str
    output_rows: int = 0
    output_pages: int = 0
    wall_ns: int = 0
    compile_count: int = 0
    #: XLA cost attribution (telemetry.profiler thread deltas): flops /
    #: bytes accessed by this operator's compiled programs per
    #: execution, and the compile wall it paid — all zero unless the
    #: profiler was enabled (EXPLAIN ANALYZE VERBOSE, bench trace role)
    flops: float = 0.0
    device_bytes: float = 0.0
    compile_ms: float = 0.0
    #: perf_counter_ns of this operator's first/last active quantum —
    #: with the driver's ``epoch_anchor`` these place the operator on a
    #: cross-process trace timeline (telemetry.tracing.add_driver_spans)
    first_ns: int = 0
    last_ns: int = 0
    #: operator-reported metrics (exchange skew stats etc.), pulled from
    #: ``op.metrics()`` once the driver finishes — the OperatorStats
    #: analog of the reference's per-operator Metrics map
    metrics: Optional[dict] = None
    #: canonical plan-node fingerprint this operator realizes (set by
    #: the local planner when history-based statistics are recording;
    #: telemetry.stats_store keys actuals by it) — None outside HBO
    node_fp: Optional[str] = None

    def line(self) -> str:
        ms = self.wall_ns / 1e6
        base = (f"{self.name}: {self.output_rows} rows, "
                f"{self.output_pages} pages, {ms:.1f}ms, "
                f"{self.compile_count} compiles")
        if self.flops or self.device_bytes or self.compile_ms:
            base += (f" [cost {self.flops:.3g} flops, "
                     f"{self.device_bytes:.3g} bytes, "
                     f"compile {self.compile_ms:.1f}ms]")
        if self.metrics:
            m = self.metrics
            if m.get("strategy"):
                # kernel-strategy operators report what RAN (incl. a
                # fallback) plus the cost-model estimate that picked it
                base += f" [strategy {m['strategy']}"
                for k in ("estimate", "fallback", "key_range"):
                    if m.get(k):
                        base += f" {k}={m[k]!r}"
                base += "]"
            if m.get("adaptive"):
                # the adaptive partial-agg decision (pass-through or
                # per-key-range split) — no 'strategy' key on agg ops
                base += f" [adaptive {m['adaptive']}]"
            extras = " ".join(
                f"{k}={m[k]}" for k in ("skew_ratio", "lane_skew_ratio",
                                        "per_dest", "a2a_retries",
                                        "sizing", "first_page_ms")
                if m.get(k) is not None)
            # split/rebalance/replay counters only when the mechanism
            # engaged (a zero on every boundary would be noise)
            extras += "".join(
                f" {k}={m[k]}" for k in ("splits", "rebalances",
                                         "reconnects", "replayed_frames")
                if m.get(k))
            if extras:
                base += f" [exchange {extras}]"
        return base


class Driver:
    """Executes one operator chain to completion."""

    def __init__(self, operators: Sequence[Operator],
                 collect_stats: bool = False):
        assert operators, "empty pipeline"
        self.operators: List[Operator] = list(operators)
        self.collect_stats = collect_stats
        #: whether the most recent process() quantum moved any page —
        #: tasks only park on blocked tokens after a no-progress quantum
        self.last_moved = False
        self.stats: List[OperatorStats] = [
            OperatorStats(type(op).__name__,
                          node_fp=getattr(op, "_hbo_fp", None))
            for op in operators]
        #: (epoch seconds, perf_counter_ns) at driver creation: converts
        #: the stats' first_ns/last_ns to wall-clock span timestamps
        self.epoch_anchor = (time.time(), time.perf_counter_ns()) \
            if collect_stats else None

    @property
    def source(self) -> Optional[SourceOperator]:
        head = self.operators[0]
        return head if isinstance(head, SourceOperator) else None

    def add_split(self, split: ConnectorSplit):
        src = self.source
        assert src is not None, "pipeline has no source operator"
        src.add_split(split)

    def no_more_splits(self):
        src = self.source
        if src is not None:
            src.no_more_splits()

    def _timed_call(self, idx: int, fn):
        """Run one operator call attributing wall/compiles/activity to
        stats[idx] — the same attribution the page-move hot path does
        inline (finish propagation and tail drains can do real work:
        an aggregation's finish builds its output state)."""
        t0 = time.perf_counter_ns()
        c0 = jit_stats.thread_total()
        p0 = profiler.thread_totals()
        out = fn()
        t1 = time.perf_counter_ns()
        st = self.stats[idx]
        st.wall_ns += t1 - t0
        st.compile_count += jit_stats.thread_total() - c0
        self._attribute_cost(st, p0)
        if st.first_ns == 0:
            st.first_ns = t0
        st.last_ns = t1
        return out

    @staticmethod
    def _attribute_cost(st: OperatorStats, before):
        """Fold the profiler's thread-delta (flops/bytes/compile wall
        of programs run since ``before``) into the operator stats —
        zeros end to end unless profiling is enabled."""
        flops, bytes_, compile_ms, _ = profiler.thread_totals()
        st.flops += flops - before[0]
        st.device_bytes += bytes_ - before[1]
        st.compile_ms += compile_ms - before[2]

    def process(self) -> bool:
        """One scheduling quantum: move pages between adjacent operators.
        Returns True if the driver is fully finished."""
        ops = self.operators
        moved = False
        for i in range(len(ops) - 1):
            cur, nxt = ops[i], ops[i + 1]
            # finish propagation
            if cur.is_finished() and not nxt._finishing:
                if self.collect_stats:
                    self._timed_call(i + 1, nxt.finish)
                else:
                    nxt.finish()
            if nxt.needs_input():
                if self.collect_stats:
                    t0 = time.perf_counter_ns()
                    c0 = jit_stats.thread_total()
                    p0 = profiler.thread_totals()
                    page = cur.get_output()
                    t1 = time.perf_counter_ns()
                    st = self.stats[i]
                    st.wall_ns += t1 - t0
                    st.compile_count += jit_stats.thread_total() - c0
                    self._attribute_cost(st, p0)
                    if st.first_ns == 0:
                        st.first_ns = t0
                    st.last_ns = t1
                    if page is not None:
                        st.output_pages += 1
                        st.output_rows += page.count()
                else:
                    page = cur.get_output()
                if page is not None:
                    if self.collect_stats:
                        t0 = time.perf_counter_ns()
                        c0 = jit_stats.thread_total()
                        p0 = profiler.thread_totals()
                        nxt.add_input(page)
                        t1 = time.perf_counter_ns()
                        st1 = self.stats[i + 1]
                        st1.wall_ns += t1 - t0
                        st1.compile_count += jit_stats.thread_total() - c0
                        self._attribute_cost(st1, p0)
                        if st1.first_ns == 0:
                            st1.first_ns = t0
                        st1.last_ns = t1
                    else:
                        nxt.add_input(page)
                    moved = True
        # drain the tail operator (sinks produce no output)
        if self.collect_stats:
            self._timed_call(len(ops) - 1, ops[-1].get_output)
        else:
            ops[-1].get_output()
        if not moved:
            # nothing moved: push finish from the head if it is done
            if ops[0].is_finished() and not ops[0]._finishing:
                if self.collect_stats:
                    self._timed_call(0, ops[0].finish)
                else:
                    ops[0].finish()
        self.last_moved = moved
        return ops[-1].is_finished()

    def collect_operator_metrics(self):
        """Pull per-operator metrics (exchange skew stats etc.) into the
        stats entries. Call after the driver finished: exchange sources
        only know their stats once the upstream collective ran."""
        for op, st in zip(self.operators, self.stats):
            m = getattr(op, "metrics", None)
            if callable(m):
                got = m()
                if got:
                    st.metrics = dict(got)
            # per-operator memory high-water mark (the context's peak
            # survives close()) — history-based statistics record it
            ctx = getattr(op, "_ctx", None)
            peak = getattr(ctx, "peak", 0) if ctx is not None else 0
            if peak:
                st.metrics = dict(st.metrics or {}, peak_bytes=peak)

    def blocked_tokens(self) -> List:
        """Listen tokens of currently-blocked operators. Meaningful
        after a ``process()`` quantum that made no progress: the task
        parks on these instead of spinning (reference:
        Driver.java:380-486 blocked-future handling)."""
        toks = []
        for op in self.operators:
            t = op.blocked_token()
            if t is not None:
                toks.append(t)
        return toks

    def run_to_completion(self, max_quanta: int = 1_000_000):
        for _ in range(max_quanta):
            if self.process():
                return
        raise RuntimeError("driver did not finish (stuck pipeline?)")


class Pipeline:
    """A driver factory: operator constructors for one pipeline of a task
    (reference analog: DriverFactory from LocalExecutionPlanner)."""

    def __init__(self, make_operators, is_source: bool = True):
        self._make = make_operators
        self.is_source = is_source

    def create_driver(self) -> Driver:
        return Driver(self._make())
