"""Dynamic filtering: build-side key domains pruning probe-side scans.

Reference analog: ``server/DynamicFilterService.java:107,278`` +
``operator/DynamicFilterSourceOperator.java`` + the ``TupleDomain``
predicate model (``spi/predicate/``).  There, build-side values stream to
a coordinator service and reach probe scans as TupleDomains; here the
planner links the two sides directly: the join build publishes its key
domain (min/max + a sorted value set when small) into a ``DynamicFilter``
that the probe-side TableScan applies to every page BEFORE rows enter
the pipeline.

TPU-first details: the scan applies the domain as a lane-mask update (no
compaction, no host sync — pruned-row counts accumulate in a device
scalar read once at query end), and the value-set membership test is a
``searchsorted`` + equality over a padded sorted array, the same
XLA-native binary-search idiom the join probe uses.

Scheduling guarantee: pipelines of a task run build-before-probe (the
physical planner sequences them), so the filter is complete before the
first probe page is scanned — the engine-level analog of Trino's
"wait for dynamic filters" scan blocking.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..block import padded_size

#: value sets larger than this keep only min/max (reference analog:
#: dynamic-filtering.small.max-distinct-values-per-driver)
MAX_VALUE_SET = 1 << 17


class DynamicFilter:
    """Domain of one join-key column, filled at build publish."""

    def __init__(self, label: str = ""):
        self.label = label
        self.ready = False
        self.allow_nan = False     # build side had NaN float keys
        self.lo = None             # numpy scalar in the key's storage dtype
        self.hi = None
        self._values: Optional[np.ndarray] = None  # sorted unique, padded
        self._values_dev = None
        self._pruned_dev = None    # lazy device accumulator (no hot sync)
        self._seen_dev = None
        self.build_rows = 0

    # -- build side -----------------------------------------------------

    def collect(self, col, nulls, valid):
        """Collect the domain from build-side device arrays (called once
        at HashBuilder publish; one device->host transfer)."""
        import jax.numpy as jnp

        live = np.asarray(valid & ~nulls)
        vals = np.asarray(col)[live]
        self.build_rows = int(vals.shape[0])
        if np.issubdtype(vals.dtype, np.floating):
            # NaN build keys: np.unique sorts NaN last, so hi would be
            # NaN and `col <= hi` would prune EVERYTHING.  The engine
            # treats NaN as joinable with itself (sortkeys tags NaN
            # groups), so drop NaNs from the domain and pass NaN probe
            # lanes through.
            nan_mask = np.isnan(vals)
            self.allow_nan = bool(nan_mask.any())
            vals = vals[~nan_mask]
        if vals.shape[0] == 0:
            # no (finite) build keys: range matches nothing; NaN lanes
            # still pass when the build had NaN keys
            self.lo, self.hi = np.int64(1), np.int64(0)
            self.ready = True
            return
        uniq = np.unique(vals)
        self.lo, self.hi = uniq[0], uniq[-1]
        if uniq.shape[0] <= MAX_VALUE_SET:
            cap = padded_size(int(uniq.shape[0]))
            padded = np.full(cap, uniq[-1], dtype=uniq.dtype)
            padded[:uniq.shape[0]] = uniq
            self._values = padded
            self._values_dev = jnp.asarray(padded)
        self.ready = True

    # -- probe side -----------------------------------------------------

    def apply(self, col, nulls, valid):
        """valid-mask update for one scanned page (device, no sync)."""
        import jax.numpy as jnp

        if not self.ready:
            return valid
        if self.lo > self.hi:  # no finite build keys
            keep = jnp.zeros_like(valid)
        else:
            keep = valid & ~nulls & \
                (col >= jnp.asarray(self.lo, dtype=col.dtype)) & \
                (col <= jnp.asarray(self.hi, dtype=col.dtype))
            if self._values_dev is not None:
                vs = self._values_dev.astype(col.dtype)
                idx = jnp.clip(jnp.searchsorted(vs, col), 0,
                               vs.shape[0] - 1)
                keep = keep & (vs[idx] == col)
        if self.allow_nan:
            keep = keep | (valid & ~nulls & jnp.isnan(col))
        pruned = jnp.sum((valid & ~keep).astype(jnp.int64))
        seen = jnp.sum(valid.astype(jnp.int64))
        self._pruned_dev = pruned if self._pruned_dev is None \
            else self._pruned_dev + pruned
        self._seen_dev = seen if self._seen_dev is None \
            else self._seen_dev + seen
        return keep

    def to_domain(self):
        """The collected build-side key domain as a ``predicate.Domain``
        — the engine's TupleDomain interop form (reference:
        DynamicFilterService handing TupleDomains to connector scans).
        NaN admission can't be expressed as a range and stays a device-
        side flag; the device ``apply`` path remains the enforcement."""
        from ..predicate import Domain, Range, ValueSet

        if not self.ready:
            return Domain.all_()
        if self.lo > self.hi:  # no finite build keys
            return Domain.none()
        if self._values is not None and self._values.shape[0] <= 1024:
            uniq = np.unique(self._values)
            return Domain(ValueSet.of(*(v.item() for v in uniq)), False)
        return Domain(ValueSet.of_ranges(
            Range(self.lo.item(), True, self.hi.item(), True)), False)

    # -- observability ---------------------------------------------------

    @property
    def pruned_rows(self) -> int:
        return 0 if self._pruned_dev is None else int(self._pruned_dev)

    @property
    def scanned_rows(self) -> int:
        return 0 if self._seen_dev is None else int(self._seen_dev)

    def stats(self) -> dict:
        return {
            "filter": self.label,
            "ready": self.ready,
            "build_rows": self.build_rows,
            "scanned_rows": self.scanned_rows,
            "pruned_rows": self.pruned_rows,
            "has_value_set": self._values is not None,
        }


def resolve_scan_column(node, symbol_name: str):
    """Walk a probe-side plan subtree to the TableScan column feeding
    ``symbol_name``, through renaming projections, filters, limits, and
    probe sides of nested joins (reference analog: the source-symbol
    walk in ``DynamicFilterService.getSourceSymbol``).  Returns
    ``(scan_node, channel)`` or None when the symbol is computed or
    crosses a pipeline boundary (union, aggregation, remote source)."""
    from ..planner.plan import (CrossJoinNode, FilterNode, JoinNode,
                                ProjectNode, SortNode, TableScanNode)
    from ..planner.symbols import SymbolRef

    name = symbol_name
    while True:
        if isinstance(node, TableScanNode):
            for pos, (s, _c) in enumerate(node.assignments):
                if s.name == name:
                    return node, pos
            return None
        # NOTE: Limit/TopN are NOT transparent — pruning below a LIMIT
        # changes which rows it selects.  Sort alone is row-preserving.
        if isinstance(node, (FilterNode, SortNode)):
            node = node.source
            continue
        if isinstance(node, ProjectNode):
            expr = None
            for s, e in node.assignments:
                if s.name == name:
                    expr = e
                    break
            if not isinstance(expr, SymbolRef):
                return None
            name = expr.name
            node = node.source
            continue
        if isinstance(node, (JoinNode, CrossJoinNode)):
            # probe-side symbols pass through the join unchanged; build
            # symbols won't resolve below and fall out as None
            node = node.left
            continue
        return None


def plan_dynamic_filters(planner, left_node, criteria, join_type: str
                         ) -> List[Tuple[object, DynamicFilter]]:
    """Register a DynamicFilter per eligible equi-clause: returns
    [(build_symbol, filter)] and records the probe-scan attachment in
    ``planner._scan_dfs``.  Inner and semi joins only: LEFT/ANTI probes
    must keep unmatched rows."""
    out: List[Tuple[object, DynamicFilter]] = []
    if join_type not in ("inner", "semi") or not criteria:
        return out
    for lsym, rsym in criteria:
        if lsym.type.is_string or rsym.type.is_string:
            continue  # string keys join via dictionary codes; pools differ
        target = resolve_scan_column(left_node, lsym.name)
        if target is None:
            continue
        scan_node, pos = target
        df = DynamicFilter(label=f"{lsym.name}<-{rsym.name}")
        planner._scan_dfs.setdefault(id(scan_node), []).append((pos, df))
        planner.dynamic_filters.append(df)
        out.append((rsym, df))
    return out
