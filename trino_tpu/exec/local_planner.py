"""Local execution planner: PlanNode tree -> operator pipelines.

Reference analog: ``sql/planner/LocalExecutionPlanner.java`` (4,405 LoC):
the visitor that turns a plan fragment into DriverFactories, fixing the
physical channel layout of every pipeline and compiling expressions. Here
a plan compiles to an ordered list of Drivers (join build sides and union
inputs run before their consumers — the reference sequences these through
pipeline dependencies and JoinBridges, same idea).
"""

from __future__ import annotations

from decimal import Decimal
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..block import Page
from ..expr.compiler import PageProcessor
from ..expr.ir import Call, InputRef, Literal, RowExpression
from ..ops.aggregation import (ADAPTIVE_KEY_BUCKETS, ADAPTIVE_MIN_ROWS,
                               ADAPTIVE_RATIO_THRESHOLD, AggCall,
                               HashAggregationOperator)
from ..ops.join import HashBuilderOperator, JoinBridge, LookupJoinOperator
from ..ops.matmul_join import MatmulJoinOperator
from ..ops.operator import (DeferredPagesSourceOperator,
                            EnforceSingleRowOperator, FilterProjectOperator,
                            LimitOperator, OffsetOperator, Operator,
                            OutputCollectorOperator, TableScanOperator,
                            ValuesOperator)
from ..ops.sort import OrderByOperator, TopNOperator
from ..ops.sortkeys import SortKey
from ..planner.logical_planner import Metadata
from ..planner.plan import (AggregationNode, CrossJoinNode, DistinctNode,
                            EnforceSingleRowNode, ExceptNode, FilterNode,
                            IntersectNode, JoinNode, LimitNode, OutputNode,
                            PlanNode, ProjectNode, SortNode, TableScanNode,
                            TopNNode, UnionNode, ValuesNode)
from ..planner.symbols import Symbol, to_input_refs
from ..types import TrinoError


def create_table_idempotent(conn, schema: str, table: str, columns):
    """Execution-time CTAS create that tolerates losing the race to a
    sibling writer task (the analyzer already rejected genuinely
    pre-existing targets)."""
    try:
        return conn.metadata().create_table(schema, table, columns)
    except TrinoError as e:
        if e.code != "TABLE_ALREADY_EXISTS":
            raise
        return conn.metadata().get_table_handle(schema, table)


def grouping_options(props: Dict) -> Dict:
    """LocalExecutionPlanner grouping/kernel kwargs from a raw
    session-properties mapping, with registered defaults applied — the
    ONE place the property names map to planner knobs (every runner
    builds its planners through this, so the sites cannot drift)."""
    from .. import session_properties as SP

    return {
        "hash_grouping": SP.prop_value(props, "hash_grouping_enabled"),
        "adaptive_partial_agg": SP.prop_value(
            props, "adaptive_partial_aggregation_enabled"),
        "adaptive_partial_ratio": SP.prop_value(
            props,
            "adaptive_partial_aggregation_unique_rows_ratio_threshold"),
        "adaptive_partial_min_rows": SP.prop_value(
            props, "adaptive_partial_aggregation_min_rows"),
        "adaptive_partial_buckets": SP.prop_value(
            props, "adaptive_partial_aggregation_key_range_buckets"),
        "matmul_max_key_range": SP.prop_value(
            props, "matmul_join_max_key_range"),
        "hybrid_join": SP.prop_value(props, "hybrid_join_enabled"),
        "hybrid_join_fanout": SP.prop_value(
            props, "hybrid_join_fanout"),
        "hybrid_join_max_depth": SP.prop_value(
            props, "hybrid_join_max_depth"),
    }


class PhysicalPipeline:
    """One operator chain; drivers run pipelines in list order (upstream
    build/union pipelines first)."""

    def __init__(self, operators: List[Operator]):
        self.operators = operators


class LocalExecutionPlan:
    def __init__(self, pipelines: List[PhysicalPipeline],
                 sink: OutputCollectorOperator,
                 column_names: List[str], output_types: List[T.Type],
                 progress=None):
        self.pipelines = pipelines
        self.sink = sink
        self.column_names = column_names
        self.output_types = output_types
        #: telemetry.progress.QueryProgress fed live task counts
        self.progress = progress

    def execute(self, collect_stats: bool = False) -> List[Page]:
        from .driver import Driver

        self.drivers = []
        p_ = self.progress
        if p_ is not None:
            p_.tasks_total = len(self.pipelines)
        for p in self.pipelines:
            d = Driver(p.operators, collect_stats=collect_stats)
            self.drivers.append(d)
            if p_ is not None:
                p_.task_started()
            try:
                d.run_to_completion()
            finally:
                if p_ is not None:
                    p_.task_finished()
        return self.sink.pages


class LocalExecutionPlanner:
    """``task_id``/``task_count`` assign a subset of table splits to this
    task (reference: split assignment in SqlTaskExecution);
    ``exchange_reader(fragment_id, kind) -> thunk`` resolves
    RemoteSourceNodes to upstream fragment output pages."""

    def __init__(self, metadata: Metadata, desired_splits: int = 4,
                 task_id: int = 0, task_count: int = 1,
                 exchange_reader=None, memory_pool=None,
                 join_max_lanes: Optional[int] = None,
                 dynamic_filtering: bool = True,
                 page_sink_factory=None,
                 hash_grouping: bool = True,
                 scan_coalesce: bool = True,
                 adaptive_partial_agg: bool = True,
                 adaptive_partial_ratio: float = ADAPTIVE_RATIO_THRESHOLD,
                 adaptive_partial_min_rows: int = ADAPTIVE_MIN_ROWS,
                 adaptive_partial_buckets: int = ADAPTIVE_KEY_BUCKETS,
                 matmul_max_key_range: int = 1024,
                 hybrid_join: bool = True,
                 hybrid_join_fanout: int = 0,
                 hybrid_join_max_depth: int = 3,
                 processor_cache=None, progress=None, hbo=None,
                 params=None):
        self.metadata = metadata
        self.desired_splits = desired_splits
        self.task_id = task_id
        self.task_count = task_count
        self.exchange_reader = exchange_reader
        self.memory_pool = memory_pool
        #: coalesce split-tail scan pages up to the connector page size
        #: before device upload (``scan_coalesce_enabled``)
        self.scan_coalesce = scan_coalesce
        self.join_max_lanes = join_max_lanes
        self.dynamic_filtering = dynamic_filtering
        #: GROUP BY path: vectorized open-addressing hash table (default)
        #: vs sort-based oracle (``hash_grouping_enabled`` session prop)
        self.hash_grouping = hash_grouping
        self.adaptive_partial_agg = adaptive_partial_agg
        self.adaptive_partial_ratio = adaptive_partial_ratio
        self.adaptive_partial_min_rows = adaptive_partial_min_rows
        self.adaptive_partial_buckets = adaptive_partial_buckets
        #: densest key domain the matmul join strategy may one-hot
        #: encode (``matmul_join_max_key_range``) — the operator's
        #: runtime re-check of the cost model's range estimate
        self.matmul_max_key_range = matmul_max_key_range
        #: dynamic hybrid hash join knobs (``hybrid_join_*`` session
        #: properties): graceful build degradation under memory pressure
        self.hybrid_join = hybrid_join
        self.hybrid_join_fanout = hybrid_join_fanout
        self.hybrid_join_max_depth = hybrid_join_max_depth
        #: override for write sinks: ``factory(TableWriterNode) -> sink``
        #: — the multi-process runtime routes worker writes to the
        #: coordinator's catalog through this (page-sink RPC)
        self.page_sink_factory = page_sink_factory
        #: shared compiled-PageProcessor cache (cache.ProcessorCache):
        #: repeat plans land on already-traced jit programs instead of
        #: re-tracing every expression per submission; None = build
        #: fresh per plan (the pre-cache behavior)
        self.processor_cache = processor_cache
        #: live progress tracker (telemetry.progress.QueryProgress):
        #: table scans feed rows_scanned, the plan feeds task counts
        self.progress = progress
        #: history-based statistics binding
        #: (telemetry.stats_store.HboContext): when set, every plan
        #: node's realizing operator is tagged with its canonical
        #: fingerprint (actuals recording) and partial aggregations
        #: seed their adaptive verdicts from recorded history
        self.hbo = hbo
        #: template-parameter bindings (round 16): GLOBAL literal-slot
        #: index -> raw device scalar.  A template plan's IR carries
        #: opaque ParamRefs; this map binds them for ONE statement so
        #: the shared compiled programs run without retracing.  None/{}
        #: for ordinary (literal-baked) plans.
        self._params = dict(params or {})
        self.pipelines: List[PhysicalPipeline] = []
        # scan-node id -> [(channel, DynamicFilter)] attachments
        self._scan_dfs: Dict[int, List] = {}
        self.dynamic_filters: List = []  # all filters, for query stats

    def _processor(self, input_types, projections,
                   filter_expr=None) -> PageProcessor:
        """Every PageProcessor this planner builds comes through here so
        the shared-processor cache can intercept: the IR is frozen
        dataclasses, so (types, projections, filter) IS the program."""
        if self.processor_cache is not None:
            return self.processor_cache.get(input_types, projections,
                                            filter_expr)
        return PageProcessor(list(input_types), list(projections),
                             filter_expr)

    def _params_for(self, proc: PageProcessor) -> tuple:
        """This statement's raw bindings for the slots ``proc``
        consumes, in ``proc.param_indices`` order (a missing binding is
        a planner bug: the template/member contract guarantees the full
        literal vector)."""
        if not proc.param_indices:
            return ()
        return tuple(self._params[i] for i in proc.param_indices)

    def _fp_operator(self, input_types, projections,
                     filter_expr=None) -> FilterProjectOperator:
        proc = self._processor(input_types, projections, filter_expr)
        return FilterProjectOperator(proc, self._params_for(proc))

    def _mem_ctx(self, name: str):
        if self.memory_pool is None:
            return None
        return self.memory_pool.create_context(name)

    def _memory_constrained(self) -> bool:
        """True when the query runs under active memory pressure
        management (spill on): the join probe then keeps its
        one-page-in-flight footprint, since its pending buffers are
        invisible to the pool's reserve/revoke machinery."""
        return self.memory_pool is not None \
            and self.memory_pool.spill_enabled

    def plan(self, root: OutputNode) -> LocalExecutionPlan:
        ops, layout, types_ = self.visit(root.source)
        # final projection into output order
        projections = [InputRef(s.type, layout[s.name])
                       for s in root.outputs]
        if [p.channel for p in projections] != list(range(len(types_))) or \
                len(projections) != len(types_):
            ops.append(self._fp_operator(types_, projections))
        sink = OutputCollectorOperator()
        ops.append(sink)
        self.pipelines.append(PhysicalPipeline(ops))
        return LocalExecutionPlan(
            self.pipelines, sink, root.column_names,
            [s.type for s in root.outputs], progress=self.progress)

    # ------------------------------------------------------------------

    def visit(self, node: PlanNode
              ) -> Tuple[List[Operator], Dict[str, int], List[T.Type]]:
        m = getattr(self, "_v_" + type(node).__name__, None)
        if m is None:
            raise TrinoError(
                f"no local planning for {type(node).__name__}",
                "NOT_SUPPORTED")
        out = m(node)
        if self.hbo is not None and out[0]:
            # the tail operator realizes this node's output: tag it
            # with the canonical fingerprint so the driver's stats can
            # be keyed back to the plan node (a node that adds no
            # operator re-tags its child's tail — same output stream,
            # so the actual is identical either way)
            out[0][-1]._hbo_fp = self.hbo.fp(node)
        return out

    def _v_TableScanNode(self, node: TableScanNode):
        conn = self.metadata.connectors[node.catalog]
        columns = [c for _, c in node.assignments]
        scan = TableScanOperator(conn, columns,
                                 dynamic_filters=self._scan_dfs.pop(
                                     id(node), []),
                                 coalesce_rows=getattr(
                                     conn, "page_rows", None)
                                 if self.scan_coalesce else None,
                                 progress=self.progress)
        splits = conn.split_manager().get_splits(node.table,
                                                 self.desired_splits)
        for i, split in enumerate(splits):
            if i % self.task_count == self.task_id:
                scan.add_split(split)
        scan.no_more_splits()
        layout = {s.name: i for i, (s, _) in enumerate(node.assignments)}
        types_ = [s.type for s, _ in node.assignments]
        return [scan], layout, types_

    def _v_ValuesNode(self, node: ValuesNode):
        types_ = [s.type for s in node.symbols]
        columns: List[List] = [[] for _ in node.symbols]
        for row in node.rows:
            for i, e in enumerate(row):
                columns[i].append(_eval_literal(e))
        if not node.symbols:
            # single empty row (SELECT without FROM)
            page = Page.from_pylists([], [])
            page.num_rows = max(1, len(node.rows))
            pages = [page]
        else:
            pages = [Page.from_pylists(types_, columns)]
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        return [ValuesOperator(pages)], layout, types_

    def _v_FilterNode(self, node: FilterNode):
        ops, layout, types_ = self.visit(node.source)
        pred = to_input_refs(node.predicate, layout)
        projections = [InputRef(t, i) for i, t in enumerate(types_)]
        ops.append(self._fp_operator(types_, projections, pred))
        return ops, layout, types_

    def _v_ProjectNode(self, node: ProjectNode):
        ops, layout, types_ = self.visit(node.source)
        projections = [to_input_refs(e, layout) for _, e in node.assignments]
        ops.append(self._fp_operator(types_, projections))
        new_layout = {s.name: i for i, (s, _) in enumerate(node.assignments)}
        return ops, new_layout, [s.type for s, _ in node.assignments]

    def _v_UnnestNode(self, node):
        from ..ops.unnest import UnnestOperator

        ops, layout, types_ = self.visit(node.source)
        arr_chans = [layout[s.name] for s in node.array_symbols]
        el_types = [s.type for s in node.element_symbols]
        ops.append(UnnestOperator(types_, arr_chans, el_types,
                                  node.ordinality_symbol is not None))
        out_layout = dict(layout)
        out_types = list(types_)
        extra = list(node.element_symbols)
        if node.ordinality_symbol is not None:
            extra.append(node.ordinality_symbol)
        for s in extra:
            out_layout[s.name] = len(out_types)
            out_types.append(s.type)
        return ops, out_layout, out_types

    def _v_JoinNode(self, node: JoinNode):
        return self._plan_join(node.join_type, node.left, node.right,
                               node.criteria, node.filter_expr,
                               node.strategy, node.strategy_detail,
                               node=node)

    def _v_CrossJoinNode(self, node: CrossJoinNode):
        # const-key equi join (build side replicated once)
        return self._plan_join("inner", node.left, node.right, [],
                               None)

    def _hybrid_opts(self, join_type: str, node=None) -> Optional[Dict]:
        """HashBuilderOperator ``hybrid`` options, or None when hybrid
        degradation is off.  FULL OUTER stays wholesale: its unmatched-
        build tail needs the complete index in one piece.  The hint is
        the HBO spill record of this node's previous run — the stamped
        ``hybrid_hint`` when the optimizer annotated one (multi-process
        workers plan from shipped fragments and re-read it here), else
        a direct store lookup."""
        if not self.hybrid_join or join_type == "full":
            return None
        hint = getattr(node, "hybrid_hint", None) if node is not None \
            else None
        if hint is None and node is not None and self.hbo is not None:
            hint = self.hbo.spill_hint(self.hbo.fp(node))
        return {"fanout": self.hybrid_join_fanout,
                "max_depth": self.hybrid_join_max_depth,
                "hint": hint}

    def _plan_join(self, join_type: str, left: PlanNode, right: PlanNode,
                   criteria: List[Tuple[Symbol, Symbol]],
                   filter_expr: Optional[RowExpression],
                   strategy: str = "sorted-index",
                   strategy_detail: str = "", node=None):
        build_dfs = []
        if self.dynamic_filtering:
            from .dynamic_filter import plan_dynamic_filters

            # register BEFORE visiting the probe side so its TableScan
            # picks the filters up; the build pipeline runs first, so
            # domains are complete before the first probe page scans
            build_dfs = plan_dynamic_filters(self, left, criteria,
                                             join_type)
        bops, blayout, btypes = self.visit(right)
        pops, playout, ptypes = self.visit(left)

        const_key = not criteria
        if const_key:
            # append literal-0 key channel to both sides
            bops.append(FilterProjectOperator(self._processor(
                btypes, [InputRef(t, i) for i, t in enumerate(btypes)]
                + [Literal(T.BIGINT, 0)])))
            btypes = btypes + [T.BIGINT]
            pops.append(FilterProjectOperator(self._processor(
                ptypes, [InputRef(t, i) for i, t in enumerate(ptypes)]
                + [Literal(T.BIGINT, 0)])))
            ptypes = ptypes + [T.BIGINT]
            build_keys = [len(btypes) - 1]
            probe_keys = [len(ptypes) - 1]
        else:
            build_keys = []
            probe_keys = []
            for lsym, rsym in criteria:
                # string keys are fine: the probe remaps its dictionary
                # codes into the build's pool (LookupJoinOperator._remap)
                probe_keys.append(playout[lsym.name])
                build_keys.append(blayout[rsym.name])

        bridge = JoinBridge()
        builder = HashBuilderOperator(
            btypes, build_keys, bridge,
            memory_context=self._mem_ctx("join-build"),
            dynamic_filters=[(blayout[rs.name], df)
                             for rs, df in build_dfs],
            hybrid=self._hybrid_opts(join_type, node))
        if self.hbo is not None and node is not None:
            # the builder shares the join node's fingerprint (its
            # output_rows are 0, so the row actual is untouched); its
            # hybrid_spill metric is what spill_hint() serves next run
            builder._hbo_fp = self.hbo.fp(node)
        bops.append(builder)
        self.pipelines.append(PhysicalPipeline(bops))

        filter_fn = None
        if filter_expr is not None:
            combined_layout = dict(playout)
            for name, ch in blayout.items():
                combined_layout[name] = len(ptypes) + ch
            combined_types = ptypes + btypes
            pred = to_input_refs(filter_expr, combined_layout)
            proc = self._processor(
                combined_types,
                [InputRef(t, i) for i, t in enumerate(combined_types)],
                pred)
            jparams = self._params_for(proc)

            def filter_fn(dp, _proc=proc, _params=jparams):
                return _proc.process(dp, _params)

        if strategy == "matmul":
            # the cost model picked the blocked one-hot matmul probe;
            # the operator re-checks the actual key range per build and
            # falls back to the sorted index (reason in its metrics)
            pops.append(MatmulJoinOperator(
                ptypes, probe_keys, bridge, join_type, filter_fn,
                max_lanes=self.join_max_lanes,
                memory_limited=self._memory_constrained(),
                max_key_range=self.matmul_max_key_range,
                strategy_detail=strategy_detail))
        else:
            pops.append(LookupJoinOperator(
                ptypes, probe_keys, bridge, join_type, filter_fn,
                max_lanes=self.join_max_lanes,
                memory_limited=self._memory_constrained()))
        if join_type in ("semi", "anti"):
            out_layout = dict(playout)
            out_types = ptypes
        else:
            out_layout = dict(playout)
            for name, ch in blayout.items():
                out_layout[name] = len(ptypes) + ch
            out_types = ptypes + btypes
        return pops, out_layout, out_types

    def _v_AggregationNode(self, node: AggregationNode):
        ops, layout, types_ = self.visit(node.source)
        group_channels = [layout[s.name] for s in node.group_keys]
        aggs = []
        for out_sym, a in node.aggregations:
            if a.distinct:
                raise TrinoError(
                    "DISTINCT aggregates execute via the planner rewrite; "
                    "this one was not rewritten", "NOT_SUPPORTED")
            if a.argument is None:
                aggs.append(AggCall("count_star", None, None, out_sym.type))
            elif node.step == "final":
                # input is the intermediate keys+states layout: states
                # are positional, arg channel is not read
                aggs.append(AggCall(a.function, None, a.argument.type,
                                    out_sym.type))
            else:
                ch = layout[a.argument.name]
                aggs.append(AggCall(a.function, ch, types_[ch],
                                    out_sym.type))
        if node.step == "final":
            # the operator's final path expects keys at channels [0..k)
            # then state columns — reorder if the source layout differs
            in_syms = list(node.group_keys) + list(node.state_symbols or [])
            want = [layout[s.name] for s in in_syms]
            if want != list(range(len(want))) or len(want) != len(types_):
                proj = [InputRef(types_[c], c) for c in want]
                ops.append(self._fp_operator(types_, proj))
                types_ = [types_[c] for c in want]
                layout = {s.name: i for i, s in enumerate(in_syms)}
                group_channels = list(range(len(node.group_keys)))
        seed = None
        if self.hbo is not None and node.step == "partial":
            # seed the adaptive partial-agg verdict from recorded
            # history: a repeat statement skips the observation window
            # and lands directly on the per-key-range decision its
            # last runs converged to (results unchanged either way)
            seed = self.hbo.adaptive_seed(self.hbo.fp(node))
        op = HashAggregationOperator(
            types_, group_channels, aggs, step=node.step,
            memory_context=self._mem_ctx("agg"),
            hash_grouping=self.hash_grouping,
            adaptive_partial=self.adaptive_partial_agg,
            adaptive_ratio=self.adaptive_partial_ratio,
            adaptive_min_rows=self.adaptive_partial_min_rows,
            adaptive_key_buckets=self.adaptive_partial_buckets,
            adaptive_seed=seed)
        ops.append(op)
        new_layout = {}
        out_types = []
        for i, s in enumerate(node.group_keys):
            new_layout[s.name] = i
            out_types.append(types_[group_channels[i]])
        base = len(node.group_keys)
        if node.step == "partial":
            for j, s in enumerate(node.state_symbols or []):
                new_layout[s.name] = base + j
                out_types.append(s.type)
        else:
            for j, (out_sym, _a) in enumerate(node.aggregations):
                new_layout[out_sym.name] = base + j
                out_types.append(out_sym.type)
        return ops, new_layout, out_types

    def _v_DistinctNode(self, node: DistinctNode):
        ops, layout, types_ = self.visit(node.source)
        order = sorted(layout.items(), key=lambda kv: kv[1])
        op = HashAggregationOperator(
            types_, [ch for _, ch in order], [],
            memory_context=self._mem_ctx("distinct"),
            hash_grouping=self.hash_grouping)
        ops.append(op)
        new_layout = {name: i for i, (name, _) in enumerate(order)}
        return ops, new_layout, types_

    def _v_SortNode(self, node: SortNode):
        ops, layout, types_ = self.visit(node.source)
        keys = _sort_keys(node.orderings, layout)
        ops.append(OrderByOperator(types_, keys,
                                   memory_context=self._mem_ctx("sort")))
        return ops, layout, types_

    def _v_TopNNode(self, node: TopNNode):
        ops, layout, types_ = self.visit(node.source)
        keys = _sort_keys(node.orderings, layout)
        ops.append(TopNOperator(types_, keys, node.count))
        return ops, layout, types_

    def _v_LimitNode(self, node: LimitNode):
        ops, layout, types_ = self.visit(node.source)
        if node.offset:
            ops.append(OffsetOperator(node.offset))
        if node.count is not None:
            ops.append(LimitOperator(node.count))
        return ops, layout, types_

    def _v_EnforceSingleRowNode(self, node: EnforceSingleRowNode):
        ops, layout, types_ = self.visit(node.source)
        ops.append(EnforceSingleRowOperator(types_))
        return ops, layout, types_

    def _v_UnionNode(self, node: UnionNode):
        collectors = []
        for child in node.inputs:
            cops, clayout, ctypes = self.visit(child)
            # project to union symbol order
            projections = [InputRef(s.type, clayout[cs.name])
                           for s, cs in zip(node.symbols,
                                            child.output_symbols)]
            cops.append(self._fp_operator(ctypes, projections))
            sink = OutputCollectorOperator()
            cops.append(sink)
            self.pipelines.append(PhysicalPipeline(cops))
            collectors.append(sink)
        types_ = [s.type for s in node.symbols]

        def union_pages(cs=collectors, types_=types_):
            pages = [p for c in cs for p in c.pages]
            if not pages:
                return []
            if any(t.is_string for t in types_):
                # unify dictionary pools across children (Page.concat
                # re-encodes into the first pool)
                return [Page.concat(pages)]
            return pages

        source = DeferredPagesSourceOperator(union_pages)
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        return [source], layout, [s.type for s in node.symbols]

    def _v_TopNRankingNode(self, node):
        from ..ops.grouped_topn import GroupedTopNOperator

        ops, layout, types_ = self.visit(node.source)
        pchans = [layout[s.name] for s in node.partition_by]
        keys = _sort_keys(node.orderings, layout)
        ops.append(GroupedTopNOperator(types_, pchans, keys,
                                       node.ranking, node.max_rank,
                                       step=node.step))
        if node.step == "partial":
            return ops, layout, list(types_)
        new_layout = dict(layout)
        new_layout[node.rank_symbol.name] = len(types_)
        return ops, new_layout, list(types_) + [T.BIGINT]

    def _v_WindowNode(self, node):
        from ..ops.window import WindowCall, WindowOperator

        ops, layout, types_ = self.visit(node.source)
        pchans = [layout[s.name] for s in node.partition_by]
        keys = _sort_keys(node.orderings, layout)
        calls = []
        for out_sym, f in node.functions:
            arg_ch = layout[f.argument.name] if f.argument is not None \
                else None
            calls.append(WindowCall(
                f.function, arg_ch,
                f.argument.type if f.argument is not None else None,
                out_sym.type, f.frame_mode, f.offset,
                f.frame_start, f.frame_end))
        ops.append(WindowOperator(types_, pchans, keys, calls))
        new_layout = dict(layout)
        out_types = list(types_)
        for j, (out_sym, _f) in enumerate(node.functions):
            new_layout[out_sym.name] = len(types_) + j
            out_types.append(out_sym.type)
        return ops, new_layout, out_types

    def _v_TableWriterNode(self, node):
        from ..ops.operator import TableWriterOperator

        ops, layout, types_ = self.visit(node.source)
        if self.page_sink_factory is not None:
            sink = self.page_sink_factory(node)
        else:
            conn = self.metadata.connectors[node.catalog]
            if node.create:
                # CTAS creates the target here, at execution time —
                # EXPLAIN and failed planning never mutate metadata.
                # Scaled writers: sibling tasks of a distributed CTAS
                # race to create; the analyzer already rejected genuine
                # pre-existing targets, so losing the race means a
                # sibling won — use its table
                handle = create_table_idempotent(
                    conn, node.schema, node.table_name, node.columns)
            else:
                handle = conn.metadata().get_table_handle(node.schema,
                                                          node.table_name)
            sink = conn.page_sink(handle, node.columns)
        ops.append(TableWriterOperator(sink))
        return ops, {node.rows_symbol.name: 0}, [T.BIGINT]

    def _v_RemoteSourceNode(self, node):
        assert self.exchange_reader is not None, \
            "remote source outside distributed execution"
        types_ = [s.type for s in node.symbols]
        layout = {s.name: i for i, s in enumerate(node.symbols)}
        if node.kind == "merge":
            # order-preserving gather: one stream per producer task,
            # k-way merged under the exchange's orderings
            from ..ops.merge_exchange import MergeExchangeSourceOperator

            streams = self.exchange_reader(node.fragment_id, "merge")
            keys = _sort_keys(node.orderings or [], layout)
            return [MergeExchangeSourceOperator(streams, types_, keys)], \
                layout, types_
        thunk = self.exchange_reader(node.fragment_id, node.kind)
        from ..ops.output import ExchangeSourceOperator

        # source_fragment tags the operator's exchange metrics (skew
        # ratio, per_dest, retries) with the PRODUCING fragment, so
        # EXPLAIN ANALYZE attributes a boundary's stats unambiguously
        # when a stage consumes several remote sources (joins)
        source = ExchangeSourceOperator(thunk, types_,
                                        source_fragment=node.fragment_id)
        return [source], layout, types_

    def _v_IntersectNode(self, node: IntersectNode):
        return self._set_semantics_join(node, "semi")

    def _v_ExceptNode(self, node: ExceptNode):
        return self._set_semantics_join(node, "anti")

    def _set_semantics_join(self, node, join_type: str):
        """INTERSECT/EXCEPT = Distinct(left) semi/anti-join right on all
        columns. NOTE: SQL set ops treat NULLs as equal; the join treats
        NULL keys as non-matching — NULL-row edge cases differ until the
        join gains IS NOT DISTINCT semantics."""
        left, right = node.inputs
        bops, blayout, btypes = self.visit(right)
        pops, playout, ptypes = self.visit(left)
        # align probe/build channel order to symbol order
        bchans = [blayout[s.name] for s in right.output_symbols]
        bridge = JoinBridge()
        bops.append(HashBuilderOperator(
            btypes, bchans, bridge,
            memory_context=self._mem_ctx("setop-build"),
            hybrid=self._hybrid_opts(join_type)))
        self.pipelines.append(PhysicalPipeline(bops))
        pchans = [playout[s.name] for s in left.output_symbols]
        pops.append(LookupJoinOperator(
            ptypes, pchans, bridge, join_type,
            max_lanes=self.join_max_lanes,
            memory_limited=self._memory_constrained()))
        # distinct over the probe columns; output channels follow pchans
        # order, i.e. channel j <-> left.output_symbols[j] <-> symbols[j]
        pops.append(HashAggregationOperator(
            ptypes, pchans, [],
            memory_context=self._mem_ctx("setop-distinct"),
            hash_grouping=self.hash_grouping))
        layout = {s.name: j for j, s in enumerate(node.symbols)}
        out_types = [ptypes[ch] for ch in pchans]
        return pops, layout, out_types


def project_to_wire_layout(frag, ops, layout, types_):
    """Append the projection fixing a fragment's WIRE layout: consumers
    map RemoteSourceNode symbols positionally, so the output operator
    must see output_symbols order exactly.  Shared by every runner that
    builds a fragment's output tail (in-process, worker process).
    Returns (ops, layout, types_, key_channels)."""
    out_syms = frag.output_symbols
    want = [layout[s.name] for s in out_syms]
    if want != list(range(len(types_))):
        proj = [InputRef(types_[c], c) for c in want]
        ops.append(FilterProjectOperator(PageProcessor(types_, proj)))
        types_ = [types_[c] for c in want]
        layout = {s.name: i for i, s in enumerate(out_syms)}
    key_channels = [layout[s.name] for s in frag.output_keys]
    return ops, layout, types_, key_channels


def _sort_keys(orderings, layout) -> List[SortKey]:
    keys = []
    for o in orderings:
        nulls_last = o.nulls_last if o.nulls_last is not None \
            else o.ascending
        keys.append(SortKey(layout[o.symbol.name], o.ascending, nulls_last))
    return keys


def _eval_literal(e: RowExpression):
    """Host evaluation of literal-only expression trees (VALUES rows)."""
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Call) and e.name == "$cast":
        v = _eval_literal(e.args[0])
        if v is None:
            return None
        t = e.type
        if t.is_decimal:
            return Decimal(str(v))
        if t in (T.DOUBLE, T.REAL):
            return float(v)
        if t in (T.TINYINT, T.SMALLINT, T.INTEGER, T.BIGINT):
            return int(v)
        if t.is_string:
            return str(v)
        return v
    if isinstance(e, Call) and e.name == "negate":
        v = _eval_literal(e.args[0])
        return None if v is None else -v
    raise TrinoError(f"VALUES rows must be literals, got {e!r}",
                     "NOT_SUPPORTED")
