"""Memory governance: node-wide + per-query pools, host-RAM and disk
spill tiers.

Reference analog: ``memory/MemoryPool.java`` (ONE pool per node shared by
every query, with per-query reservations), ``lib/trino-memory-context``
(the AggregatedMemoryContext tree charged by operators),
``execution/MemoryRevokingScheduler.java:48`` (pool pressure -> revoke
largest revocable operators) and ``spiller/FileSingleStreamSpiller.java``
(the disk spill target with its checksummed page frames).

TPU redesign: the scarce resource is device HBM.  Spill degrades in two
tiers — device->host (a ``DevicePage`` parked as numpy arrays in a
``SpilledPage``) and host->disk (a ``DiskSpilledPage`` holding a
CRC-framed, atomically-written spill file; see ``serde.spill_frame``) —
so a query under pressure degrades incrementally instead of failing
("Robust Dynamic Hybrid Hash Join"'s discipline).  Pool hierarchy:

  NodeMemoryPool            one per worker process, all queries charge it
    QueryMemoryPool         per (query, worker): query_max_memory_bytes
      OperatorMemoryContext per stateful operator (agg/join/sort)

A reservation that would exceed the query cap first revokes the query's
own revocable contexts largest-first (when ``spill_enabled``); one that
would exceed the NODE cap revokes across queries largest-first; still
over => MemoryExceededError (EXCEEDED_LOCAL_MEMORY_LIMIT) respectively
NodeMemoryExceededError (EXCEEDED_NODE_MEMORY) — both
INSUFFICIENT_RESOURCES, so the coordinator's memory-aware retry can
re-admit with a grown budget.  Host-RAM residency of spilled state is
tracked by a ``HostSpillLedger`` (node-wide when a node pool exists);
crossing its limit demotes the largest spilled pages to disk when
``spill_to_disk_enabled``.

Locking: the pool lock and context locks are never held together —
revoke callbacks run under the victim context's lock only (so they can't
stall other threads' reserve/free), and pool bookkeeping for the freed
bytes happens after the context lock is released.  The node pool's lock
is likewise never held across a revoke callback.  Operators must mutate
spillable state only under their context lock so a revoke from another
thread cannot interleave with ``add_input``.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Callable, Dict, List, Optional

import numpy as np

from ..types import TrinoError


class MemoryExceededError(TrinoError):
    def __init__(self, requested: int, reserved: int, limit: int):
        super().__init__(
            f"Query exceeded per-query memory limit of {limit} bytes "
            f"(reserved {reserved}, requested {requested}); "
            "raise query_max_memory_bytes or enable spill_enabled",
            "EXCEEDED_LOCAL_MEMORY_LIMIT")
        self.requested = requested
        self.reserved = reserved
        self.limit = limit


class NodeMemoryExceededError(TrinoError):
    """The worker-wide pool is exhausted across ALL queries and
    cross-query revocation could not free enough (reference: the node
    MemoryPool blocking with no revocable bytes left)."""

    def __init__(self, requested: int, reserved: int, limit: int,
                 query_id: str = ""):
        super().__init__(
            f"Worker memory pool exhausted: node limit {limit} bytes, "
            f"reserved {reserved} across all queries, query "
            f"{query_id or '?'} requested {requested} more",
            "EXCEEDED_NODE_MEMORY")
        self.requested = requested
        self.reserved = reserved
        self.limit = limit


def default_node_memory_bytes(fallback: int = 16 << 30) -> int:
    """Auto default for ``node_max_memory_bytes``: the accelerator's
    own reported capacity (``Device.memory_stats()['bytes_limit']`` on
    TPU/GPU backends), so the node pool tracks real HBM instead of a
    hardwired constant. CPU backends report no stats — fall back.
    Never raises: a worker must come up even on an odd backend."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit") \
                or stats.get("bytes_reservable_limit")
            if limit:
                return int(limit)
    except Exception:
        pass
    return fallback


def device_page_bytes(page) -> int:
    """Accounted HBM footprint of a DevicePage: padded columns + null
    masks + the valid mask.  Disk-parked pages carry their recorded
    footprint (their arrays are not in RAM to measure)."""
    hbm = getattr(page, "hbm_bytes", None)
    if hbm is not None:
        return hbm
    cap = page.capacity
    total = cap  # valid mask (bool = 1 byte)
    for c, n in zip(page.cols, page.nulls):
        total += cap * c.dtype.itemsize
        total += cap  # null mask
    return total


class SpilledPage:
    """A DevicePage parked in host RAM.

    Live lanes are compacted to the smallest power-of-two bucket: device
    pages are often mostly dead lanes (filtered rows, partial-aggregation
    outputs padded to their input capacity), so compaction shrinks both
    the host footprint and — more importantly — the HBM needed to bring
    the page back."""

    __slots__ = ("types", "cols", "nulls", "valid", "dictionaries",
                 "__weakref__")

    def __init__(self, page):
        from ..block import padded_size

        valid = np.asarray(page.valid)
        keep = np.nonzero(valid)[0]
        cap = padded_size(len(keep))
        self.types = list(page.types)
        self.dictionaries = list(page.dictionaries)
        if cap < valid.shape[0]:
            k = len(keep)
            self.cols = []
            self.nulls = []
            for c, n in zip(page.cols, page.nulls):
                cc = np.zeros(cap, dtype=np.asarray(c).dtype)
                cc[:k] = np.asarray(c)[keep]
                nn = np.zeros(cap, dtype=bool)
                nn[:k] = np.asarray(n)[keep]
                self.cols.append(cc)
                self.nulls.append(nn)
            v = np.zeros(cap, dtype=bool)
            v[:k] = True
            self.valid = v
        else:
            self.cols = [np.asarray(c) for c in page.cols]
            self.nulls = [np.asarray(n) for n in page.nulls]
            self.valid = valid

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def host_bytes(self) -> int:
        return sum(c.nbytes for c in self.cols) \
            + sum(n.nbytes for n in self.nulls) + self.valid.nbytes

    def host(self) -> "SpilledPage":
        """An in-RAM view of this page (disk-parked pages load here)."""
        return self

    def to_device(self):
        import jax.numpy as jnp

        from ..block import DevicePage

        return DevicePage(list(self.types),
                          [jnp.asarray(c) for c in self.cols],
                          [jnp.asarray(n) for n in self.nulls],
                          jnp.asarray(self.valid),
                          list(self.dictionaries))


class DiskSpilledPage(SpilledPage):
    """A SpilledPage demoted to a per-query spill file: the arrays live
    on disk in one CRC-checked frame (``serde.spill_frame``), written
    atomically; only types/dictionaries/footprint stay in RAM
    (dictionaries are shared host-side objects — the page reloads in
    this process, so pools need not be serialized).

    Reference analog: ``spiller/FileSingleStreamSpiller.java`` — the
    tier below host RAM."""

    __slots__ = ("path", "_capacity", "hbm_bytes", "disk_bytes")

    def __init__(self, spilled: SpilledPage, path: str):
        # deliberately no super().__init__: the array slots stay unset
        self.types = list(spilled.types)
        self.dictionaries = list(spilled.dictionaries)
        self.path = path
        self._capacity = spilled.capacity
        self.hbm_bytes = device_page_bytes(spilled)
        self.disk_bytes = 0  # set by DiskSpiller after the write

    @property
    def capacity(self) -> int:
        return self._capacity

    def host(self) -> SpilledPage:
        """Load the frame back into an in-RAM SpilledPage."""
        from .serde import read_spill_file

        cols, nulls, valid = read_spill_file(self.path)
        page = SpilledPage.__new__(SpilledPage)
        page.types = list(self.types)
        page.dictionaries = list(self.dictionaries)
        page.cols = cols
        page.nulls = nulls
        page.valid = valid
        return page

    def to_device(self):
        return self.host().to_device()


class HostSpillLedger:
    """Live host-RAM bytes held by SpilledPages, node-wide when a node
    pool exists.  Charged at spill time and discharged by a weakref
    finalizer when the parked page is dropped (uploaded back or
    demoted), so residency tracks actual lifetime, not call sites.

    The ledger also TRACKS the operator page lists holding parked
    pages (with the context lock guarding each), so over-limit
    demotion can run ACROSS operator lists: the operator that happens
    to spill last is often not the one parking the biggest pages, and
    demoting only its own list leaves the ledger over budget while
    colder, larger state sits in RAM (reference: MemoryRevokingScheduler
    picking victims pool-wide, not caller-local)."""

    def __init__(self, limit_bytes: Optional[int] = None):
        self.limit_bytes = limit_bytes
        self.resident_bytes = 0
        self.peak_bytes = 0
        self.cross_list_demotions = 0
        # REENTRANT: dropping a SpilledPage reference can fire its
        # ``_discharge`` finalizer on the dropping thread at any
        # allocation/decref point — including while this very lock is
        # held (the untrack_pool deadlock); an RLock absorbs that
        self._lock = threading.RLock()
        #: (pages list, guarding context lock, owning QueryMemoryPool);
        #: entries die with their pool (untrack_pool at close)
        self._tracked: List[tuple] = []

    def charge(self, page: SpilledPage) -> None:
        nbytes = page.host_bytes()
        with self._lock:
            self.resident_bytes += nbytes
            self.peak_bytes = max(self.peak_bytes, self.resident_bytes)
        weakref.finalize(page, self._discharge, nbytes)

    def _discharge(self, nbytes: int) -> None:
        with self._lock:
            self.resident_bytes -= nbytes

    def over_limit(self) -> bool:
        with self._lock:
            return self.limit_bytes is not None \
                and self.resident_bytes > self.limit_bytes

    # -- cross-operator-list demotion -----------------------------------

    def track(self, pages: List, lock, pool: "QueryMemoryPool") -> None:
        """Register an operator's revocable page list as a demotion
        candidate (idempotent per list)."""
        if pool.disk_spiller is None:
            return  # its pages can never demote — don't scan them
        with self._lock:
            for ps, _, _ in self._tracked:
                if ps is pages:
                    return
            self._tracked.append((pages, lock, pool))

    def untrack_pool(self, pool: "QueryMemoryPool") -> None:
        with self._lock:
            dropped = [t for t in self._tracked if t[2] is pool]
            self._tracked = [t for t in self._tracked
                             if t[2] is not pool]
        # the entries held the last strong refs to their page lists:
        # release OUTSIDE the lock so the pages' discharge finalizers
        # (which take it) fire lock-free
        del dropped

    def demote_across(self, exclude: Optional[List] = None) -> None:
        """Demote in-RAM SpilledPages of OTHER tracked lists,
        node-wide largest-first, while over limit.  Foreign context
        locks are taken non-blocking: an operator actively mutating
        its state is skipped rather than deadlocked against (the
        caller already holds its OWN context lock — blocking on a
        foreign one would create an AB-BA cycle with that operator's
        own demotion; never blocking also makes holding several
        foreign locks at once cycle-free, which is what lets the
        candidate sort span every lockable list instead of draining
        them one at a time in tracking order)."""
        if not self.over_limit():
            return
        with self._lock:
            tracked = list(self._tracked)
        held = []
        demoted = 0
        try:
            for pages, lock, pool in tracked:
                if pages is exclude:
                    continue
                if not lock.acquire(blocking=False):
                    continue
                if pool.disk_spiller.closed:
                    lock.release()  # pool closed after the snapshot
                    continue
                held.append((pages, lock, pool))
            candidates = sorted(
                ((i, pages, pool)
                 for pages, _, pool in held
                 for i, p in enumerate(pages)
                 if isinstance(p, SpilledPage)
                 and not isinstance(p, DiskSpilledPage)),
                key=lambda t: -t[1][t[0]].host_bytes())
            for i, pages, pool in candidates:
                if not self.over_limit():
                    break
                try:
                    pages[i] = pool.disk_spiller.spill(pages[i])
                except RuntimeError:
                    continue  # close() raced the spill; nothing leaked
                demoted += 1
        finally:
            for _, lock, _ in held:
                lock.release()
        if demoted:
            with self._lock:
                self.cross_list_demotions += demoted


class DiskSpiller:
    """Per-query spill-file manager: one directory per query, one
    CRC-framed file per demoted page, atomic writes (reference:
    ``FileSingleStreamSpiller`` + ``SpillerFactory``'s per-query
    directories)."""

    def __init__(self, query_id: str = "q"):
        self.query_id = query_id
        self._dir: Optional[str] = None
        self._seq = 0
        self._lock = threading.Lock()
        self.closed = False
        self.spill_events = 0
        self.spilled_bytes = 0       # uncompressed bytes demoted
        self.file_bytes = 0          # on-disk (compressed) bytes

    def _next_path(self) -> str:
        import tempfile

        with self._lock:
            if self.closed:
                # a cross-list demotion racing the owner's close must
                # not resurrect the reaped spill directory
                raise RuntimeError("spiller closed")
            if self._dir is None:
                # env read per spiller, not at import: embedders may set
                # the spill root after importing the package
                root = os.environ.get("TRINO_TPU_SPILL_DIR",
                                      "/tmp/trino_tpu_spill")
                base = os.path.join(root, str(os.getpid()))
                os.makedirs(base, exist_ok=True)
                self._dir = tempfile.mkdtemp(
                    prefix=f"{self.query_id}.", dir=base)
            self._seq += 1
            return os.path.join(self._dir, f"spill-{self._seq}.bin")

    def spill(self, page: SpilledPage) -> DiskSpilledPage:
        from .serde import write_spill_file

        path = self._next_path()
        disk = DiskSpilledPage(page, path)
        nbytes = write_spill_file(path, page.cols, page.nulls, page.valid)
        disk.disk_bytes = nbytes
        with self._lock:
            self.spill_events += 1
            self.spilled_bytes += page.host_bytes()
            self.file_bytes += nbytes
        # the file dies with the page object (upload consumed it) or at
        # close(), whichever first
        weakref.finalize(disk, _remove_quiet, path)
        return disk

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"disk_spill_events": self.spill_events,
                    "disk_spilled_bytes": self.spilled_bytes,
                    "disk_file_bytes": self.file_bytes}

    def close(self):
        import shutil

        with self._lock:
            self.closed = True
            d, self._dir = self._dir, None
        if d is not None:
            shutil.rmtree(d, ignore_errors=True)


def _remove_quiet(path: str):
    try:
        os.remove(path)
    except OSError:
        pass


def spill_pages(pages: List, pool: "QueryMemoryPool" = None,
                lock=None) -> int:
    """Convert DevicePage entries to SpilledPage in place (caller holds
    the owning context's lock); returns the HBM bytes freed.  With a
    pool, host residency is charged to its ledger and — when the ledger
    is over its limit and disk spill is enabled — the largest parked
    pages demote to the disk tier, in this list first and then across
    every other tracked operator list.  ``lock`` is the context lock
    guarding ``pages`` (i.e. the one the caller holds): passing it
    registers the list so OTHER operators' over-limit demotions can
    reach these pages too."""
    from ..block import DevicePage

    freed = 0
    for i, p in enumerate(pages):
        if isinstance(p, DevicePage):
            freed += device_page_bytes(p)
            spilled = SpilledPage(p)
            if pool is not None:
                pool.host_ledger.charge(spilled)
            pages[i] = spilled
    if pool is not None:
        if lock is not None:
            pool.host_ledger.track(pages, lock, pool)
        pool.maybe_demote(pages)
    return freed


def reserve_and_append(ctx: "OperatorMemoryContext", pages: List, page):
    """The add_input discipline shared by spillable operators: charge the
    page, then publish it to the revocable list under the context lock."""
    ctx.reserve(device_page_bytes(page))
    with ctx.lock:
        pages.append(page)


def prepare_finish(ctx: "OperatorMemoryContext", pages: List):
    """Shared finish-time transition for spillable operators: their pages
    stop being revocable (the finish pass owns them), so if the finish
    transient (~2x total for concat + result) would not fit alongside the
    current reservations, park everything on host first — spill compacts
    dead lanes, so totals are recomputed from parked sizes (= what
    re-upload actually costs).  Returns (total, uploads)."""
    pool = ctx.pool
    with ctx.lock:
        total = sum(device_page_bytes(p) for p in pages)
        uploads = sum(device_page_bytes(p) for p in pages
                      if isinstance(p, SpilledPage))
        freed = 0
        if pool.spill_enabled and \
                pool.reserved + uploads + 2 * total > pool.max_bytes:
            freed = spill_pages(pages, pool, ctx.lock)
            total = sum(device_page_bytes(p) for p in pages)
            uploads = total
        # clear the callback INSIDE the lock: a concurrent pool revoke
        # between the totals above and here would invalidate them
        ctx.set_revoke_callback(None)
    if freed:
        pool.record_spill(freed)
        ctx.free(freed)
    return total, uploads


class OperatorMemoryContext:
    """One operator's slice of the query pool (reference:
    ``memory/context/LocalMemoryContext``).

    ``lock`` guards the owner's spillable state; a revoke callback runs
    under it.  ``reserve``/``free`` must be called WITHOUT holding it.
    """

    def __init__(self, pool: "QueryMemoryPool", name: str):
        self.pool = pool
        self.name = name
        self.lock = threading.RLock()
        self.reserved = 0
        self.peak = 0               # high-water mark (survives close();
        #                             history-based stats record it)
        self.revocable = 0          # portion of reserved that revoke can free
        self._revoke_cb: Optional[Callable[[], int]] = None

    def set_revoke_callback(self, cb: Callable[[], int]):
        """cb() spills the owner's revocable state to host and returns the
        bytes freed (reference: Operator.startMemoryRevoke)."""
        self._revoke_cb = cb

    def reserve(self, nbytes: int, revocable: bool = True):
        if nbytes <= 0:
            return
        self.pool._reserve(self, nbytes, revocable)

    def free(self, nbytes: int, revocable: bool = True):
        if nbytes <= 0:
            return
        self.pool._free(self, nbytes, revocable)

    def close(self):
        if self.reserved:
            self.pool._free(self, self.reserved, revocable=False)
            self.revocable = 0


class QueryMemoryPool:
    """Per-(query, node) HBM accounting with synchronous revocation.

    Reference: ``memory/MemoryPool.java``'s per-query reservation +
    ``QueryContext``.  With a ``parent`` NodeMemoryPool every reservation
    also charges the node; without one (single-query runners) the pool
    stands alone.
    """

    def __init__(self, max_bytes: int, spill_enabled: bool = False,
                 spill_to_disk: bool = False,
                 host_spill_limit: Optional[int] = None,
                 parent: "NodeMemoryPool" = None,
                 query_id: str = "q"):
        self.max_bytes = int(max_bytes)
        self.spill_enabled = spill_enabled
        self.spill_to_disk = spill_to_disk
        self.query_id = query_id
        self.parent = parent
        self.reserved = 0
        self.peak_bytes = 0
        self.spill_events = 0
        self.spilled_bytes = 0
        self.partition_spills = 0       # hybrid-join partitions demoted
        self.partition_spilled_bytes = 0
        #: chaos harness only (FaultSchedule kind="revoke-memory"): a
        #: PERIOD of reserve calls — every `countdown`-th reservation
        #: triggers one full-pressure revocation, so deterministic
        #: revocation pressure lands mid-build AND mid-probe without
        #: shrinking the pool
        self.fault_revoke_countdown: Optional[int] = None
        self._fault_revoke_left: Optional[int] = None
        self._lock = threading.Lock()
        self._contexts: List[OperatorMemoryContext] = []
        self.host_ledger = parent.host_ledger if parent is not None \
            else HostSpillLedger(host_spill_limit)
        self.disk_spiller = DiskSpiller(query_id) if spill_to_disk \
            else None

    def create_context(self, name: str) -> OperatorMemoryContext:
        ctx = OperatorMemoryContext(self, name)
        with self._lock:
            self._contexts.append(ctx)
        return ctx

    # -- spill tiers ----------------------------------------------------

    def maybe_demote(self, pages: List):
        """Demote the largest in-RAM SpilledPages to disk while the
        host ledger is over its limit (the host tier stays the fast
        path; disk absorbs the overflow): this operator's own list
        first (its context lock is already held by the caller), then
        COOPERATIVELY across every other tracked operator list on the
        node — the last spiller is rarely the biggest holder."""
        if self.disk_spiller is None or not self.host_ledger.over_limit():
            return
        self._demote_list_locked(pages)
        if self.host_ledger.over_limit():
            self.host_ledger.demote_across(exclude=pages)

    def _demote_list_locked(self, pages: List):
        """Demote one list largest-first (caller holds the list's
        guarding context lock).  Largest-first order is fixed up front —
        one sort, not a rescan per demotion."""
        order = sorted(
            (i for i, p in enumerate(pages)
             if isinstance(p, SpilledPage)
             and not isinstance(p, DiskSpilledPage)),
            key=lambda i: -pages[i].host_bytes())
        for i in order:
            if not self.host_ledger.over_limit():
                return
            # the replaced SpilledPage's finalizer discharges the
            # ledger as soon as the reference drops
            pages[i] = self.disk_spiller.spill(pages[i])

    # -- internal (called by contexts) ----------------------------------

    def _reserve(self, ctx: OperatorMemoryContext, nbytes: int,
                 revocable: bool):
        self._reserve_local(ctx, nbytes, revocable)
        if self.parent is not None:
            try:
                self.parent.reserve_for(self, nbytes)
            except TrinoError:
                # roll back the LOCAL admit only: the node charge never
                # happened, so _free's parent uncharge must not run
                with self._lock:
                    self._free_locked(ctx, nbytes, revocable)
                raise

    def _maybe_fault_revoke(self):
        """Injected revocation (chaos harness): every `countdown`-th
        reserve call revokes EVERYTHING revocable — the partial-
        revocation paths (hybrid-join partition demotion) then run
        under real concurrency at every phase of the query, not just
        under real pressure."""
        with self._lock:
            period = self.fault_revoke_countdown
            if period is None:
                return
            left = self._fault_revoke_left
            left = period - 1 if left is None else left - 1
            if left > 0:
                self._fault_revoke_left = left
                return
            self._fault_revoke_left = period
        self.revoke_up_to(self.max_bytes)

    def _reserve_local(self, ctx: OperatorMemoryContext, nbytes: int,
                       revocable: bool):
        self._maybe_fault_revoke()
        # revoke-until-fit loop: a concurrent reserve may consume bytes
        # another round of revocation just freed, so the target is
        # re-derived under the lock each round and the request only
        # fails once revocation stops making progress
        while True:
            with self._lock:
                if self.reserved + nbytes <= self.max_bytes:
                    self._admit_locked(ctx, nbytes, revocable)
                    return
                if not self.spill_enabled:
                    raise MemoryExceededError(nbytes, self.reserved,
                                              self.max_bytes)
                needed = self.reserved + nbytes - self.max_bytes
            # requester's own state first: self-revoke is deadlock-free
            # (its RLock is reentrant on the calling thread) and the
            # largest state usually belongs to the operator asking for
            # more
            if self.revoke_up_to(needed, prefer=ctx) <= 0:
                break
        with self._lock:
            if self.reserved + nbytes > self.max_bytes:
                raise MemoryExceededError(nbytes, self.reserved,
                                          self.max_bytes)
            self._admit_locked(ctx, nbytes, revocable)

    def revoke_up_to(self, needed: int, prefer=None) -> int:
        """Spill revocable contexts largest-first until ``needed`` bytes
        came free (or no revocable state remains); returns the bytes
        actually freed.  Runs WITHOUT the pool lock held: callbacks move
        whole operator states device->host, and other threads'
        reserve/free must not serialize behind that transfer (reference:
        MemoryRevokingScheduler revokes asynchronously)."""
        with self._lock:
            candidates = sorted(self._contexts,
                                key=lambda c: (c is not prefer,
                                               -c.revocable))
        total_freed = 0
        for c in candidates:
            if total_freed >= needed:
                break
            if c.revocable <= 0:
                continue
            # PARTIAL-REVOCATION CONTRACT: a callback may free only a
            # SLICE of its revocable state per call (the hybrid hash
            # join demotes one build partition at a time) — keep asking
            # the same context until the target is met or it stops
            # making progress.  Wholesale callbacks are compatible: the
            # second call finds nothing left and returns 0.
            while total_freed < needed:
                with c.lock:
                    cb = c._revoke_cb
                    freed = cb() if cb is not None else 0
                if freed <= 0:
                    break
                total_freed += freed
                self.record_spill(freed)
                self._free(c, freed, revocable=True)
        return total_freed

    def revocable_bytes(self) -> int:
        with self._lock:
            return sum(c.revocable for c in self._contexts)

    def _admit_locked(self, ctx, nbytes, revocable):
        self.reserved += nbytes
        ctx.reserved += nbytes
        ctx.peak = max(ctx.peak, ctx.reserved)
        if revocable:
            ctx.revocable += nbytes
        self.peak_bytes = max(self.peak_bytes, self.reserved)

    def _free(self, ctx: OperatorMemoryContext, nbytes: int,
              revocable: bool):
        with self._lock:
            freed = self._free_locked(ctx, nbytes, revocable)
        if freed and self.parent is not None:
            self.parent.uncharge_for(self, freed)

    def _free_locked(self, ctx, nbytes, revocable) -> int:
        nbytes = min(nbytes, ctx.reserved)
        self.reserved -= nbytes
        ctx.reserved -= nbytes
        if revocable:
            ctx.revocable = max(0, ctx.revocable - nbytes)
        return nbytes

    def record_spill(self, freed: int):
        with self._lock:
            self.spill_events += 1
            self.spilled_bytes += freed

    def record_partition_spill(self, freed: int, parts: int = 1):
        """One hybrid-join build partition demoted off-device (the
        graceful-degradation counter the acceptance bar reads: a
        squeezed join shows partition_spills > 0 with query_retries
        still 0)."""
        with self._lock:
            self.partition_spills += parts
            self.partition_spilled_bytes += freed

    def close(self):
        """Release every context's residue and the disk spill directory
        (end of the query's life on this node)."""
        with self._lock:
            contexts = list(self._contexts)
        for c in contexts:
            c.close()
        # drop this query's page lists from the node ledger's demotion
        # candidates BEFORE the spill dir dies with the spiller
        self.host_ledger.untrack_pool(self)
        if self.disk_spiller is not None:
            self.disk_spiller.close()

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, int]:
        out = {
            "reserved_bytes": self.reserved,
            "peak_bytes": self.peak_bytes,
            "max_bytes": self.max_bytes,
            "spill_events": self.spill_events,
            "spilled_bytes": self.spilled_bytes,
            "partition_spills": self.partition_spills,
            "partition_spilled_bytes": self.partition_spilled_bytes,
        }
        if self.disk_spiller is not None:
            out.update(self.disk_spiller.stats())
        return out


class NodeMemoryPool:
    """The worker-wide pool every concurrent query charges (reference:
    ``memory/MemoryPool.java`` — the actual per-node general pool).

    Over-budget reservations revoke across queries LARGEST-REVOCABLE-
    first; a node that still cannot admit records a blocked event (the
    signal the coordinator's low-memory killer keys on) and raises
    EXCEEDED_NODE_MEMORY."""

    def __init__(self, max_bytes: int,
                 host_spill_limit: Optional[int] = None):
        self.max_bytes = int(max_bytes)
        self.reserved = 0
        self.peak_bytes = 0
        self.blocked_events = 0
        self.cross_query_revokes = 0
        self._lock = threading.Lock()
        self._children: Dict[str, QueryMemoryPool] = {}
        #: peaks of already-released queries, kept so a heartbeat after
        #: the fast failure still feeds the retry MemoryEstimator
        self._released_peaks: Dict[str, int] = {}
        self.host_ledger = HostSpillLedger(host_spill_limit)

    def create_query_pool(self, query_id: str, max_bytes: int,
                          spill_enabled: bool = False,
                          spill_to_disk: bool = False) -> QueryMemoryPool:
        with self._lock:
            pool = self._children.get(query_id)
            if pool is None:
                pool = QueryMemoryPool(
                    max_bytes, spill_enabled, spill_to_disk,
                    parent=self, query_id=query_id)
                self._children[query_id] = pool
            else:
                # a hit must not serve a stale configuration (the
                # qlint cache-coherence class): a memory-aware retry
                # re-admits with an ESCALATED budget while a straggling
                # prior attempt still holds a pool ref — widen to the
                # newest request instead of silently keeping the old
                # limits
                pool.max_bytes = max(pool.max_bytes, int(max_bytes))
                pool.spill_enabled = pool.spill_enabled or spill_enabled
                if spill_to_disk and not pool.spill_to_disk:
                    pool.spill_to_disk = True
                    if pool.disk_spiller is None:
                        pool.disk_spiller = DiskSpiller(query_id)
            return pool

    def release_query(self, query_id: str):
        with self._lock:
            pool = self._children.pop(query_id, None)
            if pool is not None:
                if len(self._released_peaks) >= 64:
                    self._released_peaks.clear()
                self._released_peaks[query_id] = pool.peak_bytes
        if pool is not None:
            pool.close()
            # close() frees context residue, which uncharges us; any
            # accounting drift dies with the child here
            with self._lock:
                self.reserved -= min(self.reserved, pool.reserved)

    # -- charging (called by child pools, never under their lock) --------

    def reserve_for(self, child: QueryMemoryPool, nbytes: int):
        # revoke-until-fit (same discipline as the query pool): the
        # target re-derives under the lock each round so concurrent
        # admissions cannot turn a satisfiable request into a failure
        # while revocable state remains
        while True:
            with self._lock:
                if self.reserved + nbytes <= self.max_bytes:
                    self._admit_locked(nbytes)
                    return
                needed = self.reserved + nbytes - self.max_bytes
                # cross-query revocation, largest revocable first; the
                # requester revokes last (its state is already
                # host-bound if its own cap forced spill)
                victims = sorted(self._children.values(),
                                 key=lambda p: (p is child,
                                                -p.revocable_bytes()))
            round_freed = 0
            for victim in victims:
                if round_freed >= needed:
                    break
                if not victim.spill_enabled:
                    continue
                freed = victim.revoke_up_to(needed - round_freed)
                if freed > 0:
                    with self._lock:
                        self.cross_query_revokes += 1
                round_freed += freed
            if round_freed <= 0:
                break
        with self._lock:
            if self.reserved + nbytes > self.max_bytes:
                self.blocked_events += 1
                raise NodeMemoryExceededError(
                    nbytes, self.reserved, self.max_bytes,
                    child.query_id)
            self._admit_locked(nbytes)

    def _admit_locked(self, nbytes: int):
        self.reserved += nbytes
        self.peak_bytes = max(self.peak_bytes, self.reserved)

    def uncharge_for(self, child: QueryMemoryPool, nbytes: int):
        with self._lock:
            self.reserved -= min(self.reserved, nbytes)

    # -- observability ---------------------------------------------------

    def snapshot(self) -> dict:
        """The heartbeat-piggyback payload: node totals + per-query
        reservations, the ClusterMemoryManager's input (reference:
        MemoryInfo in the ServerInfo heartbeat).  ``blocked_events`` is
        a DELTA consumed by the read: one blocked episode must trigger
        at most one killer decision, not one per heartbeat forever."""
        with self._lock:
            queries = {qid: {"reserved": 0, "peak": peak, "spilled": 0}
                       for qid, peak in self._released_peaks.items()}
            queries.update({qid: {"reserved": p.reserved,
                                  "peak": p.peak_bytes,
                                  "spilled": p.spilled_bytes}
                            for qid, p in self._children.items()})
            blocked, self.blocked_events = self.blocked_events, 0
            return {
                "max_bytes": self.max_bytes,
                "reserved_bytes": self.reserved,
                "peak_bytes": self.peak_bytes,
                "blocked_events": blocked,
                "cross_query_revokes": self.cross_query_revokes,
                "host_spill_resident": self.host_ledger.resident_bytes,
                "queries": queries,
            }


def pool_from_session(session, parent: NodeMemoryPool = None,
                      query_id: str = "q") -> QueryMemoryPool:
    from .. import session_properties as SP

    if parent is not None:
        return parent.create_query_pool(
            query_id, SP.value(session, "query_max_memory_bytes"),
            SP.value(session, "spill_enabled"),
            SP.value(session, "spill_to_disk_enabled"))
    return QueryMemoryPool(
        SP.value(session, "query_max_memory_bytes"),
        SP.value(session, "spill_enabled"),
        SP.value(session, "spill_to_disk_enabled"),
        host_spill_limit=SP.value(session, "spill_host_memory_bytes"),
        query_id=query_id)
