"""Per-query memory accounting + host-RAM spill.

Reference analog: ``memory/MemoryPool.java`` (per-node pool with per-query
reservations), ``lib/trino-memory-context`` (the AggregatedMemoryContext
tree charged by operators), ``execution/MemoryRevokingScheduler.java:48``
(pool pressure -> revoke largest revocable operators) and
``spiller/FileSingleStreamSpiller.java`` (the spill target).

TPU redesign: the scarce resource is device HBM and the spill target is
host RAM — a device->host transfer of retained ``DevicePage``s into numpy
arrays, not a file write.  Stateful operators (aggregation partials, join
build pages, sort buffers) charge the padded byte size of every retained
page to a per-query ``QueryMemoryPool``; a reservation that would exceed
``query_max_memory_bytes`` first revokes revocable contexts largest-first
(when ``spill_enabled``), then fails the query with
EXCEEDED_MEMORY_LIMIT if still over — the same admission discipline as
the reference pool's blocking reserve, made synchronous because our
drivers are synchronous.

Locking: the pool lock and context locks are never held together —
revoke callbacks run under the victim context's lock only (so they can't
stall other threads' reserve/free), and pool bookkeeping for the freed
bytes happens after the context lock is released.  Operators must mutate
spillable state only under their context lock so a revoke from another
thread cannot interleave with ``add_input``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..types import TrinoError


class MemoryExceededError(TrinoError):
    def __init__(self, requested: int, reserved: int, limit: int):
        super().__init__(
            f"Query exceeded per-query memory limit of {limit} bytes "
            f"(reserved {reserved}, requested {requested}); "
            "raise query_max_memory_bytes or enable spill_enabled",
            "EXCEEDED_LOCAL_MEMORY_LIMIT")
        self.requested = requested
        self.reserved = reserved
        self.limit = limit


def device_page_bytes(page) -> int:
    """Accounted HBM footprint of a DevicePage: padded columns + null
    masks + the valid mask."""
    cap = page.capacity
    total = cap  # valid mask (bool = 1 byte)
    for c, n in zip(page.cols, page.nulls):
        total += cap * c.dtype.itemsize
        total += cap  # null mask
    return total


class SpilledPage:
    """A DevicePage parked in host RAM.

    Live lanes are compacted to the smallest power-of-two bucket: device
    pages are often mostly dead lanes (filtered rows, partial-aggregation
    outputs padded to their input capacity), so compaction shrinks both
    the host footprint and — more importantly — the HBM needed to bring
    the page back."""

    __slots__ = ("types", "cols", "nulls", "valid", "dictionaries")

    def __init__(self, page):
        from ..block import padded_size

        valid = np.asarray(page.valid)
        keep = np.nonzero(valid)[0]
        cap = padded_size(len(keep))
        self.types = list(page.types)
        self.dictionaries = list(page.dictionaries)
        if cap < valid.shape[0]:
            k = len(keep)
            self.cols = []
            self.nulls = []
            for c, n in zip(page.cols, page.nulls):
                cc = np.zeros(cap, dtype=np.asarray(c).dtype)
                cc[:k] = np.asarray(c)[keep]
                nn = np.zeros(cap, dtype=bool)
                nn[:k] = np.asarray(n)[keep]
                self.cols.append(cc)
                self.nulls.append(nn)
            v = np.zeros(cap, dtype=bool)
            v[:k] = True
            self.valid = v
        else:
            self.cols = [np.asarray(c) for c in page.cols]
            self.nulls = [np.asarray(n) for n in page.nulls]
            self.valid = valid

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def to_device(self):
        import jax.numpy as jnp

        from ..block import DevicePage

        return DevicePage(list(self.types),
                          [jnp.asarray(c) for c in self.cols],
                          [jnp.asarray(n) for n in self.nulls],
                          jnp.asarray(self.valid),
                          list(self.dictionaries))


def spill_pages(pages: List) -> int:
    """Convert DevicePage entries to SpilledPage in place (caller holds
    the owning context's lock); returns the HBM bytes freed."""
    from ..block import DevicePage

    freed = 0
    for i, p in enumerate(pages):
        if isinstance(p, DevicePage):
            freed += device_page_bytes(p)
            pages[i] = SpilledPage(p)
    return freed


def reserve_and_append(ctx: "OperatorMemoryContext", pages: List, page):
    """The add_input discipline shared by spillable operators: charge the
    page, then publish it to the revocable list under the context lock."""
    ctx.reserve(device_page_bytes(page))
    with ctx.lock:
        pages.append(page)


def prepare_finish(ctx: "OperatorMemoryContext", pages: List):
    """Shared finish-time transition for spillable operators: their pages
    stop being revocable (the finish pass owns them), so if the finish
    transient (~2x total for concat + result) would not fit alongside the
    current reservations, park everything on host first — spill compacts
    dead lanes, so totals are recomputed from parked sizes (= what
    re-upload actually costs).  Returns (total, uploads)."""
    pool = ctx.pool
    with ctx.lock:
        total = sum(device_page_bytes(p) for p in pages)
        uploads = sum(device_page_bytes(p) for p in pages
                      if isinstance(p, SpilledPage))
        freed = 0
        if pool.spill_enabled and \
                pool.reserved + uploads + 2 * total > pool.max_bytes:
            freed = spill_pages(pages)
            total = sum(device_page_bytes(p) for p in pages)
            uploads = total
        # clear the callback INSIDE the lock: a concurrent pool revoke
        # between the totals above and here would invalidate them
        ctx.set_revoke_callback(None)
    if freed:
        pool.record_spill(freed)
        ctx.free(freed)
    return total, uploads


class OperatorMemoryContext:
    """One operator's slice of the query pool (reference:
    ``memory/context/LocalMemoryContext``).

    ``lock`` guards the owner's spillable state; a revoke callback runs
    under it.  ``reserve``/``free`` must be called WITHOUT holding it.
    """

    def __init__(self, pool: "QueryMemoryPool", name: str):
        self.pool = pool
        self.name = name
        self.lock = threading.RLock()
        self.reserved = 0
        self.revocable = 0          # portion of reserved that revoke can free
        self._revoke_cb: Optional[Callable[[], int]] = None

    def set_revoke_callback(self, cb: Callable[[], int]):
        """cb() spills the owner's revocable state to host and returns the
        bytes freed (reference: Operator.startMemoryRevoke)."""
        self._revoke_cb = cb

    def reserve(self, nbytes: int, revocable: bool = True):
        if nbytes <= 0:
            return
        self.pool._reserve(self, nbytes, revocable)

    def free(self, nbytes: int, revocable: bool = True):
        if nbytes <= 0:
            return
        self.pool._free(self, nbytes, revocable)

    def close(self):
        if self.reserved:
            self.pool._free(self, self.reserved, revocable=False)
            self.revocable = 0


class QueryMemoryPool:
    """Per-query HBM accounting with synchronous revocation.

    Reference: ``memory/MemoryPool.java`` + ``QueryContext`` — collapsed
    to one pool per query because device HBM is per-process here.
    """

    def __init__(self, max_bytes: int, spill_enabled: bool = False):
        self.max_bytes = int(max_bytes)
        self.spill_enabled = spill_enabled
        self.reserved = 0
        self.peak_bytes = 0
        self.spill_events = 0
        self.spilled_bytes = 0
        self._lock = threading.Lock()
        self._contexts: List[OperatorMemoryContext] = []

    def create_context(self, name: str) -> OperatorMemoryContext:
        ctx = OperatorMemoryContext(self, name)
        with self._lock:
            self._contexts.append(ctx)
        return ctx

    # -- internal (called by contexts) ----------------------------------

    def _reserve(self, ctx: OperatorMemoryContext, nbytes: int,
                 revocable: bool):
        with self._lock:
            if self.reserved + nbytes <= self.max_bytes:
                self._admit_locked(ctx, nbytes, revocable)
                return
            if not self.spill_enabled:
                raise MemoryExceededError(nbytes, self.reserved,
                                          self.max_bytes)
            # requester's own state first: self-revoke is deadlock-free
            # (its RLock is reentrant on the calling thread) and the
            # largest state usually belongs to the operator asking for
            # more
            candidates = sorted(self._contexts,
                                key=lambda c: (c is not ctx, -c.revocable))
        # Revoke OUTSIDE the pool lock: callbacks move whole operator
        # states device->host, and other threads' reserve/free must not
        # serialize behind that transfer (reference:
        # MemoryRevokingScheduler revokes asynchronously).
        for c in candidates:
            with self._lock:
                if self.reserved + nbytes <= self.max_bytes:
                    break
            if c.revocable <= 0:
                continue
            with c.lock:
                cb = c._revoke_cb
                freed = cb() if cb is not None else 0
            if freed > 0:
                self.record_spill(freed)
                self._free(c, freed, revocable=True)
        with self._lock:
            if self.reserved + nbytes > self.max_bytes:
                raise MemoryExceededError(nbytes, self.reserved,
                                          self.max_bytes)
            self._admit_locked(ctx, nbytes, revocable)

    def _admit_locked(self, ctx, nbytes, revocable):
        self.reserved += nbytes
        ctx.reserved += nbytes
        if revocable:
            ctx.revocable += nbytes
        self.peak_bytes = max(self.peak_bytes, self.reserved)

    def _free(self, ctx: OperatorMemoryContext, nbytes: int,
              revocable: bool):
        with self._lock:
            self._free_locked(ctx, nbytes, revocable)

    def _free_locked(self, ctx, nbytes, revocable):
        nbytes = min(nbytes, ctx.reserved)
        self.reserved -= nbytes
        ctx.reserved -= nbytes
        if revocable:
            ctx.revocable = max(0, ctx.revocable - nbytes)

    def record_spill(self, freed: int):
        with self._lock:
            self.spill_events += 1
            self.spilled_bytes += freed

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {
            "reserved_bytes": self.reserved,
            "peak_bytes": self.peak_bytes,
            "max_bytes": self.max_bytes,
            "spill_events": self.spill_events,
            "spilled_bytes": self.spilled_bytes,
        }


def pool_from_session(session) -> QueryMemoryPool:
    from .. import session_properties as SP

    return QueryMemoryPool(SP.value(session, "query_max_memory_bytes"),
                           SP.value(session, "spill_enabled"))
