"""Page wire serde: the cross-process exchange format.

Reference analog: ``execution/buffer/PagesSerdeFactory.java:24`` +
``PageSerializer.java:17-19,76`` / ``PageDeserializer.java`` — block
encodings in a compressed, checksummed frame.  Differences driven by the
TPU-first data model: every block is one flat fixed-width buffer (string
columns are int32 dictionary codes), so the encoding is just
dtype-tagged raw buffers + a packed null bitmap; compression is zlib
level 1 (lz4 is not in this image); and dictionary POOLS ship once per
(stream, channel, pool) — subsequent pages carry only the pool id, the
"dictionary shipped once per channel" contract of the device exchange
applied to the wire.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..block import Block, Dictionary, Page

_MAGIC = 0x54505047  # "TPPG"
_SPILL_MAGIC = 0x54505350  # "TPSP"


def spill_frame(cols: List[np.ndarray], nulls: List[np.ndarray],
                valid: np.ndarray, compress: bool = True) -> bytes:
    """One disk-spill frame: dtype-tagged raw buffers in a compressed,
    CRC-checksummed envelope — the page-frame discipline applied to a
    parked SpilledPage's arrays (reference:
    ``spiller/FileSingleStreamSpiller``'s serialized page stream).
    Dictionaries do NOT ride along: spill files are read back by the
    process that wrote them, where pools are shared host objects.

    In-memory convenience over ``_write_spill_stream`` — the ONE
    encoder of the spill format (shared with ``write_spill_file``)."""
    buf = io.BytesIO()
    _write_spill_stream(buf, cols, nulls, valid, compress)
    return buf.getvalue()


def parse_spill_frame(frame: bytes):
    """Inverse of ``spill_frame``; raises on any corruption (bad magic,
    CRC mismatch, short frame) — a torn spill file must fail loudly,
    never yield partial rows. In-memory convenience over
    ``_read_spill_stream``, the ONE decoder of the spill format."""
    return _read_spill_stream(io.BytesIO(frame))


#: read/compress granularity for the streaming spill paths: bounds the
#: transient RAM of a spill write/read to one chunk + one array instead
#: of the whole frame (the ack-cursor "stream, don't materialize" shape
#: applied to the disk tier)
_SPILL_CHUNK = 1 << 20


def _write_spill_stream(f, cols, nulls, valid, compress: bool):
    """STREAMING spill encoder (the one writer of the format): arrays
    feed one compressobj in bounded chunks straight onto ``f`` (never
    the whole frame in RAM), CRC accumulates over the compressed body
    as written and is patched into the header afterwards. ``f`` must be
    positioned at 0 and seekable."""
    arrays = [np.ascontiguousarray(a) for a in [*cols, *nulls, valid]]
    raw_len = 2 + sum(1 + len(a.dtype.str.encode()) + 4 + a.nbytes
                      for a in arrays)
    comp = zlib.compressobj(1) if compress else None
    crc = 0
    # CRC placeholder: the body streams first, the header's crc field
    # is patched once the last byte is known
    f.write(struct.pack("<IBII", _SPILL_MAGIC,
                        1 if compress else 0, raw_len, 0))

    def emit(data):
        nonlocal crc
        out = comp.compress(data) if comp is not None else data
        if out:
            crc = zlib.crc32(out, crc)
            f.write(out)

    emit(struct.pack("<H", len(cols)))
    for a in arrays:
        tag = a.dtype.str.encode()
        emit(struct.pack("<B", len(tag)) + tag
             + struct.pack("<I", a.nbytes))
        mv = memoryview(a).cast("B")
        for off in range(0, len(mv), _SPILL_CHUNK):
            emit(mv[off:off + _SPILL_CHUNK])
    if comp is not None:
        tail = comp.flush()
        crc = zlib.crc32(tail, crc)
        f.write(tail)
    f.flush()
    f.seek(9)  # <IBII: crc sits after magic(4)+flag(1)+raw_len(4)
    f.write(struct.pack("<I", crc))
    f.flush()


def write_spill_file(path: str, cols, nulls, valid,
                     compress: bool = True) -> int:
    """Atomic streaming spill write: ``_write_spill_stream`` onto a
    sibling temp file, fsync, rename — a crash mid-write leaves no
    half-frame under the final name."""
    import os

    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        _write_spill_stream(f, cols, nulls, valid, compress)
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return os.path.getsize(path)


def _read_spill_stream(f):
    """STREAMING spill decoder (the one reader of the format):
    decompress + parse in bounded chunks (the unparsed tail never
    exceeds one array + one chunk). Arrays are handed back only after
    the whole body's CRC verified — corruption still fails loudly
    before any consumer sees rows."""
    head = f.read(13)
    if len(head) < 13:
        raise T.TrinoError("spill frame truncated",
                           "GENERIC_INTERNAL_ERROR")
    magic, compressed, raw_len, crc = struct.unpack("<IBII", head)
    if magic != _SPILL_MAGIC:
        raise T.TrinoError("bad spill frame magic",
                           "GENERIC_INTERNAL_ERROR")
    decomp = zlib.decompressobj() if compressed else None
    state = {"crc": 0, "raw": 0, "eof": False}
    buf = bytearray()

    def feed() -> bool:
        if state["eof"]:
            return False
        chunk = f.read(_SPILL_CHUNK)
        try:
            if not chunk:
                state["eof"] = True
                if decomp is not None:
                    tail = decomp.flush()
                    state["raw"] += len(tail)
                    buf.extend(tail)
                return False
            state["crc"] = zlib.crc32(chunk, state["crc"])
            out = decomp.decompress(chunk) if decomp is not None \
                else chunk
        except zlib.error as e:
            # zlib's own integrity check can fire before our CRC
            # comparison does — same loud-failure contract
            raise T.TrinoError(f"spill frame corrupt: {e}",
                               "GENERIC_INTERNAL_ERROR")
        state["raw"] += len(out)
        buf.extend(out)
        return True

    def take(n: int, writable: bool = False):
        while len(buf) < n:
            if not feed():
                raise T.TrinoError("spill frame truncated",
                                   "GENERIC_INTERNAL_ERROR")
        # a bytearray slice is already a fresh writable bytearray —
        # keeps the resulting ndarray writable (consumers re-upload
        # and may mutate) without a second copy; headers become bytes
        out = buf[:n] if writable else bytes(buf[:n])
        del buf[:n]
        return out

    try:
        (ncols,) = struct.unpack("<H", take(2))
        arrays: List[np.ndarray] = []
        for _ in range(2 * ncols + 1):
            (tlen,) = struct.unpack("<B", take(1))
            dtype = np.dtype(take(tlen).decode())
            (nbytes,) = struct.unpack("<I", take(4))
            arrays.append(np.frombuffer(take(nbytes, writable=True),
                                        dtype=dtype))
        while feed():
            pass
    except (ValueError, TypeError, UnicodeDecodeError,
            struct.error) as e:
        # parsing runs AHEAD of the full-body CRC check (the read
        # is incremental), so corrupted bytes can surface here
        # first — keep the loud typed-failure contract
        raise T.TrinoError(f"spill frame corrupt: {e}",
                           "GENERIC_INTERNAL_ERROR")
    if state["crc"] != crc:
        raise T.TrinoError("spill frame checksum mismatch",
                           "GENERIC_INTERNAL_ERROR")
    if state["raw"] != raw_len:
        raise T.TrinoError("spill frame length mismatch",
                           "GENERIC_INTERNAL_ERROR")
    return arrays[:ncols], arrays[ncols:2 * ncols], arrays[2 * ncols]


def read_spill_file(path: str):
    """Streaming spill read off disk (``_read_spill_stream`` over the
    open file: bounded chunks, CRC verified before rows are handed
    back)."""
    with open(path, "rb") as f:
        return _read_spill_stream(f)


def _jsonable(v):
    """Pool-entry -> JSON: tuples become tagged lists (nesting survives
    the round trip as tuples, not lists) and Decimals become tagged
    strings."""
    from decimal import Decimal

    if isinstance(v, tuple):
        return ["t", [_jsonable(x) for x in v]]
    if isinstance(v, Decimal):
        return ["d", str(v)]
    return ["v", v]


def _from_jsonable(doc):
    from decimal import Decimal

    tag, payload = doc
    if tag == "t":
        return tuple(_from_jsonable(x) for x in payload)
    if tag == "d":
        return Decimal(payload)
    return payload


def _wire_signature(t: T.Type) -> str:
    """Type -> wire string. TIMESTAMP WITH TIME ZONE carries its zone
    (case-sensitive) in brackets; ``parse_type`` alone would drop it."""
    if t.is_timestamp_tz:
        return f"timestamptz[{t.zone}]"
    return str(t)


def _parse_wire_signature(sig: str) -> T.Type:
    if sig.startswith("timestamptz[") and sig.endswith("]"):
        return T.timestamp_tz_type(sig[len("timestamptz["):-1])
    return T.parse_type(sig)


class PageSerializer:
    """One serializer per output stream (per consumer); tracks which
    dictionary pools were already shipped on each channel."""

    def __init__(self, compress: bool = True):
        self.compress = compress
        self._sent_pools: Dict[Tuple[int, int], int] = {}
        self._next_pool_id = 1

    def serialize(self, page: Page) -> bytes:
        parts: List[bytes] = [struct.pack("<IH", page.num_rows,
                                          page.channel_count)]
        for ch, b in enumerate(page.blocks):
            b = b.numpy()
            sig = _wire_signature(b.type).encode()
            flags = 0
            dict_payload = b""
            if b.dictionary is not None:
                key = (ch, id(b.dictionary))
                pool_id = self._sent_pools.get(key)
                if pool_id is None:
                    pool_id = self._next_pool_id
                    self._next_pool_id += 1
                    # pool contents ride along exactly once per stream;
                    # later pages reference the id only.  Pools are
                    # append-only, so ship the CURRENT length and send a
                    # delta if it grew (scan pools grow across pages).
                    self._sent_pools[key] = pool_id
                    sent_len = 0
                else:
                    sent_len = self._sent_pools.get((ch, -pool_id), 0)
                values = b.dictionary.values
                delta = list(values[sent_len:])
                # record what was ACTUALLY sent: the pool may grow
                # concurrently (Dictionary.code is thread-safe growth),
                # and len(values) re-read here could exceed the slice
                self._sent_pools[(ch, -pool_id)] = sent_len + len(delta)
                if b.type.is_pooled and not b.type.is_string:
                    # composite pool entries (tuples) ship as JSON;
                    # flag bit 4 tells the reader to decode them back
                    enc = [json.dumps(_jsonable(v)).encode()
                           for v in delta]
                    flags |= 4
                else:
                    enc = [v.encode() for v in delta]
                dict_payload = struct.pack("<III", pool_id, sent_len,
                                           len(enc))
                dict_payload += b"".join(
                    struct.pack("<I", len(e)) + e for e in enc)
                flags |= 2
            data = np.ascontiguousarray(b.data).tobytes()
            if b.nulls is not None:
                flags |= 1
                nulls = np.packbits(b.nulls.astype(np.uint8)).tobytes()
            else:
                nulls = b""
            parts.append(struct.pack("<BH", flags, len(sig)))
            parts.append(sig)
            parts.append(dict_payload)
            parts.append(struct.pack("<I", len(data)))
            parts.append(data)
            parts.append(struct.pack("<I", len(nulls)))
            parts.append(nulls)
        raw = b"".join(parts)
        body = zlib.compress(raw, 1) if self.compress else raw
        header = struct.pack("<IBII", _MAGIC, 1 if self.compress else 0,
                             len(raw), zlib.crc32(body))
        return header + body


class PageDeserializer:
    """One per input stream; reconstructs dictionary pools by id."""

    def __init__(self):
        self._pools: Dict[Tuple[int, int], Dictionary] = {}

    def deserialize(self, frame: bytes) -> Page:
        magic, compressed, raw_len, crc = struct.unpack_from("<IBII",
                                                             frame, 0)
        if magic != _MAGIC:
            raise T.TrinoError("bad page frame magic",
                               "GENERIC_INTERNAL_ERROR")
        body = frame[13:]
        if zlib.crc32(body) != crc:
            raise T.TrinoError("page frame checksum mismatch",
                               "GENERIC_INTERNAL_ERROR")
        raw = zlib.decompress(body) if compressed else body
        if len(raw) != raw_len:
            raise T.TrinoError("page frame length mismatch",
                               "GENERIC_INTERNAL_ERROR")
        off = 0
        num_rows, nch = struct.unpack_from("<IH", raw, off)
        off += 6
        blocks = []
        for ch in range(nch):
            flags, sig_len = struct.unpack_from("<BH", raw, off)
            off += 3
            sig = raw[off:off + sig_len].decode()
            off += sig_len
            type_ = _parse_wire_signature(sig)
            dictionary: Optional[Dictionary] = None
            if flags & 2:
                pool_id, sent_len, n_delta = struct.unpack_from(
                    "<III", raw, off)
                off += 12
                values = []
                for _ in range(n_delta):
                    (vlen,) = struct.unpack_from("<I", raw, off)
                    off += 4
                    text = raw[off:off + vlen].decode()
                    values.append(_from_jsonable(json.loads(text))
                                  if flags & 4 else text)
                    off += vlen
                dictionary = self._pools.get((ch, pool_id))
                if dictionary is None:
                    dictionary = Dictionary()
                    self._pools[(ch, pool_id)] = dictionary
                if len(dictionary) < sent_len + len(values):
                    # append the delta POSITIONALLY (pools may repeat
                    # values — Dictionary.aligned — so dedup via code()
                    # would misalign codes)
                    for v in values[len(dictionary) - sent_len:]:
                        dictionary._index.setdefault(
                            v, len(dictionary.values))
                        dictionary.values.append(v)
                    dictionary._sort_rank = None
            (dlen,) = struct.unpack_from("<I", raw, off)
            off += 4
            data = np.frombuffer(raw, dtype=type_.storage, count=num_rows,
                                 offset=off).copy()
            off += dlen
            (nlen,) = struct.unpack_from("<I", raw, off)
            off += 4
            nulls = None
            if flags & 1:
                bits = np.frombuffer(raw, dtype=np.uint8, count=nlen,
                                     offset=off)
                nulls = np.unpackbits(bits, count=num_rows).astype(bool)
            off += nlen
            blocks.append(Block(type_, data, nulls, dictionary))
        return Page(blocks, num_rows)
