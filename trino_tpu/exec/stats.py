"""Query/stage/task stats tree for distributed execution.

Reference analog: ``execution/QueryStats.java`` / ``StageInfo`` /
``TaskStats`` / ``OperatorStats`` — the hierarchy the coordinator
aggregates from task status updates and serves on ``/v1/query/{id}``
and through EXPLAIN ANALYZE.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .driver import OperatorStats


@dataclass
class TaskStatsTree:
    task_id: int
    operators: List[OperatorStats] = field(default_factory=list)

    @property
    def wall_ns(self) -> int:
        return sum(o.wall_ns for o in self.operators)

    @property
    def output_rows(self) -> int:
        # the tail operator is a sink (output buffer / collector): stage
        # output = rows produced by the operator feeding it
        if len(self.operators) >= 2:
            return self.operators[-2].output_rows
        return self.operators[-1].output_rows if self.operators else 0

    def to_dict(self) -> dict:
        return {
            "task_id": self.task_id,
            "wall_ms": round(self.wall_ns / 1e6, 2),
            "operators": [
                {"name": o.name, "rows": o.output_rows,
                 "pages": o.output_pages,
                 "wall_ms": round(o.wall_ns / 1e6, 2),
                 "compiles": o.compile_count,
                 **({"flops": o.flops,
                     "device_bytes": o.device_bytes,
                     "compile_ms": round(o.compile_ms, 2)}
                    if (o.flops or o.compile_ms) else {}),
                 **({"exchange": o.metrics} if o.metrics else {})}
                for o in self.operators],
        }


@dataclass
class StageStatsTree:
    stage_id: int
    partitioning: str
    output_kind: str
    tasks: List[TaskStatsTree] = field(default_factory=list)
    #: output-boundary exchange skew stats (device collective or host
    #: buffer — the same dict surface either way), attached by the
    #: runner once the query completes
    exchange: Optional[Dict] = None

    def to_dict(self) -> dict:
        return {
            "stage_id": self.stage_id,
            "partitioning": self.partitioning,
            "output_kind": self.output_kind,
            "exchange": self.exchange,
            "tasks": [t.to_dict() for t in self.tasks],
        }

    def exchange_line(self) -> Optional[str]:
        """One EXPLAIN ANALYZE line for this stage's output exchange:
        identical shape for the device-collective and host paths."""
        ex = self.exchange
        if not ex:
            return None
        parts = [f"exchange [{ex.get('kind', '?')}]:",
                 f"{ex.get('rows', 0)} rows,",
                 f"skew {ex.get('skew_ratio', 0.0):.2f}"]
        if ex.get("sizing") is not None:
            parts.append(f", sizing={ex['sizing']}")
        if ex.get("per_dest") is not None:
            parts.append(f", per_dest={ex['per_dest']}")
        parts.append(f", retries={ex.get('a2a_retries', 0)}")
        if ex.get("splits"):
            # hot partitions split across receiver lanes, e.g.
            # "splits=1x4 (lane skew 1.02)" — the receive-side answer
            # to one partition capping the collective
            parts.append(
                f", splits={ex['splits']}x{ex.get('split_ways', 1)}"
                f" (lane skew {ex.get('lane_skew_ratio', 0.0):.2f})")
        if ex.get("rebalances") is not None:
            parts.append(
                f", rebalances={ex['rebalances']}"
                f" ({ex.get('scaled_partitions', 0)} scaled/"
                f"{ex.get('logical_partitions', 0)} logical -> "
                f"{ex.get('writer_lanes', 0)} lanes)")
        if ex.get("data_collectives"):
            parts.append(
                f", collectives={ex.get('count_collectives', 0)}"
                f"+{ex['data_collectives']}")
        if ex.get("bytes_moved") is not None:
            parts.append(f", {ex['bytes_moved']} bytes moved")
        return " ".join(p.strip() for p in parts).replace(" ,", ",")


@dataclass
class QueryStatsTree:
    stages: List[StageStatsTree] = field(default_factory=list)
    wall_ms: float = 0.0
    memory: Optional[Dict] = None
    #: ClusterMemoryManager.cluster_stats(): worker count, cluster-wide
    #: reserved/max bytes, blocked nodes, low-memory kills + policy —
    #: the coordinator's memory-governance view of this query's run
    cluster_memory: Optional[Dict] = None
    #: self-healing counters for this query (fault.RecoveryStats dict):
    #: attempts, retries by error type, backoff wall-time, workers
    #: replaced, speculative launches/wins — attached by the process
    #: runner so EXPLAIN ANALYZE and the bench surface recovery
    recovery: Optional[Dict] = None
    #: finished distributed-trace spans (telemetry.tracing dicts):
    #: coordinator root/plan/fragment/attempt spans + the worker
    #: task/operator spans piggybacked on task responses — the timeline
    #: the Chrome-trace export and the Trace: line render
    trace: Optional[List[dict]] = None
    #: history-based statistics: node-fingerprint -> estimated rows
    #: (as planned, history consulted) so render() can print per-node
    #: Q-error beside the actual, plus the worst-misestimate summary
    estimates: Optional[Dict[str, float]] = None
    worst_misestimate: Optional[Dict] = None

    def to_dict(self) -> dict:
        return {
            "wall_ms": round(self.wall_ms, 2),
            "memory": self.memory,
            "cluster_memory": self.cluster_memory,
            "recovery": self.recovery,
            "trace": self.trace,
            "stages": [s.to_dict() for s in self.stages],
        }

    def trace_line(self) -> Optional[str]:
        """One EXPLAIN ANALYZE line: span count + the critical path
        through the assembled trace tree; None when tracing was off."""
        if not self.trace:
            return None
        from ..telemetry.tracing import trace_line

        return trace_line(self.trace)

    def cluster_memory_line(self) -> Optional[str]:
        """One EXPLAIN ANALYZE line for the cluster memory view; None
        when no worker reported a pool (local runs stay clean)."""
        cm = self.cluster_memory
        if not cm or not cm.get("workers"):
            return None
        return (f"Cluster memory: {cm.get('total_reserved_bytes', 0)} / "
                f"{cm.get('total_max_bytes', 0)} bytes reserved over "
                f"{cm['workers']} workers, "
                f"{cm.get('blocked_nodes', 0)} blocked, "
                f"{cm.get('kills', 0)} kills "
                f"[{cm.get('killer_policy', 'none')}]")

    def recovery_line(self) -> Optional[str]:
        """One EXPLAIN ANALYZE line summarizing what self-healing did;
        None when the query saw no faults (keep clean plans clean)."""
        r = self.recovery
        if not r:
            return None
        interesting = (r.get("task_retries", 0) or
                       r.get("query_retries", 0) or
                       r.get("workers_replaced", 0) or
                       r.get("speculative_launched", 0))
        if not interesting:
            return None
        by_type = ", ".join(f"{k}={v}" for k, v in
                            sorted(r.get("retries_by_type", {}).items()))
        return (f"Recovery: {r.get('task_attempts', 0)} task attempts, "
                f"{r.get('task_retries', 0)} task retries + "
                f"{r.get('query_retries', 0)} query retries"
                + (f" [{by_type}]" if by_type else "")
                + f", backoff {r.get('backoff_wall_s', 0.0):.2f}s, "
                f"workers replaced {r.get('workers_replaced', 0)}, "
                f"speculative {r.get('speculative_wins', 0)}/"
                f"{r.get('speculative_launched', 0)} won")

    def render(self) -> List[str]:
        """EXPLAIN ANALYZE text: stages top-down with per-task operator
        rows/pages/wall (reference: planprinter/PlanPrinter +
        TextRenderer)."""
        lines: List[str] = []
        lines.append(f"Query: {self.wall_ms:.1f}ms")
        if self.memory:
            disk = ""
            if self.memory.get("disk_spill_events") is not None:
                disk = (f", disk {self.memory['disk_spill_events']} "
                        f"files "
                        f"({self.memory.get('disk_spilled_bytes', 0)} "
                        f"bytes)")
            lines.append(
                f"Memory: peak {self.memory.get('peak_bytes', 0)} bytes, "
                f"{self.memory.get('spill_events', 0)} spills "
                f"({self.memory.get('spilled_bytes', 0)} bytes)" + disk)
        cm_line = self.cluster_memory_line()
        if cm_line:
            lines.append(cm_line)
        rec_line = self.recovery_line()
        if rec_line:
            lines.append(rec_line)
        tr_line = self.trace_line()
        if tr_line:
            lines.append(tr_line)
        for s in sorted(self.stages, key=lambda s: -s.stage_id):
            total_rows = sum(t.output_rows for t in s.tasks)
            lines.append(
                f"Stage {s.stage_id} [{s.partitioning} -> "
                f"{s.output_kind}] {len(s.tasks)} tasks, "
                f"{total_rows} rows out")
            ex_line = s.exchange_line()
            if ex_line:
                lines.append("    " + ex_line)
            # aggregate the per-operator view across tasks (positional:
            # every task of a stage runs the same operator chain)
            agg: Dict[int, OperatorStats] = {}
            for t in s.tasks:
                for i, o in enumerate(t.operators):
                    a = agg.get(i)
                    if a is None:
                        agg[i] = OperatorStats(o.name, o.output_rows,
                                               o.output_pages, o.wall_ns,
                                               o.compile_count,
                                               flops=o.flops,
                                               device_bytes=o.device_bytes,
                                               compile_ms=o.compile_ms,
                                               metrics=o.metrics,
                                               node_fp=o.node_fp)
                    else:
                        a.output_rows += o.output_rows
                        a.output_pages += o.output_pages
                        a.wall_ns += o.wall_ns
                        a.compile_count += o.compile_count
                        a.flops += o.flops
                        a.device_bytes += o.device_bytes
                        a.compile_ms += o.compile_ms
                        # exchange metrics describe the ONE shared
                        # boundary object; every task reports the same
                        # dict, so keep the first
                        if a.metrics is None:
                            a.metrics = o.metrics
            for i in sorted(agg):
                line = "    " + agg[i].line()
                est = (self.estimates or {}).get(agg[i].node_fp) \
                    if agg[i].node_fp is not None else None
                if est is not None:
                    from ..telemetry.stats_store import q_error

                    line += (f" [est {est:.0f} rows, q="
                             f"{q_error(est, agg[i].output_rows):.2f}]")
                lines.append(line)
            for t in s.tasks:
                lines.append(f"    task {t.task_id}: "
                             f"{t.output_rows} rows, "
                             f"{t.wall_ns / 1e6:.1f}ms")
        if self.worst_misestimate:
            w = self.worst_misestimate
            lines.append(
                f"Worst misestimate: {w['name']} est "
                f"{w['est_rows']:.0f} rows, actual {w['actual_rows']} "
                f"(q={w['qerror']:.2f})")
        return lines
