"""TaskExecutor: cooperative time-sharing of task work across a shared
worker pool.

Reference analog: ``execution/executor/TaskExecutor.java:82,491-519`` —
a fixed thread pool pulls prioritized entries from a
``MultilevelSplitQueue`` (5 levels bucketed by accumulated CPU time,
level 0 scheduled most often), runs each for a bounded quantum, and
requeues it at its new level. Long-running queries sink to deeper
levels, so short queries keep low latency under concurrency.

TPU adaptation: the schedulable unit is a GENERATOR — task code yields
at page boundaries (one driver ``process()`` call per step), and the
executor times each step to accumulate the entry's scheduled nanos.

Blocked-entry state (the streaming scheduler's requirement): a task
that cannot progress yields ``Blocked(tokens)`` — listen tokens from
its blocked operators (empty exchange channel, full output buffer) —
and the entry PARKS instead of re-entering the queue: the first token
to fire re-offers it (reference: ``operator/Driver.java:380-486``
blocked futures + TaskExecutor's waiting splits). A reaper re-offers
parked entries after a few seconds as a safety net, so a lost wakeup
degrades to slow polling, never deadlock.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Iterator, List, Optional

#: level i holds entries with accumulated scheduled time >= threshold
LEVEL_THRESHOLDS_S = (0.0, 1.0, 10.0, 60.0, 300.0)
#: scheduling weight of each level (reference: LEVEL_CONTRIBUTION_CAP /
#: levelMinPriority scheme, compressed to fixed 2:1 ratios)
LEVEL_WEIGHTS = (16, 8, 4, 2, 1)


class TaskFuture:
    def __init__(self):
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def _finish(self, error: Optional[BaseException] = None):
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("task did not finish in time")
        if self._error is not None:
            raise self._error


class Blocked:
    """Yield value signaling the task cannot progress; the executor
    parks the entry until one of the tokens fires."""

    __slots__ = ("tokens",)

    def __init__(self, tokens):
        self.tokens = list(tokens)


class _Entry:
    __slots__ = ("gen", "future", "scheduled_ns", "parked", "parked_at",
                 "park_lock")

    def __init__(self, gen: Iterator):
        self.gen = gen
        self.future = TaskFuture()
        self.scheduled_ns = 0
        self.parked = False
        self.parked_at = 0.0
        self.park_lock = threading.Lock()

    @property
    def level(self) -> int:
        s = self.scheduled_ns / 1e9
        lvl = 0
        for i, th in enumerate(LEVEL_THRESHOLDS_S):
            if s >= th:
                lvl = i
        return lvl


class MultilevelSplitQueue:
    """Five FIFO levels; ``take`` picks a level by weighted round-robin
    credits so lower levels (fresh work) run more often but deep levels
    never starve (reference: executor/MultilevelSplitQueue.java)."""

    def __init__(self):
        self._levels: List[deque] = [deque() for _ in LEVEL_THRESHOLDS_S]
        self._credits = list(LEVEL_WEIGHTS)
        self._cond = threading.Condition()
        self._closed = False

    def offer(self, entry: _Entry):
        with self._cond:
            if self._closed:
                return  # late wakeup after close: drop
            self._levels[entry.level].append(entry)
            self._cond.notify()

    def take(self) -> Optional[_Entry]:
        with self._cond:
            while True:
                if self._closed:
                    return None
                got = self._pick()
                if got is not None:
                    return got
                self._cond.wait()

    def _pick(self) -> Optional[_Entry]:
        nonempty = [i for i, lv in enumerate(self._levels) if lv]
        if not nonempty:
            return None
        # spend credits top-down; replenish when every nonempty level
        # is out of credit
        for i in nonempty:
            if self._credits[i] > 0:
                self._credits[i] -= 1
                return self._levels[i].popleft()
        for i in range(len(self._credits)):
            self._credits[i] = LEVEL_WEIGHTS[i]
        i = nonempty[0]
        self._credits[i] -= 1
        return self._levels[i].popleft()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class TaskExecutor:
    """Shared pool running task generators with per-step timing."""

    #: reaper interval / max park time before a forced re-offer
    reap_every_s = 1.0
    max_park_s = 5.0

    def __init__(self, num_threads: Optional[int] = None,
                 name: str = "task-executor"):
        self.queue = MultilevelSplitQueue()
        self._closed = False
        self._parked: set = set()
        self._parked_lock = threading.Lock()
        n = num_threads or max(1, min(8, os.cpu_count() or 1))
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(n)]
        for t in self._threads:
            t.start()
        self._reaper = threading.Thread(target=self._reap, daemon=True,
                                        name=f"{name}-reaper")
        self._reaper.start()

    def submit(self, gen: Iterator) -> TaskFuture:
        entry = _Entry(gen)
        self.queue.offer(entry)
        return entry.future

    def run_all(self, gens: List[Iterator],
                timeout: Optional[float] = None):
        """Submit a batch and wait for every task (the per-fragment
        barrier of the distributed runner)."""
        futures = [self.submit(g) for g in gens]
        errors = []
        for f in futures:
            try:
                f.result(timeout)
            except BaseException as e:  # noqa: BLE001 - propagate first
                errors.append(e)
        if errors:
            raise errors[0]

    def _unpark(self, entry: _Entry):
        """One-shot wakeup: the first firing token (or the reaper)
        re-offers the entry; later firings are no-ops."""
        with entry.park_lock:
            if not entry.parked:
                return
            entry.parked = False
        with self._parked_lock:
            self._parked.discard(entry)
        self.queue.offer(entry)

    def _park(self, entry: _Entry, blocked: Blocked):
        with entry.park_lock:
            entry.parked = True
            entry.parked_at = time.monotonic()
        with self._parked_lock:
            self._parked.add(entry)
        for token in blocked.tokens:
            token.on_ready(lambda e=entry: self._unpark(e))

    def _reap(self):
        while not self._closed:
            time.sleep(self.reap_every_s)
            now = time.monotonic()
            with self._parked_lock:
                stale = [e for e in self._parked
                         if now - e.parked_at > self.max_park_s]
            for e in stale:
                self._unpark(e)

    def _worker(self):
        while True:
            entry = self.queue.take()
            if entry is None:
                return
            t0 = time.perf_counter_ns()
            try:
                yielded = next(entry.gen)
            except StopIteration:
                entry.scheduled_ns += time.perf_counter_ns() - t0
                entry.future._finish()
                continue
            except BaseException as e:  # noqa: BLE001
                entry.future._finish(e)
                continue
            entry.scheduled_ns += time.perf_counter_ns() - t0
            if isinstance(yielded, Blocked) and yielded.tokens:
                self._park(entry, yielded)
            else:
                self.queue.offer(entry)

    def close(self):
        self._closed = True
        self.queue.close()
        with self._parked_lock:
            self._parked.clear()


_shared: Optional[TaskExecutor] = None
_shared_lock = threading.Lock()


def shared_executor() -> TaskExecutor:
    """The process-wide executor (reference: one TaskExecutor per worker
    JVM); all in-process runners time-share it."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = TaskExecutor()
        return _shared
