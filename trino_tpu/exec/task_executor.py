"""TaskExecutor: cooperative time-sharing of task work across a shared
worker pool.

Reference analog: ``execution/executor/TaskExecutor.java:82,491-519`` —
a fixed thread pool pulls prioritized entries from a
``MultilevelSplitQueue`` (5 levels bucketed by accumulated CPU time,
level 0 scheduled most often), runs each for a bounded quantum, and
requeues it at its new level. Long-running queries sink to deeper
levels, so short queries keep low latency under concurrency.

TPU adaptation: the schedulable unit is a GENERATOR — task code yields
at page boundaries (one driver ``process()`` call per step), and the
executor times each step to accumulate the entry's scheduled nanos.
There is no blocked-future machinery: stage barriers mean exchange
reads never wait mid-quantum (SURVEY §5: the stage boundary is the
checkpoint), so a step always makes progress or finishes.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Iterator, List, Optional

#: level i holds entries with accumulated scheduled time >= threshold
LEVEL_THRESHOLDS_S = (0.0, 1.0, 10.0, 60.0, 300.0)
#: scheduling weight of each level (reference: LEVEL_CONTRIBUTION_CAP /
#: levelMinPriority scheme, compressed to fixed 2:1 ratios)
LEVEL_WEIGHTS = (16, 8, 4, 2, 1)


class TaskFuture:
    def __init__(self):
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def _finish(self, error: Optional[BaseException] = None):
        self._error = error
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("task did not finish in time")
        if self._error is not None:
            raise self._error


class _Entry:
    __slots__ = ("gen", "future", "scheduled_ns")

    def __init__(self, gen: Iterator):
        self.gen = gen
        self.future = TaskFuture()
        self.scheduled_ns = 0

    @property
    def level(self) -> int:
        s = self.scheduled_ns / 1e9
        lvl = 0
        for i, th in enumerate(LEVEL_THRESHOLDS_S):
            if s >= th:
                lvl = i
        return lvl


class MultilevelSplitQueue:
    """Five FIFO levels; ``take`` picks a level by weighted round-robin
    credits so lower levels (fresh work) run more often but deep levels
    never starve (reference: executor/MultilevelSplitQueue.java)."""

    def __init__(self):
        self._levels: List[deque] = [deque() for _ in LEVEL_THRESHOLDS_S]
        self._credits = list(LEVEL_WEIGHTS)
        self._cond = threading.Condition()
        self._closed = False

    def offer(self, entry: _Entry):
        with self._cond:
            self._levels[entry.level].append(entry)
            self._cond.notify()

    def take(self) -> Optional[_Entry]:
        with self._cond:
            while True:
                if self._closed:
                    return None
                got = self._pick()
                if got is not None:
                    return got
                self._cond.wait()

    def _pick(self) -> Optional[_Entry]:
        nonempty = [i for i, lv in enumerate(self._levels) if lv]
        if not nonempty:
            return None
        # spend credits top-down; replenish when every nonempty level
        # is out of credit
        for i in nonempty:
            if self._credits[i] > 0:
                self._credits[i] -= 1
                return self._levels[i].popleft()
        for i in range(len(self._credits)):
            self._credits[i] = LEVEL_WEIGHTS[i]
        i = nonempty[0]
        self._credits[i] -= 1
        return self._levels[i].popleft()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class TaskExecutor:
    """Shared pool running task generators with per-step timing."""

    def __init__(self, num_threads: Optional[int] = None,
                 name: str = "task-executor"):
        self.queue = MultilevelSplitQueue()
        n = num_threads or max(1, min(8, os.cpu_count() or 1))
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(n)]
        for t in self._threads:
            t.start()

    def submit(self, gen: Iterator) -> TaskFuture:
        entry = _Entry(gen)
        self.queue.offer(entry)
        return entry.future

    def run_all(self, gens: List[Iterator],
                timeout: Optional[float] = None):
        """Submit a batch and wait for every task (the per-fragment
        barrier of the distributed runner)."""
        futures = [self.submit(g) for g in gens]
        errors = []
        for f in futures:
            try:
                f.result(timeout)
            except BaseException as e:  # noqa: BLE001 - propagate first
                errors.append(e)
        if errors:
            raise errors[0]

    def _worker(self):
        while True:
            entry = self.queue.take()
            if entry is None:
                return
            t0 = time.perf_counter_ns()
            try:
                next(entry.gen)
            except StopIteration:
                entry.scheduled_ns += time.perf_counter_ns() - t0
                entry.future._finish()
                continue
            except BaseException as e:  # noqa: BLE001
                entry.future._finish(e)
                continue
            entry.scheduled_ns += time.perf_counter_ns() - t0
            self.queue.offer(entry)

    def close(self):
        self.queue.close()


_shared: Optional[TaskExecutor] = None
_shared_lock = threading.Lock()


def shared_executor() -> TaskExecutor:
    """The process-wide executor (reference: one TaskExecutor per worker
    JVM); all in-process runners time-share it."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = TaskExecutor()
        return _shared
