from .ir import Call, InputRef, Literal, RowExpression  # noqa: F401
from .compiler import PageProcessor  # noqa: F401
