"""Expression compiler: RowExpression trees -> one jitted page program.

Reference analog: ``sql/gen/ExpressionCompiler.java`` + ``PageFunctionCompiler``
producing a fused filter+project ``PageProcessor``
(``operator/project/PageProcessor.java``). There the kernel is runtime JVM
bytecode; here it is a JAX trace compiled by XLA.

TPU-first string strategy: device lanes only ever hold int32 dictionary
codes. Any operation that needs string *values* (comparisons, LIKE,
substr, length, casts) is planned at construction time into a **LUT slot**:
a host-computed per-code lookup table, gathered on device. Rank LUTs give
total order for string comparisons (both sides ranked in a merged value
space), so <,=,> compile to integer compares on device.

Null semantics: every value is (raw, null-mask); functions default to
RETURN_NULL_ON_NULL; AND/OR implement three-valued logic; CASE/IF/COALESCE
evaluate all branches (vector select) — SQL-visible behavior matches lazy
evaluation because kernels never trap (div-by-zero lanes are masked).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, Dictionary, padded_size
from ..types import TrinoError, TypeError_
from . import functions as F
from .ir import Call, InputRef, Literal, ParamRef, RowExpression


def param_raw(t: T.Type, v):
    """Python literal value -> raw device scalar under type ``t`` (the
    same lowering ``_literal_raw`` bakes at trace time — template
    parameters must bind to bit-identical rawness or the batched path
    diverges from the serial oracle)."""
    if t.is_decimal:
        return np.int64(t.to_raw(v))
    if t == T.BOOLEAN:
        return np.bool_(v)
    return np.asarray(v, dtype=t.storage)[()]


def pad_lut(raw: np.ndarray, minimum: int = 8) -> np.ndarray:
    """Pad a host LUT to a power-of-two length so LUT uploads hit a
    bounded set of jit shapes (the same bucketing ``padded_size``
    applies to row counts).  Shared by ``PageProcessor._fill_luts`` and
    the batched executor's rank/inverse LUT uploads."""
    cap = padded_size(max(len(raw), 1), minimum=minimum)
    arr = np.zeros(cap, dtype=raw.dtype)
    arr[:len(raw)] = raw
    return arr


def _is_string(t: T.Type) -> bool:
    return t.is_string


def _is_pooled(t: T.Type) -> bool:
    """Strings AND arrays: device codes into a host value pool."""
    return getattr(t, "is_pooled", False)


class _StrView:
    """Plan-time view of a string-valued expression: codes come from one
    input channel (or a literal), values are a host transform chain over
    that channel's dictionary."""

    __slots__ = ("channel", "transform", "literal")

    def __init__(self, channel=None, transform=None, literal=None):
        self.channel = channel            # int | None
        self.transform = transform        # Callable[[str|None], str|None] | None
        self.literal = literal            # str | None (literal value)

    def values(self, dicts) -> List[Optional[str]]:
        if self.channel is None:
            return [self.literal]
        d = dicts[self.channel]
        vals = d.values if d is not None else []
        if self.transform is None:
            return list(vals)
        return [None if v is None else self.transform(v) for v in vals]


class _Slot:
    """A LUT input to the jitted program: fill(dicts) -> np array."""

    __slots__ = ("fill", "dtype", "cache_key_fn")

    def __init__(self, fill, dtype):
        self.fill = fill
        self.dtype = dtype


class PageProcessor:
    """Compiled filter+projections over a fixed input-channel layout."""

    def __init__(self, input_types: List[T.Type],
                 projections: List[RowExpression],
                 filter_expr: Optional[RowExpression] = None):
        import threading

        self.input_types = list(input_types)
        self.projections = list(projections)
        self.filter_expr = filter_expr
        # instances are SHARED across concurrent queries when built
        # through cache.ProcessorCache (the per-instance jax.jit is the
        # whole point — repeat statements must not retrace); the lock
        # serializes the host-side LUT/dictionary caches only, never
        # the jitted compute
        self._cache_lock = threading.Lock()
        self.slots: List[_Slot] = []
        self._slot_of: Dict[int, int] = {}   # id(plan-node) -> slot index
        self._lut_cache: Dict = {}
        self._dict_cache: Dict = {}
        # id(projection expr) -> dicts->Dictionary, for string-valued
        # expressions whose output pool is built per process() call
        # (string CASE/COALESCE merge branch pools)
        self._out_dict_resolvers: Dict[int, object] = {}
        # template parameter slots (round 16): ParamRefs in the IR bind
        # to traced inputs instead of baked constants.  param_indices is
        # the sorted tuple of GLOBAL literal-slot indices this program
        # consumes; callers pass bindings in that order.
        exprs = ([filter_expr] if filter_expr is not None else []) \
            + self.projections
        self._param_types: Dict[int, T.Type] = {}

        def note_params(e):
            if isinstance(e, ParamRef):
                self._param_types[e.index] = e.type
            elif isinstance(e, Call):
                for a in e.args:
                    note_params(a)

        for e in exprs:
            note_params(e)
        self.param_indices: Tuple[int, ...] = tuple(
            sorted(self._param_types))
        self._param_pos = {idx: pos for pos, idx
                           in enumerate(self.param_indices)}
        #: lazily-built vmapped programs per batch mode ("shared" |
        #: "carried") — lazy so param-free processors never pay for or
        #: perturb the serial program registry
        self._batched_jits: Dict[str, object] = {}
        # plan every expression once (assigns slots deterministically)
        self._plans = [self._plan(e) for e in exprs]
        if filter_expr is not None:
            self._filter_plan = self._plans[0]
            self._proj_plans = self._plans[1:]
        else:
            self._filter_plan = None
            self._proj_plans = self._plans
        # output dictionaries resolved per process() call.
        # profiled (telemetry.profiler) under the SAME semantic key
        # the ProcessorCache uses — (input types, projection/filter
        # IR) IS the program identity, so the cost registry joins
        # cleanly with the processor cache
        from ..telemetry.profiler import instrument

        self._jit = instrument(
            "page_processor", jax.jit(self._run),
            key=(tuple(self.input_types), tuple(self.projections),
                 filter_expr))

    @property
    def output_types(self) -> List[T.Type]:
        return [p.type for p in self.projections]

    # ------------------------------------------------------------------
    # planning: turn the IR into a tree of eval closures + LUT slots

    def _new_slot(self, fill, dtype) -> int:
        self.slots.append(_Slot(fill, dtype))
        return len(self.slots) - 1

    def _str_view(self, e: RowExpression) -> _StrView:
        """Build the host-value view of a string expression."""
        if isinstance(e, InputRef):
            return _StrView(channel=e.channel)
        if isinstance(e, Literal):
            return _StrView(literal=e.value)
        if isinstance(e, Call):
            if e.name == "$cast" and _is_pooled(e.args[0].type):
                base = self._str_view(e.args[0])
                if isinstance(e.type, T.CharType):
                    # CHAR(n) semantics: fixed length, space padded —
                    # comparisons then naturally ignore trailing-space
                    # differences between CHARs of equal length
                    n = e.type.length
                    prev = base.transform

                    def pad(s, _n=n, _prev=prev):
                        if s is None:
                            return None
                        if _prev is not None:
                            s = _prev(s)
                            if s is None:
                                return None
                        return s[:_n].ljust(_n)

                    if base.channel is None:
                        return _StrView(literal=pad(base.literal))
                    return _StrView(channel=base.channel, transform=pad)
                return base  # varchar(n) <-> varchar: code passthrough
            fn = F.get_function(e.name)
            if fn.str_transform is None:
                raise TypeError_(
                    f"string function {e.name} not usable on device path")
            base = None
            extra: List = []
            for a in e.args:
                if _is_pooled(a.type):
                    if base is not None:
                        # two string columns: only literal second arg works
                        v = self._str_view(a)
                        if v.channel is not None:
                            raise TypeError_(
                                f"{e.name} over two string columns "
                                "not supported on device yet")
                        extra.append(("lit", v.literal))
                    else:
                        base = self._str_view(a)
                        extra.append(("base", None))
                elif isinstance(a, Literal):
                    extra.append(("lit", a.value))
                else:
                    raise TypeError_(
                        f"{e.name}: non-literal argument {a!r} requires "
                        "per-row host work")
            if base is None:  # all-literal string expr
                args = [v for k, v in extra if k == "lit"]
                return _StrView(literal=fn.str_transform(*args))
            prev = base.transform

            def chained(s, _fn=fn.str_transform, _extra=tuple(extra), _prev=prev):
                if s is None:
                    return None
                if _prev is not None:
                    s = _prev(s)
                    if s is None:
                        return None
                args = [s if k == "base" else v for k, v in _extra]
                return _fn(*args)

            if base.channel is None:
                # literal base with extra args: fold on the host now
                return _StrView(literal=chained(base.literal))
            return _StrView(channel=base.channel, transform=chained)
        raise TypeError_(f"unsupported string expression {e!r}")

    def _string_nulls_plan(self, e: RowExpression):
        """Null mask of a string expression = nulls of its base channel."""
        v = self._str_view(e)
        if v.channel is None:
            is_null = v.literal is None
            return lambda env: (jnp.full((), is_null) if is_null else None)
        ch = v.channel
        return lambda env: env["nulls"][ch]

    def _plan_str_codes(self, e: RowExpression):
        """Device codes of a string expression (transform-invariant)."""
        v = self._str_view(e)
        if v.channel is None:
            return lambda env: jnp.zeros((), dtype=jnp.int32)
        ch = v.channel
        return lambda env: env["cols"][ch]

    def _plan_rank_pair(self, a: RowExpression, b: RowExpression):
        """Rank LUT slots for comparing two string expressions in a merged
        value space."""
        va, vb = self._str_view(a), self._str_view(b)

        def fill_pair(dicts):
            from ..block import _rank_sort_key

            xs = va.values(dicts)
            ys = vb.values(dicts)
            # None-totalizing key: composite pool entries may hold
            # nested NULLs that plain comparison cannot order
            merged = sorted(set(v for v in xs + ys if v is not None),
                            key=_rank_sort_key)
            rank = {v: i for i, v in enumerate(merged)}
            ra = np.asarray([rank.get(v, -1) for v in xs], dtype=np.int32)
            rb = np.asarray([rank.get(v, -1) for v in ys], dtype=np.int32)
            return ra, rb

        sa = self._new_slot(lambda dicts: fill_pair(dicts)[0], np.int32)
        sb = self._new_slot(lambda dicts: fill_pair(dicts)[1], np.int32)
        return sa, sb

    def _plan(self, e: RowExpression) -> Callable:
        """Returns eval(env) -> (raw, null|None). env has cols/nulls/luts."""
        if isinstance(e, InputRef):
            ch = e.channel
            return lambda env: (env["cols"][ch], env["nulls"][ch])

        if isinstance(e, Literal):
            t = e.type
            if e.value is None:
                z = np.zeros((), dtype=t.storage if t.storage is not None
                             else np.bool_)
                return lambda env: (jnp.asarray(z), jnp.asarray(True))
            if _is_pooled(t):
                # projected pooled literal (string/array): code 0 into
                # the one-entry dictionary process() resolves via
                # _str_view
                return lambda env: (jnp.zeros((), dtype=jnp.int32), None)
            raw = self._literal_raw(e)
            return lambda env: (jnp.asarray(raw), None)

        if isinstance(e, ParamRef):
            if _is_pooled(e.type):
                # pooled params would need per-member host pools —
                # template build treats this shape as ineligible
                raise TypeError_(
                    f"unsupported string expression {e!r}")
            pos = self._param_pos[e.index]
            # cache-marked literals are never NULL (NullLiteral stays in
            # the shape), so the mask is statically absent
            return lambda env: (env["params"][pos], None)

        assert isinstance(e, Call), e
        name = e.name

        if name in ("$and", "$or"):
            plans = [self._plan(a) for a in e.args]
            is_and = name == "$and"

            def ev(env):
                raws, nulls = [], []
                for p in plans:
                    r, n = p(env)
                    raws.append(r)
                    nulls.append(n)
                acc_r, acc_n = raws[0], nulls[0]
                for r, n in zip(raws[1:], nulls[1:]):
                    if is_and:
                        new_r = acc_r & r
                        # null unless any operand is definitively false
                        a_false = _def_false(acc_r, acc_n)
                        b_false = _def_false(r, n)
                        new_n = _or_null(acc_n, n, a_false | b_false)
                    else:
                        new_r = acc_r | r
                        a_true = _def_true(acc_r, acc_n)
                        b_true = _def_true(r, n)
                        new_n = _or_null(acc_n, n, a_true | b_true)
                    acc_r, acc_n = new_r, new_n
                return acc_r, acc_n

            return ev

        if name == "$not":
            p = self._plan(e.args[0])
            return lambda env: ((lambda rn: (~rn[0], rn[1]))(p(env)))

        if name == "$is_null":
            arg = e.args[0]
            if _is_pooled(arg.type):
                if isinstance(arg, Call) and arg.name in (
                        "$if", "$case", "$coalesce"):
                    # nested string select: its own plan computes nulls
                    p = self._plan(arg)
                    return lambda env: (_nz(p(env)[1]), None)
                np_ = self._string_nulls_plan(arg)
                return lambda env: (_nz(np_(env)), None)
            p = self._plan(arg)

            def ev(env):
                _, n = p(env)
                return (jnp.asarray(False) if n is None else n), None

            return ev

        if name == "$coalesce":
            rt = e.type
            if _is_pooled(rt):
                # coalesce = first-non-null CASE over the branch views
                conds = [Call(T.BOOLEAN, "$not",
                              (Call(T.BOOLEAN, "$is_null", (a,)),))
                         for a in e.args[:-1]]
                return self._plan_string_select(e, conds,
                                                list(e.args[:-1]),
                                                e.args[-1])
            plans = [self._plan(a) for a in e.args]

            def ev(env):
                r_acc, n_acc = plans[0](env)
                r_acc = F.coerce_raw(r_acc, e.args[0].type, rt)
                n_acc = _nz(n_acc)
                for p, a in zip(plans[1:], e.args[1:]):
                    r, n = p(env)
                    r = F.coerce_raw(r, a.type, rt)
                    r_acc = jnp.where(n_acc, r, r_acc)
                    n_acc = n_acc & _nz(n)
                return r_acc, n_acc

            return ev

        if name in ("$if", "$case"):
            return self._plan_case(e)

        if name == "$in":
            return self._plan_in(e)

        if name == "$between":
            x, lo, hi = e.args
            desugared = Call(T.BOOLEAN, "$and", (
                Call(T.BOOLEAN, "ge", (x, lo)),
                Call(T.BOOLEAN, "le", (x, hi))))
            return self._plan(desugared)

        if name == "$like":
            return self._plan_like(e)

        if name == "$cast":
            return self._plan_cast(e)

        if name.startswith("$extract_"):
            fn = F.get_function(name)
            return self._plan_default_call(e, fn)

        fn = F.get_function(name)

        # pooled-value comparisons (strings, arrays) -> rank LUTs
        if name in ("eq", "ne", "lt", "le", "gt", "ge") and \
                any(_is_pooled(a.type) for a in e.args):
            return self._plan_string_cmp(e)

        # host pool functions -> LUT gather. Pooled OUTPUT dispatches on
        # str_transform first: a function registered with both (array
        # subscript) is a transform when its result is pooled, a scalar
        # LUT otherwise.
        if fn.str_transform is not None and _is_pooled(e.type):
            # pool-valued: consumed by an outer pool op or projection;
            # evaluation happens via _str_view there. Standalone eval
            # means a projection — handled in process(); return codes.
            codes = self._plan_str_codes(e)
            nulls = self._string_nulls_plan(e)
            return lambda env: (codes(env), _nz(nulls(env)))
        if fn.str_scalar is not None and _is_pooled(e.args[0].type):
            return self._plan_str_scalar(e, fn)

        return self._plan_default_call(e, fn)

    # -- helpers -------------------------------------------------------

    def _literal_raw(self, e: Literal):
        return param_raw(e.type, e.value)

    def _plan_default_call(self, e: Call, fn: F.ScalarFunction):
        plans = [self._plan(a) for a in e.args]
        arg_types = [a.type for a in e.args]
        rt = e.type
        kern = fn.kernel
        if kern is None:
            raise TypeError_(f"function {fn.name} has no device kernel")

        def ev(env):
            raws, nulls = [], []
            for p in plans:
                r, n = p(env)
                raws.append(r)
                nulls.append(n)
            out = kern(raws, arg_types, rt)
            null = None
            for n in nulls:
                if n is not None:
                    null = n if null is None else (null | n)
            return out, null

        return ev

    def _plan_string_cmp(self, e: Call):
        a, b = e.args
        sa, sb = self._plan_rank_pair(a, b)
        ca = self._plan_str_codes(a)
        cb = self._plan_str_codes(b)
        na = self._string_nulls_plan(a)
        nb = self._string_nulls_plan(b)
        op = {"eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
              "le": jnp.less_equal, "gt": jnp.greater,
              "ge": jnp.greater_equal}[e.name]

        def ev(env):
            ra = env["luts"][sa][ca(env)]
            rb = env["luts"][sb][cb(env)]
            raw = op(ra, rb)
            null = _merge_nulls(na(env), nb(env))
            return raw, null

        return ev

    def _plan_str_scalar(self, e: Call, fn: F.ScalarFunction):
        base = e.args[0]
        view = self._str_view(base)
        lit_args = []
        for a in e.args[1:]:
            if not isinstance(a, Literal):
                raise TypeError_(
                    f"{e.name}: non-literal extra args unsupported")
            lit_args.append(a.value)
        rt = e.type

        memo: Dict = {}

        def results(dicts):
            # ONE host pass shared by both slots (value + None mask)
            d0 = dicts[view.channel] if view.channel is not None else None
            key = (d0.uid if d0 is not None else 0, len(d0 or ())) \
                if view.channel is not None else ("lit",)
            hit = memo.get(key)
            if hit is None:
                vals = view.values(dicts)
                hit = [None if v is None
                       else fn.str_scalar(v, *lit_args) for v in vals]
                memo.clear()
                memo[key] = hit
            return hit

        def fill(dicts):
            res = results(dicts)
            out = np.zeros(len(res), dtype=rt.storage)
            for i, r in enumerate(res):
                if r is not None:
                    out[i] = r
            return out

        def fill_none(dicts):
            # a None RESULT on a non-null input is SQL NULL (e.g. array
            # subscript out of range)
            return np.asarray([r is None for r in results(dicts)],
                              dtype=np.bool_)

        slot = self._new_slot(fill, rt.storage)
        none_slot = self._new_slot(fill_none, np.bool_)
        codes = self._plan_str_codes(base)
        nulls = self._string_nulls_plan(base)

        def ev(env):
            c = codes(env)
            null = _merge_nulls(nulls(env), env["luts"][none_slot][c])
            return env["luts"][slot][c], null

        return ev

    def _plan_like(self, e: Call):
        base, pattern = e.args[0], e.args[1]
        escape = e.args[2].value if len(e.args) > 2 else None
        if not isinstance(pattern, Literal):
            raise TypeError_("LIKE pattern must be a literal")
        rx = F.like_to_regex(pattern.value, escape)
        view = self._str_view(base)

        def fill(dicts):
            vals = view.values(dicts)
            return np.asarray(
                [v is not None and rx.match(v) is not None for v in vals],
                dtype=np.bool_)

        slot = self._new_slot(fill, np.bool_)
        codes = self._plan_str_codes(base)
        nulls = self._string_nulls_plan(base)

        def ev(env):
            return env["luts"][slot][codes(env)], _nz_opt(nulls(env))

        return ev

    def _plan_in(self, e: Call):
        value, items = e.args[0], e.args[1:]
        if _is_string(value.type):
            lits = []
            for it in items:
                if not isinstance(it, Literal):
                    raise TypeError_("string IN list must be literals")
                lits.append(it.value)
            view = self._str_view(value)
            # SQL three-valued IN: a NULL list item makes non-matches
            # NULL (never FALSE) — so NOT IN over a list with NULL keeps
            # nothing
            has_null_item = any(v is None for v in lits)
            lit_set = set(v for v in lits if v is not None)

            def fill(dicts):
                vals = view.values(dicts)
                return np.asarray([v in lit_set for v in vals],
                                  dtype=np.bool_)

            slot = self._new_slot(fill, np.bool_)
            codes = self._plan_str_codes(value)
            nulls = self._string_nulls_plan(value)

            def ev(env):
                matched = env["luts"][slot][codes(env)]
                null = _nz_opt(nulls(env))
                if has_null_item:
                    null = _nz(null) | ~matched
                return matched, null

            return ev

        ors = Call(T.BOOLEAN, "$or", tuple(
            Call(T.BOOLEAN, "eq", (value, it)) for it in items))
        return self._plan(ors if len(items) > 1
                          else Call(T.BOOLEAN, "eq", (value, items[0])))

    def _plan_case(self, e: Call):
        """$if(cond, then, else) / $case(c1, v1, c2, v2, ..., default)."""
        args = list(e.args)
        if e.name == "$if":
            conds, vals = [args[0]], [args[1]]
            default = args[2] if len(args) > 2 else Literal(e.type, None)
        else:
            pairs, default = args[:-1], args[-1]
            conds = pairs[0::2]
            vals = pairs[1::2]
        rt = e.type
        if _is_pooled(rt):
            return self._plan_string_select(e, conds, vals, default)
        cond_plans = [self._plan(c) for c in conds]
        val_plans = [self._plan(v) for v in vals]
        def_plan = self._plan(default)
        val_types = [v.type for v in vals] + [default.type]

        def ev(env):
            out_r, out_n = def_plan(env)
            out_r = F.coerce_raw(out_r, val_types[-1], rt)
            out_n = _nz(out_n)
            # first-match-wins: walk branches in order with a 'taken' mask
            out = None
            out_null = None
            taken = jnp.asarray(False)
            for cp, vp, vt in zip(cond_plans, val_plans, val_types[:-1]):
                cr, cn = cp(env)
                fires = cr & ~_nz(cn) & ~taken
                vr, vn = vp(env)
                vr = F.coerce_raw(vr, vt, rt)
                if out is None:
                    out = jnp.where(fires, vr, out_r)
                    out_null = jnp.where(fires, _nz(vn), out_n)
                else:
                    out = jnp.where(fires, vr, out)
                    out_null = jnp.where(fires, _nz(vn), out_null)
                taken = taken | fires
            if out is None:
                return out_r, out_n
            return out, out_null

        return ev

    def _plan_string_select(self, e: Call, conds, vals, default):
        """String-valued CASE/IF/COALESCE: branch values come from
        different channels (different code pools), so each branch gets a
        per-process remap LUT into ONE merged output pool, and the
        select itself is plain code arithmetic on device. The merged
        pool is append-only and cached per input-pool state, so codes
        stay stable across pages."""
        def decompose(expr: Call):
            args = list(expr.args)
            if expr.name == "$if":
                return ([args[0]], [args[1]],
                        args[2] if len(args) > 2
                        else Literal(expr.type, None))
            if expr.name == "$coalesce":
                cs = [Call(T.BOOLEAN, "$not",
                           (Call(T.BOOLEAN, "$is_null", (a,)),))
                      for a in args[:-1]]
                return cs, args[:-1], args[-1]
            pairs, dflt = args[:-1], args[-1]
            return pairs[0::2], pairs[1::2], dflt

        def collect_views(expr, out):
            """Leaf _StrViews of a possibly-nested select tree."""
            if isinstance(expr, Call) and expr.name in ("$if", "$case",
                                                        "$coalesce"):
                cs, vs, dflt = decompose(expr)
                for v in vs:
                    collect_views(v, out)
                collect_views(dflt, out)
            else:
                out.append(self._str_view(expr))
            return out

        all_views: List[_StrView] = []
        for v in vals:
            collect_views(v, all_views)
        collect_views(default, all_views)
        key_channels = tuple(sorted({v.channel for v in all_views
                                     if v.channel is not None}))
        token = ("strsel", len(self._out_dict_resolvers), id(e))

        def merged_dict(dicts) -> Dictionary:
            key = (token,) + tuple(
                (dicts[c].uid if dicts[c] is not None else 0,
                 len(dicts[c]) if dicts[c] is not None
                 else 0) for c in key_channels)
            d = self._dict_cache.get(key)
            if d is None:
                d = Dictionary()
                self._dict_cache[key] = d
            return d

        self._out_dict_resolvers[id(e)] = merged_dict

        from ..block import null_pool_value as _npv

        null_pool_value = _npv(e.type)

        def code_slot(view: _StrView) -> int:
            if view.channel is None:
                def fill_lit(dicts, _v=view.literal):
                    m = merged_dict(dicts)
                    code = m.code(null_pool_value if _v is None else _v)
                    return np.asarray([code], dtype=np.int32)

                return self._new_slot(fill_lit, np.int32)

            def fill(dicts, _view=view):
                m = merged_dict(dicts)
                vals_ = _view.values(dicts)
                arr = [m.code(null_pool_value if v is None else v)
                       for v in vals_]
                # empty input pool: one dead entry keeps the gather legal
                return np.asarray(arr or [m.code(null_pool_value)],
                                  dtype=np.int32)

            return self._new_slot(fill, np.int32)

        def plan_branch(expr):
            """eval(env) -> (merged-pool code, null mask) for one branch
            value — recursing through nested selects into the SAME
            merged pool."""
            if isinstance(expr, Call) and expr.name in ("$if", "$case",
                                                        "$coalesce"):
                cs, vs, dflt = decompose(expr)
                cond_ps = [self._plan(c) for c in cs]
                val_ps = [plan_branch(v) for v in vs]
                dflt_p = plan_branch(dflt)

                def sel_ev(env, _c=cond_ps, _v=val_ps, _d=dflt_p):
                    out, out_null = _d(env)
                    taken = jnp.asarray(False)
                    for cp, vp in zip(_c, _v):
                        cr, cn = cp(env)
                        fires = cr & ~_nz(cn) & ~taken
                        vr, vn = vp(env)
                        out = jnp.where(fires, vr, out)
                        out_null = jnp.where(fires, vn, out_null)
                        taken = taken | fires
                    return out, out_null

                return sel_ev
            view = self._str_view(expr)
            slot = code_slot(view)
            if view.channel is None:
                is_null = view.literal is None

                def lit_ev(env, _s=slot, _n=is_null):
                    return env["luts"][_s][0], jnp.asarray(_n)

                return lit_ev
            codes = self._plan_str_codes(expr)
            nulls = self._string_nulls_plan(expr)

            def col_ev(env, _s=slot, _c=codes, _n=nulls):
                return env["luts"][_s][_c(env)], _nz(_n(env))

            return col_ev

        cond_plans = [self._plan(c) for c in conds]
        branch_plans = [plan_branch(v) for v in vals]
        default_plan = plan_branch(default)

        def ev(env):
            out, out_null = default_plan(env)
            taken = jnp.asarray(False)
            for cp, vp in zip(cond_plans, branch_plans):
                cr, cn = cp(env)
                fires = cr & ~_nz(cn) & ~taken
                vr, vn = vp(env)
                out = jnp.where(fires, vr, out)
                out_null = jnp.where(fires, vn, out_null)
                taken = taken | fires
            return out, out_null

        return ev

    def _plan_cast(self, e: Call):
        src = e.args[0]
        st, rt = src.type, e.type
        if _is_pooled(st) and _is_pooled(rt):
            codes = self._plan_str_codes(src)
            nulls = self._string_nulls_plan(src)
            return lambda env: (codes(env), _nz_opt(nulls(env)))
        if _is_string(st):
            # varchar -> fixed width via parse LUT
            view = self._str_view(src)

            def parse(v):
                if rt == T.DATE:
                    from datetime import date
                    y, m, d = v.split("-")
                    return (date(int(y), int(m), int(d)) -
                            __import__("datetime").date(1970, 1, 1)).days
                if rt.is_decimal:
                    return rt.to_raw(v)
                if rt == T.BOOLEAN:
                    return v.strip().lower() in ("true", "t", "1")
                return rt.storage.type(v.strip())

            def fill(dicts):
                vals = view.values(dicts)
                out = np.zeros(len(vals), dtype=rt.storage)
                for i, v in enumerate(vals):
                    if v is not None:
                        out[i] = parse(v)
                return out

            slot = self._new_slot(fill, rt.storage)
            codes = self._plan_str_codes(src)
            nulls = self._string_nulls_plan(src)
            return lambda env: (env["luts"][slot][codes(env)],
                                _nz_opt(nulls(env)))
        if _is_string(rt):
            raise TypeError_("cast to varchar needs host materialization")
        p = self._plan(src)

        def ev(env):
            r, n = p(env)
            if st.is_timestamp_tz or rt.is_timestamp_tz:
                from .tz import device_utc_to_wall, device_wall_to_utc

                day_us = np.int64(86_400_000_000)
                if st.is_timestamp_tz and rt.is_timestamp_tz:
                    return r, n  # same instant; zone is type metadata
                if st.is_timestamp_tz and rt == T.TIMESTAMP:
                    return device_utc_to_wall(r, st.zone), n
                if st.is_timestamp_tz and rt == T.DATE:
                    wall = device_utc_to_wall(r, st.zone)
                    return jnp.floor_divide(wall, day_us) \
                        .astype(jnp.int32), n
                if st == T.TIMESTAMP and rt.is_timestamp_tz:
                    # wall clock interpreted in the target's zone
                    return device_wall_to_utc(r, rt.zone), n
                if st == T.DATE and rt.is_timestamp_tz:
                    wall = r.astype(jnp.int64) * day_us
                    return device_wall_to_utc(wall, rt.zone), n
            if st == T.DATE and rt == T.TIMESTAMP:
                return r.astype(jnp.int64) * np.int64(86_400_000_000), n
            if st == T.TIMESTAMP and rt == T.DATE:
                return jnp.floor_divide(r, np.int64(86_400_000_000)) \
                    .astype(jnp.int32), n
            if st == T.BOOLEAN and rt != T.BOOLEAN:
                return r.astype(rt.storage), n
            return F.coerce_raw(r, st, rt), n

        return ev

    # ------------------------------------------------------------------
    # runtime

    def _fill_luts(self, dicts) -> Tuple:
        # keys use Dictionary.uid, never id(): shared processors outlive
        # queries, and a freed pool's ADDRESS can be reused by a new
        # same-length pool — uid cannot alias
        arrs = []
        with self._cache_lock:
            for i, slot in enumerate(self.slots):
                key = (i, tuple(d.uid for d in dicts if d is not None),
                       tuple(len(d) for d in dicts if d is not None))
                arr = self._lut_cache.get(key)
                if arr is None:
                    arr = pad_lut(slot.fill(dicts))
                    self._lut_cache[key] = arr
                    if len(self._lut_cache) > 256:
                        self._lut_cache.clear()
                arrs.append(arr)
        # host->device uploads OUTSIDE the lock: concurrent queries
        # sharing this processor must serialize only the cache lookups
        return tuple(jnp.asarray(a) for a in arrs)

    def _run(self, cols, nulls, valid, luts, params=()):
        from .. import jit_stats

        jit_stats.bump("page_processor")  # trace-time only (cache miss)
        env = {"cols": cols, "nulls": nulls, "luts": luts,
               "params": params}
        new_valid = valid
        if self._filter_plan is not None:
            r, n = self._filter_plan(env)
            keep = r & ~_nz(n)
            new_valid = valid & keep
        out_cols, out_nulls = [], []
        for plan, proj in zip(self._proj_plans, self.projections):
            r, n = plan(env)
            r = jnp.broadcast_to(r, valid.shape).astype(proj.type.storage)
            n = jnp.broadcast_to(_nz(n), valid.shape)
            out_cols.append(r)
            out_nulls.append(n)
        return tuple(out_cols), tuple(out_nulls), new_valid

    def process(self, dpage: DevicePage, params: Tuple = ()) -> DevicePage:
        dicts = dpage.dictionaries
        luts = self._fill_luts(dicts)
        cols, nulls, valid = self._jit(
            tuple(dpage.cols), tuple(dpage.nulls), dpage.valid, luts,
            params)
        with self._cache_lock:
            out_dicts = self._resolve_out_dicts(dicts)
        return DevicePage(self.output_types, list(cols), list(nulls), valid,
                          out_dicts)

    # -- batched execution (round 16) ----------------------------------

    def _batched_jit(self, mode: str):
        """One jitted ``vmap(_run)`` per batch mode, built lazily.

        "shared": stage 1 of a burst — the scan page is SHARED across
        the batch (no leading axis); only the parameter bindings carry
        the ``(B,)`` axis, and vmap broadcasts the page once on device.
        "carried": downstream stages — data already has the ``B`` axis
        from the previous stage.  LUTs are value-independent of params
        (string params are template-ineligible) so they never batch.
        """
        with self._cache_lock:
            fn = self._batched_jits.get(mode)
        if fn is not None:
            return fn
        from ..telemetry.profiler import instrument

        ax = None if mode == "shared" else 0
        fn = instrument(
            "page_processor_batched",
            jax.jit(jax.vmap(self._run, in_axes=(ax, ax, ax, None, 0))),
            key=(mode, tuple(self.input_types), tuple(self.projections),
                 self.filter_expr))
        with self._cache_lock:
            self._batched_jits.setdefault(mode, fn)
            return self._batched_jits[mode]

    def bind_params(self, values: Tuple) -> Tuple:
        """Raw bindings for ONE statement, ordered by this program's
        consumed slots.  ``values`` holds the python literal value per
        GLOBAL slot index (the shape's full literal vector)."""
        return tuple(
            param_raw(self._param_types[i], values[i])
            for i in self.param_indices)

    def process_batched(self, cols, nulls, valid, dicts, params_batch,
                        mode: str = "shared"):
        """Run the whole ``(B,)`` burst as ONE device launch.

        ``params_batch`` is a tuple (one entry per consumed slot, in
        ``param_indices`` order) of stacked ``(B,)`` arrays.  Returns
        ``(cols, nulls, valid, out_dicts)`` with a leading batch axis on
        every device array — the caller demuxes per statement."""
        luts = self._fill_luts(dicts)
        out_cols, out_nulls, new_valid = self._batched_jit(mode)(
            cols, nulls, valid, luts, params_batch)
        with self._cache_lock:
            out_dicts = self._resolve_out_dicts(dicts)
        return out_cols, out_nulls, new_valid, out_dicts

    def _resolve_out_dicts(self, dicts) -> List[Optional[Dictionary]]:
        """Output dictionary per projection (caller holds _cache_lock:
        pool identity must be stable across pages AND across the
        concurrent queries sharing this processor)."""
        out_dicts = []
        for j, proj in enumerate(self.projections):
            if _is_pooled(proj.type):
                resolver = self._out_dict_resolvers.get(id(proj))
                if resolver is not None:
                    out_dicts.append(resolver(dicts))
                    continue
                view = self._str_view(proj)
                if view.channel is None:
                    key = (j, "lit")
                    d = self._dict_cache.get(key)
                    if d is None:
                        d = Dictionary([view.literal])
                        self._dict_cache[key] = d
                    out_dicts.append(d)
                elif view.transform is None:
                    # plain column passthrough: SAME pool object, so code
                    # spaces stay stable across pages (group-by/join
                    # correctness depends on pool identity)
                    out_dicts.append(dicts[view.channel])
                else:
                    base = dicts[view.channel]
                    key = (j, base.uid, len(base))
                    d = self._dict_cache.get(key)
                    if d is None:
                        from ..block import null_pool_value as _npv_fn

                        vals = view.values(dicts)
                        npv = _npv_fn(proj.type)
                        # pool must stay code-aligned with the input pool
                        # (derived values may repeat), so no dedup here
                        d = Dictionary.aligned(
                            [npv if v is None else v for v in vals])
                        self._dict_cache[key] = d
                    out_dicts.append(d)
            else:
                out_dicts.append(None)
        return out_dicts


# ---------------------------------------------------------------------------
# small null-mask helpers


def _nz(n):
    return jnp.asarray(False) if n is None else n


def _nz_opt(n):
    return None if n is None else n


def _merge_nulls(a, b):
    a, b = _nz_opt(a), _nz_opt(b)
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _def_false(r, n):
    return ~r & ~_nz(n)


def _def_true(r, n):
    return r & ~_nz(n)


def _or_null(na, nb, definitive):
    return (_nz(na) | _nz(nb)) & ~definitive
