"""Scalar function registry: type resolution + device kernels.

Reference analog: the builtin function catalog registered in
``metadata/SystemFunctionBundle.java`` — scalar ops from
``core/trino-main/src/main/java/io/trino/type/*Operators.java`` (decimal
type-derivation rules mirrored from ``type/DecimalOperators.java:76,158,239,
323,503``) and ``operator/scalar/``.

Each function carries:
- ``resolve(arg_types) -> return type`` (raises TypeError_ on no match)
- ``kernel(raws, arg_types, ret_type) -> raw`` — traced under jit over raw
  storage arrays (decimals are scaled int64, dates int32 days, ...)
- string functions instead carry host-side transforms applied over
  dictionary values (``str_transform`` for string->string,
  ``str_scalar`` for string->fixed-width); the compiler turns them into
  per-code lookup tables gathered on device.

Null propagation is the compiler's job (RETURN_NULL_ON_NULL default);
kernels see raw lanes and may compute garbage in null lanes (masked out).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..types import is_numeric
from ..types import TypeError_


@dataclass
class ScalarFunction:
    name: str
    resolve: Callable
    kernel: Optional[Callable] = None
    str_transform: Optional[Callable] = None   # (*py_args) -> str|None
    str_scalar: Optional[Callable] = None      # (*py_args) -> python scalar|None


REGISTRY: dict = {}


def register(fn: ScalarFunction):
    REGISTRY[fn.name] = fn
    return fn


def get_function(name: str) -> ScalarFunction:
    f = REGISTRY.get(name)
    if f is None:
        raise TypeError_(f"unknown function: {name}")
    return f


# ---------------------------------------------------------------------------
# helpers

_POW10 = [10 ** i for i in range(19)]


def rescale(x, k: int):
    """x * 10^k (k static python int; negative k divides truncating)."""
    if k == 0:
        return x
    if k > 0:
        return x * np.int64(_POW10[k])
    return x // np.int64(_POW10[-k])


def div_round_half_up(x, y):
    """Integer divide rounding half away from zero (reference:
    DecimalOperators.divideRoundUp)."""
    sign = jnp.where((x < 0) ^ (y < 0), -1, 1).astype(x.dtype)
    ax = jnp.abs(x)
    ay = jnp.abs(y)
    ay_safe = jnp.where(ay == 0, 1, ay)  # null/error lanes masked upstream
    q = (2 * ax + ay_safe) // (2 * ay_safe)
    return sign * q


def _is_int(t):
    return t in (T.TINYINT, T.SMALLINT, T.INTEGER, T.BIGINT)


def _is_float(t):
    return t in (T.REAL, T.DOUBLE)


def _as_decimal(t) -> T.DecimalType:
    """View an integer type as decimal(p, 0) for mixed arithmetic."""
    if t.is_decimal:
        return t
    digits = {T.TINYINT: 3, T.SMALLINT: 5, T.INTEGER: 10, T.BIGINT: 18}[t]
    return T.decimal_type(digits, 0)


def _numeric_pair(a, b):
    """Classify a binary numeric op: 'float' | 'decimal' | 'int'."""
    if _is_float(a) or _is_float(b):
        return "float"
    if a.is_decimal or b.is_decimal:
        return "decimal"
    if _is_int(a) and _is_int(b):
        return "int"
    return None


# ---------------------------------------------------------------------------
# arithmetic


def _resolve_add_sub(args):
    a, b = args
    kind = _numeric_pair(a, b)
    if kind == "float":
        return T.DOUBLE if T.DOUBLE in (a, b) else T.REAL
    if kind == "int":
        return T.common_super_type(a, b)
    if kind == "decimal":
        da, db = _as_decimal(a), _as_decimal(b)
        s = max(da.scale, db.scale)
        p = min(18, max(da.precision - da.scale, db.precision - db.scale) + s + 1)
        return T.decimal_type(p, s)
    # date/timestamp[tz] +- interval
    if (a in (T.DATE, T.TIMESTAMP) or a.is_timestamp_tz) \
            and b in (T.INTERVAL_DAY_SECOND, T.INTERVAL_YEAR_MONTH):
        return a
    if (b in (T.DATE, T.TIMESTAMP) or b.is_timestamp_tz) \
            and a in (T.INTERVAL_DAY_SECOND, T.INTERVAL_YEAR_MONTH):
        return b
    raise TypeError_(f"cannot add/subtract {a} and {b}")


def _date_plus_interval(val, ival, itype, sign):
    if itype == T.INTERVAL_DAY_SECOND:
        days = ival // np.int64(86_400_000_000)
        return (val + sign * days).astype(jnp.int32)
    # year-month: civil-calendar month addition
    y, m, d = _civil_from_days(val)
    months = (y * 12 + (m - 1)) + sign * ival
    ny = jnp.floor_divide(months, 12)
    nm = months - ny * 12 + 1
    # clamp day to last day of target month
    last = _days_in_month(ny, nm)
    nd = jnp.minimum(d, last)
    return _days_from_civil(ny, nm, nd).astype(jnp.int32)


def _to_float(x, t):
    if t.is_decimal:
        return x.astype(jnp.float64) / _POW10[t.scale]
    return x.astype(jnp.float64)


def coerce_raw(x, t, ret):
    """Convert raw storage of type t to raw storage of type ret."""
    if t == ret:
        return x
    if ret.is_decimal:
        if _is_float(t):
            return (x.astype(jnp.float64) * _POW10[ret.scale]).astype(jnp.int64)
        return rescale(x.astype(jnp.int64), ret.scale - _as_decimal(t).scale)
    if _is_float(ret):
        return _to_float(x, t).astype(ret.storage)
    if t.is_decimal:  # decimal -> int: truncate toward zero
        s = np.int64(_POW10[t.scale])
        return (jnp.sign(x) * (jnp.abs(x) // s)).astype(ret.storage)
    return x.astype(ret.storage)


def _add_sub_kernel(sign):
    def kernel(raws, arg_types, ret_type):
        a, b = raws
        ta, tb = arg_types
        if tb in (T.DATE, T.TIMESTAMP) or tb.is_timestamp_tz:
            # interval + date => date + interval
            a, b, ta, tb = b, a, tb, ta
        if ta.is_timestamp_tz and tb in (T.INTERVAL_DAY_SECOND,
                                         T.INTERVAL_YEAR_MONTH):
            if tb == T.INTERVAL_DAY_SECOND:
                return a + sign * b  # instant arithmetic
            # year-month intervals add in WALL time (reference:
            # TimestampWithTimeZoneOperators) — convert, add, convert back
            from .tz import device_utc_to_wall, device_wall_to_utc

            wall = device_utc_to_wall(a, ta.zone)
            days = _date_plus_interval(
                (wall // np.int64(86_400_000_000)).astype(jnp.int32),
                b, tb, sign)
            new_wall = days.astype(jnp.int64) * np.int64(86_400_000_000) \
                + wall % np.int64(86_400_000_000)
            return device_wall_to_utc(new_wall, ta.zone)
        if ta in (T.DATE, T.TIMESTAMP) and tb in (T.INTERVAL_DAY_SECOND,
                                                  T.INTERVAL_YEAR_MONTH):
            if ta == T.TIMESTAMP:
                if tb == T.INTERVAL_DAY_SECOND:
                    return a + sign * b
                days = _date_plus_interval(
                    (a // np.int64(86_400_000_000)).astype(jnp.int32),
                    b, tb, sign)
                return days.astype(jnp.int64) * np.int64(86_400_000_000) \
                    + a % np.int64(86_400_000_000)
            return _date_plus_interval(a, b, tb, sign)
        if ta == T.DATE and tb == T.DATE and sign == -1:
            return (a.astype(jnp.int64) - b.astype(jnp.int64))
        return coerce_raw(a, ta, ret_type) + sign * coerce_raw(b, tb, ret_type)

    return kernel


register(ScalarFunction("add", _resolve_add_sub, _add_sub_kernel(1)))
register(ScalarFunction("subtract", _resolve_add_sub, _add_sub_kernel(-1)))


def _resolve_mul(args):
    a, b = args
    kind = _numeric_pair(a, b)
    if kind == "float":
        return T.DOUBLE if T.DOUBLE in (a, b) else T.REAL
    if kind == "int":
        return T.common_super_type(a, b)
    if kind == "decimal":
        da, db = _as_decimal(a), _as_decimal(b)
        return T.decimal_type(min(18, da.precision + db.precision),
                              da.scale + db.scale)
    if a == T.INTERVAL_DAY_SECOND and _is_int(b):
        return a
    raise TypeError_(f"cannot multiply {a} and {b}")


def _mul_kernel(raws, arg_types, ret_type):
    a, b = raws
    ta, tb = arg_types
    if _is_float(ret_type):
        return (_to_float(a, ta) * _to_float(b, tb)).astype(ret_type.storage)
    if ret_type.is_decimal:
        return a.astype(jnp.int64) * b.astype(jnp.int64)
    return (a.astype(ret_type.storage)) * (b.astype(ret_type.storage))


register(ScalarFunction("multiply", _resolve_mul, _mul_kernel))


def _resolve_div(args):
    a, b = args
    kind = _numeric_pair(a, b)
    if kind == "float":
        return T.DOUBLE if T.DOUBLE in (a, b) else T.REAL
    if kind == "int":
        return T.common_super_type(a, b)
    if kind == "decimal":
        da, db = _as_decimal(a), _as_decimal(b)
        # reference: DecimalOperators.java:323-324
        p = min(18, da.precision + db.scale + max(db.scale - da.scale, 0))
        s = max(da.scale, db.scale)
        return T.decimal_type(p, s)
    raise TypeError_(f"cannot divide {a} and {b}")


def _div_kernel(raws, arg_types, ret_type):
    a, b = raws
    ta, tb = arg_types
    if _is_float(ret_type):
        return (_to_float(a, ta) / _to_float(b, tb)).astype(ret_type.storage)
    if ret_type.is_decimal:
        da, db = _as_decimal(ta), _as_decimal(tb)
        # rescaleFactor = resultScale - dividendScale + divisorScale
        k = ret_type.scale - da.scale + db.scale
        return div_round_half_up(rescale(a.astype(jnp.int64), k),
                                 b.astype(jnp.int64))
    bz = jnp.where(b == 0, 1, b)
    return (a.astype(ret_type.storage)) // (bz.astype(ret_type.storage))


register(ScalarFunction("divide", _resolve_div, _div_kernel))


def _resolve_mod(args):
    a, b = args
    kind = _numeric_pair(a, b)
    if kind == "float":
        return T.DOUBLE if T.DOUBLE in (a, b) else T.REAL
    if kind == "int":
        return T.common_super_type(a, b)
    if kind == "decimal":
        da, db = _as_decimal(a), _as_decimal(b)
        # reference: DecimalOperators.java:503-504
        s = max(da.scale, db.scale)
        p = min(db.precision - db.scale, da.precision - da.scale) + s
        return T.decimal_type(min(18, p), s)
    raise TypeError_(f"cannot mod {a} and {b}")


def _mod_kernel(raws, arg_types, ret_type):
    a, b = raws
    ta, tb = arg_types
    if _is_float(ret_type):
        return jnp.fmod(_to_float(a, ta), _to_float(b, tb)).astype(ret_type.storage)
    if ret_type.is_decimal:
        da, db = _as_decimal(ta), _as_decimal(tb)
        s = ret_type.scale
        ra = rescale(a.astype(jnp.int64), s - da.scale)
        rb = rescale(b.astype(jnp.int64), s - db.scale)
        rbz = jnp.where(rb == 0, 1, rb)
        return ra - (jnp.sign(ra) * (jnp.abs(ra) // jnp.abs(rbz))) * rbz
    bz = jnp.where(b == 0, 1, b)
    # SQL mod takes dividend sign (fmod), not python floor-mod
    q = jnp.sign(a) * (jnp.abs(a) // jnp.abs(bz.astype(a.dtype)))
    return (a - q * bz.astype(a.dtype)).astype(ret_type.storage)


register(ScalarFunction("modulus", _resolve_mod, _mod_kernel))
register(ScalarFunction("mod", _resolve_mod, _mod_kernel))


def _resolve_negate(args):
    (a,) = args
    if _is_int(a) or _is_float(a) or a.is_decimal or a in (
            T.INTERVAL_DAY_SECOND, T.INTERVAL_YEAR_MONTH):
        return a
    raise TypeError_(f"cannot negate {a}")


register(ScalarFunction("negate", _resolve_negate,
                        lambda raws, at, rt: -raws[0]))


# ---------------------------------------------------------------------------
# comparisons (numeric / date / boolean; string comparisons are routed
# through dictionary rank LUTs by the compiler, not this kernel)


def _resolve_cmp(args):
    a, b = args
    if a == b or T.common_super_type(a, b) is not None:
        return T.BOOLEAN
    raise TypeError_(f"cannot compare {a} and {b}")


def _cmp_kernel(op):
    def kernel(raws, arg_types, ret_type):
        a, b = raws
        ta, tb = arg_types
        if ta.is_decimal or tb.is_decimal:
            if _is_float(ta) or _is_float(tb):
                a, b = _to_float(a, ta), _to_float(b, tb)
            else:
                da, db = _as_decimal(ta), _as_decimal(tb)
                s = max(da.scale, db.scale)
                a = rescale(a.astype(jnp.int64), s - da.scale)
                b = rescale(b.astype(jnp.int64), s - db.scale)
        elif _is_float(ta) or _is_float(tb):
            a, b = _to_float(a, ta), _to_float(b, tb)
        return op(a, b)

    return kernel


# orderability of lt/le/gt/ge is enforced once, at analysis
# (_an_ComparisonExpression / sort planning), not per-resolver
for _n, _op in [("eq", jnp.equal), ("ne", jnp.not_equal), ("lt", jnp.less),
                ("le", jnp.less_equal), ("gt", jnp.greater),
                ("ge", jnp.greater_equal)]:
    register(ScalarFunction(_n, _resolve_cmp, _cmp_kernel(_op)))


# ---------------------------------------------------------------------------
# math


def _resolve_unary_double(args):
    (a,) = args
    if _is_int(a) or _is_float(a) or a.is_decimal:
        return T.DOUBLE
    raise TypeError_(f"expected numeric, got {a}")


def _unary_double(fn):
    return lambda raws, at, rt: fn(_to_float(raws[0], at[0]))


register(ScalarFunction("sqrt", _resolve_unary_double, _unary_double(jnp.sqrt)))
register(ScalarFunction("ln", _resolve_unary_double, _unary_double(jnp.log)))
register(ScalarFunction("log10", _resolve_unary_double, _unary_double(jnp.log10)))
register(ScalarFunction("exp", _resolve_unary_double, _unary_double(jnp.exp)))
register(ScalarFunction("sin", _resolve_unary_double, _unary_double(jnp.sin)))
register(ScalarFunction("cos", _resolve_unary_double, _unary_double(jnp.cos)))
register(ScalarFunction("tan", _resolve_unary_double, _unary_double(jnp.tan)))


def _resolve_same(args):
    (a,) = args
    if _is_int(a) or _is_float(a) or a.is_decimal:
        return a
    raise TypeError_(f"expected numeric, got {a}")


register(ScalarFunction("abs", _resolve_same,
                        lambda raws, at, rt: jnp.abs(raws[0])))


def _resolve_power(args):
    a, b = args
    if _numeric_pair(a, b):
        return T.DOUBLE
    raise TypeError_(f"cannot power {a}, {b}")


register(ScalarFunction(
    "power", _resolve_power,
    lambda raws, at, rt: jnp.power(_to_float(raws[0], at[0]),
                                   _to_float(raws[1], at[1]))))
register(ScalarFunction(
    "pow", _resolve_power,
    lambda raws, at, rt: jnp.power(_to_float(raws[0], at[0]),
                                   _to_float(raws[1], at[1]))))


def _resolve_round(args):
    a = args[0]
    if len(args) == 2 and not _is_int(args[1]):
        raise TypeError_("round() scale must be integer")
    if a.is_decimal:
        if len(args) == 2:
            # round(decimal, n) keeps the type (digits beyond n zeroed)
            return a
        return T.decimal_type(min(18, a.precision - a.scale + 1), 0)
    if _is_int(a) or _is_float(a):
        return a
    raise TypeError_(f"cannot round {a}")


def _round_kernel(raws, arg_types, ret_type):
    a = raws[0]
    ta = arg_types[0]
    if _is_float(ta):
        if len(raws) == 2:
            f = jnp.power(10.0, raws[1].astype(jnp.float64))
            # SQL rounds half away from zero (not banker's rounding)
            return (jnp.sign(a) * jnp.floor(jnp.abs(a) * f + 0.5) / f).astype(ta.storage)
        return (jnp.sign(a) * jnp.floor(jnp.abs(a) + 0.5)).astype(ta.storage)
    if ta.is_decimal:
        if len(raws) == 1:
            return div_round_half_up(a, np.int64(_POW10[ta.scale]))
        # round(decimal, n): zero out digits beyond scale n (n runtime value)
        k = jnp.clip(ta.scale - raws[1].astype(jnp.int64), 0, 18)
        f = jnp.asarray(_POW10, dtype=jnp.int64)[k]
        return div_round_half_up(a, f) * f
    return a


register(ScalarFunction("round", _resolve_round, _round_kernel))


def _resolve_floor_ceil(args):
    (a,) = args
    if a.is_decimal:
        return T.decimal_type(min(18, a.precision - a.scale + 1), 0)
    if _is_int(a) or _is_float(a):
        return a
    raise TypeError_(f"cannot floor/ceil {a}")


def _floor_kernel(raws, arg_types, ret_type):
    a, ta = raws[0], arg_types[0]
    if ta.is_decimal:
        return jnp.floor_divide(a, np.int64(_POW10[ta.scale]))
    if _is_float(ta):
        return jnp.floor(a)
    return a


def _ceil_kernel(raws, arg_types, ret_type):
    a, ta = raws[0], arg_types[0]
    if ta.is_decimal:
        return -jnp.floor_divide(-a, np.int64(_POW10[ta.scale]))
    if _is_float(ta):
        return jnp.ceil(a)
    return a


register(ScalarFunction("floor", _resolve_floor_ceil, _floor_kernel))
register(ScalarFunction("ceil", _resolve_floor_ceil, _ceil_kernel))
register(ScalarFunction("ceiling", _resolve_floor_ceil, _ceil_kernel))


def _resolve_greatest(args):
    t = args[0]
    for a in args[1:]:
        t2 = T.common_super_type(t, a)
        if t2 is None:
            raise TypeError_(f"greatest/least mixed types {t}, {a}")
        t = t2
    return t


def _minmax_kernel(jfn):
    def kernel(raws, arg_types, ret_type):
        acc = None
        for r, t in zip(raws, arg_types):
            if ret_type.is_decimal:
                v = rescale(r.astype(jnp.int64), ret_type.scale - _as_decimal(t).scale)
            elif _is_float(ret_type):
                v = _to_float(r, t)
            else:
                v = r.astype(ret_type.storage)
            acc = v if acc is None else jfn(acc, v)
        return acc

    return kernel


register(ScalarFunction("greatest", _resolve_greatest, _minmax_kernel(jnp.maximum)))
register(ScalarFunction("least", _resolve_greatest, _minmax_kernel(jnp.minimum)))


# ---------------------------------------------------------------------------
# date / time (civil calendar math; Howard Hinnant's algorithms —
# vectorized integer ops, MXU/VPU friendly, no host round-trip)


def _civil_from_days(days):
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y, m):
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                          dtype=jnp.int64)
    base = lengths[m - 1]
    return jnp.where((m == 2) & leap, 29, base)


def days_from_civil_host(y: int, m: int, d: int) -> int:
    import datetime
    return datetime.date(y, m, d).toordinal() - datetime.date(1970, 1, 1).toordinal()


def _resolve_date_part(args):
    (a,) = args
    if a in (T.DATE, T.TIMESTAMP) or a.is_timestamp_tz:
        return T.BIGINT
    raise TypeError_(f"expected date/timestamp, got {a}")


def _to_days(raw, t):
    if t.is_timestamp_tz:
        from .tz import device_utc_to_wall

        wall = device_utc_to_wall(raw, t.zone)
        return jnp.floor_divide(
            wall, np.int64(86_400_000_000)).astype(jnp.int32)
    if t == T.TIMESTAMP:
        return jnp.floor_divide(raw, np.int64(86_400_000_000)).astype(jnp.int32)
    return raw


def _wall_micros(raw, t):
    """Wall-clock micros-of-day for time-of-day fields (0 for DATE)."""
    if t.is_timestamp_tz:
        from .tz import device_utc_to_wall

        wall = device_utc_to_wall(raw, t.zone)
        return jnp.remainder(wall, np.int64(86_400_000_000))
    if t == T.TIMESTAMP:
        return jnp.remainder(raw, np.int64(86_400_000_000))
    return jnp.zeros_like(raw, dtype=jnp.int64)


def _date_part_kernel(part):
    def kernel(raws, arg_types, ret_type):
        if part in ("hour", "minute", "second", "millisecond"):
            us = _wall_micros(raws[0], arg_types[0])
            if part == "hour":
                return us // np.int64(3_600_000_000)
            if part == "minute":
                return (us // np.int64(60_000_000)) % 60
            if part == "second":
                return (us // np.int64(1_000_000)) % 60
            return (us // np.int64(1_000)) % 1000
        days = _to_days(raws[0], arg_types[0])
        y, m, d = _civil_from_days(days)
        if part == "year":
            return y
        if part == "month":
            return m
        if part == "day":
            return d
        if part == "quarter":
            return (m - 1) // 3 + 1
        if part == "day_of_week":  # ISO: Mon=1..Sun=7 (1970-01-01 = Thursday)
            return ((days.astype(jnp.int64) + 3) % 7) + 1
        if part == "day_of_year":
            jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
            return days.astype(jnp.int64) - jan1 + 1
        if part == "week":  # ISO week number via the Thursday rule
            dow = (days.astype(jnp.int64) + 3) % 7  # Mon=0..Sun=6
            thursday = days.astype(jnp.int64) + (3 - dow)
            ty, tm, td = _civil_from_days(thursday.astype(jnp.int32))
            jan1 = _days_from_civil(ty, jnp.ones_like(tm), jnp.ones_like(td))
            return (thursday - jan1) // 7 + 1
        raise TypeError_(f"unsupported extract field {part}")

    return kernel


for _p in ["year", "month", "day", "quarter", "day_of_week", "day_of_year",
           "week", "hour", "minute", "second", "millisecond"]:
    register(ScalarFunction(f"$extract_{_p}", _resolve_date_part,
                            _date_part_kernel(_p)))
for _n in ("hour", "minute", "second", "millisecond"):
    register(ScalarFunction(_n, _resolve_date_part, _date_part_kernel(_n)))
register(ScalarFunction("year", _resolve_date_part, _date_part_kernel("year")))
register(ScalarFunction("month", _resolve_date_part, _date_part_kernel("month")))
register(ScalarFunction("day", _resolve_date_part, _date_part_kernel("day")))
register(ScalarFunction("quarter", _resolve_date_part, _date_part_kernel("quarter")))


def _resolve_date_diff(args):
    raise TypeError_("date_diff requires literal unit (handled by analyzer)")


# ---------------------------------------------------------------------------
# string functions (host dictionary transforms; compiler wires LUTs)


def _resolve_strlen(args):
    (a,) = args
    if a.is_string:
        return T.BIGINT
    raise TypeError_(f"length() expects varchar, got {a}")


register(ScalarFunction("length", _resolve_strlen,
                        str_scalar=lambda s: len(s)))


def _resolve_str_to_str(nargs_ok):
    def resolve(args):
        if not args[0].is_string:
            raise TypeError_(f"expected varchar, got {args[0]}")
        if not nargs_ok(len(args)):
            raise TypeError_("wrong argument count")
        return T.VARCHAR

    return resolve


register(ScalarFunction("lower", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s.lower()))
register(ScalarFunction("upper", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s.upper()))
register(ScalarFunction("trim", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s.strip()))
register(ScalarFunction("ltrim", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s.lstrip()))
register(ScalarFunction("rtrim", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s.rstrip()))
register(ScalarFunction("reverse", _resolve_str_to_str(lambda n: n == 1),
                        str_transform=lambda s: s[::-1]))


def _substr(s, start, length=None):
    # SQL substr: 1-based; 0 treated as 1; negative counts from end
    start = int(start)
    if start == 0:
        start = 1
    if start > 0:
        i = start - 1
    else:
        i = len(s) + start
        if i < 0:
            i = 0
    if length is None:
        return s[i:]
    return s[i:i + int(length)]


def _resolve_substr(args):
    if not args[0].is_string:
        raise TypeError_(f"substr expects varchar, got {args[0]}")
    for a in args[1:]:
        if not _is_int(a):
            raise TypeError_("substr offsets must be integers")
    return T.VARCHAR


register(ScalarFunction("substr", _resolve_substr, str_transform=_substr))
register(ScalarFunction("substring", _resolve_substr, str_transform=_substr))


def _resolve_concat(args):
    for a in args:
        if not a.is_string:
            raise TypeError_(f"concat expects varchar, got {a}")
    return T.VARCHAR


register(ScalarFunction("concat", _resolve_concat,
                        str_transform=lambda *parts: "".join(parts)))


def like_to_regex(pattern: str, escape: Optional[str] = None) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _resolve_strpos(args):
    if not (args[0].is_string and args[1].is_string):
        raise TypeError_("strpos expects (varchar, varchar)")
    return T.BIGINT


register(ScalarFunction("strpos", _resolve_strpos,
                        str_scalar=lambda s, sub: s.find(sub) + 1))
register(ScalarFunction(
    "starts_with", lambda args: T.BOOLEAN,
    str_scalar=lambda s, pre: s.startswith(pre)))
register(ScalarFunction(
    "replace", _resolve_str_to_str(lambda n: n in (2, 3)),
    str_transform=lambda s, find, repl="": s.replace(find, repl)))
register(ScalarFunction(
    "lpad", _resolve_str_to_str(lambda n: n == 3),
    str_transform=lambda s, n, pad: s.rjust(int(n), pad[:1] or " ")[:int(n)]))
register(ScalarFunction(
    "rpad", _resolve_str_to_str(lambda n: n == 3),
    str_transform=lambda s, n, pad: s.ljust(int(n), pad[:1] or " ")[:int(n)]))


# ---------------------------------------------------------------------------
# math breadth (reference: operator/scalar/MathFunctions.java)


def _resolve_binary_double(args):
    if len(args) != 2:
        raise TypeError_(f"expected 2 arguments, got {len(args)}")
    for a in args:
        if not (is_numeric(a)):
            raise TypeError_(f"expected numeric, got {a}")
    return T.DOUBLE


def _binary_double(op):
    def kernel(raws, arg_types, ret_type):
        a = _to_float(raws[0], arg_types[0])
        b = _to_float(raws[1], arg_types[1])
        return op(a, b)

    return kernel


register(ScalarFunction("power", _resolve_binary_double,
                        _binary_double(jnp.power)))
register(ScalarFunction("pow", _resolve_binary_double,
                        _binary_double(jnp.power)))
register(ScalarFunction("atan2", _resolve_binary_double,
                        _binary_double(jnp.arctan2)))
register(ScalarFunction(
    "log", _resolve_binary_double,
    _binary_double(lambda b, x: jnp.log(x) / jnp.log(b))))

for _n, _f in [("cbrt", jnp.cbrt), ("asin", jnp.arcsin),
               ("acos", jnp.arccos), ("atan", jnp.arctan),
               ("sinh", jnp.sinh), ("cosh", jnp.cosh),
               ("tanh", jnp.tanh), ("degrees", jnp.degrees),
               ("radians", jnp.radians), ("log2", jnp.log2)]:
    register(ScalarFunction(_n, _resolve_unary_double, _unary_double(_f)))


def _resolve_sign(args):
    (a,) = args
    if not is_numeric(a):
        raise TypeError_(f"sign expects numeric, got {a}")
    return T.DOUBLE if a in (T.REAL, T.DOUBLE) else T.BIGINT


def _sign_kernel(raws, arg_types, ret_type):
    x = raws[0]
    if arg_types[0].is_decimal or arg_types[0] not in (T.REAL, T.DOUBLE):
        return jnp.sign(x.astype(jnp.int64))
    return jnp.sign(x.astype(jnp.float64))


register(ScalarFunction("sign", _resolve_sign, _sign_kernel))


def _resolve_truncate(args):
    if not (1 <= len(args) <= 2):
        raise TypeError_(f"truncate expects 1-2 arguments, got {len(args)}")
    if not is_numeric(args[0]):
        raise TypeError_(f"truncate expects numeric, got {args[0]}")
    if len(args) == 2 and not _is_int(args[1]):
        raise TypeError_("truncate digit count must be an integer")
    return T.DOUBLE if args[0] in (T.REAL, T.DOUBLE) else args[0]


def _truncate_kernel(raws, arg_types, ret_type):
    t = arg_types[0]
    x = raws[0]
    n = raws[1].astype(jnp.int64) if len(raws) > 1 else jnp.int64(0)
    if t in (T.REAL, T.DOUBLE):
        f = jnp.power(10.0, n.astype(jnp.float64))
        return jnp.trunc(x.astype(jnp.float64) * f) / f
    if t.is_decimal and t.scale is not None:
        # zero digits beyond n decimal places, toward zero; negative n
        # zeroes digits LEFT of the point (f grows past the scale)
        keep = jnp.clip(jnp.int64(t.scale) - n, 0, 18)
        f = (10.0 ** keep.astype(jnp.float64)).astype(jnp.int64)
        return jnp.sign(x) * (jnp.abs(x) // f) * f
    return x


register(ScalarFunction("truncate", _resolve_truncate, _truncate_kernel))


def _resolve_double_predicate(args):
    if not is_numeric(args[0]):
        raise TypeError_(f"expected numeric, got {args[0]}")
    return T.BOOLEAN


register(ScalarFunction(
    "is_nan", _resolve_double_predicate,
    lambda raws, at, rt: jnp.isnan(_to_float(raws[0], at[0]))))
register(ScalarFunction(
    "is_finite", _resolve_double_predicate,
    lambda raws, at, rt: jnp.isfinite(_to_float(raws[0], at[0]))))
register(ScalarFunction(
    "is_infinite", _resolve_double_predicate,
    lambda raws, at, rt: jnp.isinf(_to_float(raws[0], at[0]))))

for _n, _v in [("pi", np.pi), ("e", np.e), ("nan", np.nan),
               ("infinity", np.inf)]:
    register(ScalarFunction(
        _n, lambda args, _n=_n: T.DOUBLE if not args
        else (_ for _ in ()).throw(TypeError_(f"{_n} takes no args")),
        lambda raws, at, rt, _v=_v: jnp.float64(_v)))


# bitwise (reference: operator/scalar/BitwiseFunctions.java)

def _resolve_bitwise(args):
    for a in args:
        if not _is_int(a):
            raise TypeError_(f"bitwise function expects integers, got {a}")
    return T.BIGINT


for _n, _f in [("bitwise_and", jnp.bitwise_and),
               ("bitwise_or", jnp.bitwise_or),
               ("bitwise_xor", jnp.bitwise_xor)]:
    register(ScalarFunction(
        _n, _resolve_bitwise,
        lambda raws, at, rt, _f=_f: _f(raws[0].astype(jnp.int64),
                                       raws[1].astype(jnp.int64))))
register(ScalarFunction(
    "bitwise_not", _resolve_bitwise,
    lambda raws, at, rt: ~raws[0].astype(jnp.int64)))
register(ScalarFunction(
    "bitwise_left_shift", _resolve_bitwise,
    lambda raws, at, rt: raws[0].astype(jnp.int64)
    << raws[1].astype(jnp.int64)))
register(ScalarFunction(
    "bitwise_right_shift", _resolve_bitwise,
    lambda raws, at, rt: (raws[0].astype(jnp.int64).view(jnp.uint64)
                          >> raws[1].astype(jnp.uint64))
    .view(jnp.int64)))


# string breadth (host pool transforms)

register(ScalarFunction("codepoint", _resolve_strlen,
                        str_scalar=lambda s: ord(s[0]) if s else 0))


def _split_part(s, delim, n):
    parts = s.split(delim)
    i = int(n)
    return parts[i - 1] if 1 <= i <= len(parts) else None


register(ScalarFunction(
    "split_part", _resolve_str_to_str(lambda n: n == 3),
    str_transform=_split_part))
register(ScalarFunction(
    "translate", _resolve_str_to_str(lambda n: n == 3),
    str_transform=lambda s, frm, to: s.translate(
        {ord(f): (to[i] if i < len(to) else None)
         for i, f in enumerate(frm)})))


# date/time breadth (reference: operator/scalar/DateTimeFunctions.java)

def _trunc_days(days, unit):
    y, m, d = _civil_from_days(days)
    one = jnp.ones_like(m)
    if unit == "year":
        return _days_from_civil(y, one, one)
    if unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        return _days_from_civil(y, qm, one)
    if unit == "month":
        return _days_from_civil(y, m, one)
    if unit == "week":  # ISO week starts Monday
        dow = (days.astype(jnp.int64) + 3) % 7
        return days.astype(jnp.int64) - dow
    return days.astype(jnp.int64)


def _date_trunc_kernel(unit):
    day_us = np.int64(86_400_000_000)

    def kernel(raws, arg_types, ret_type):
        t = arg_types[0]
        x = raws[0]
        if t == T.DATE:
            return _trunc_days(x, unit).astype(jnp.int32)
        if t.is_timestamp_tz:
            from .tz import device_utc_to_wall, device_wall_to_utc

            wall = device_utc_to_wall(x, t.zone)
            tr = _trunc_wall_micros(wall, unit, day_us)
            return device_wall_to_utc(tr, t.zone)
        return _trunc_wall_micros(x, unit, day_us)

    return kernel


def _trunc_wall_micros(x, unit, day_us):
    if unit in ("year", "quarter", "month", "week", "day"):
        days = jnp.floor_divide(x, day_us).astype(jnp.int32)
        return _trunc_days(days, unit).astype(jnp.int64) * day_us
    scale = {"hour": 3_600_000_000, "minute": 60_000_000,
             "second": 1_000_000}[unit]
    return (x // np.int64(scale)) * np.int64(scale)


def _resolve_trunc_unit(args):
    (a,) = args
    if a in (T.DATE, T.TIMESTAMP) or a.is_timestamp_tz:
        return a
    raise TypeError_(f"date_trunc expects date/timestamp, got {a}")


for _u in ("year", "quarter", "month", "week", "day", "hour", "minute",
           "second"):
    register(ScalarFunction(f"$date_trunc_{_u}", _resolve_trunc_unit,
                            _date_trunc_kernel(_u)))

register(ScalarFunction("day_of_week", _resolve_date_part,
                        _date_part_kernel("day_of_week")))
register(ScalarFunction("dow", _resolve_date_part,
                        _date_part_kernel("day_of_week")))
register(ScalarFunction("day_of_year", _resolve_date_part,
                        _date_part_kernel("day_of_year")))
register(ScalarFunction("doy", _resolve_date_part,
                        _date_part_kernel("day_of_year")))
register(ScalarFunction("week", _resolve_date_part,
                        _date_part_kernel("week")))
register(ScalarFunction("week_of_year", _resolve_date_part,
                        _date_part_kernel("week")))


def _resolve_last_day(args):
    if args[0] not in (T.DATE, T.TIMESTAMP):
        raise TypeError_("last_day_of_month expects date/timestamp")
    return T.DATE


def _last_day_kernel(raws, arg_types, ret_type):
    days = _to_days(raws[0], arg_types[0])
    y, m, _ = _civil_from_days(days)
    return (_days_from_civil(y, m, jnp.ones_like(m))
            + _days_in_month(y, m) - 1).astype(jnp.int32)


register(ScalarFunction("last_day_of_month", _resolve_last_day,
                        _last_day_kernel))


def _resolve_to_unixtime(args):
    if args[0] not in (T.TIMESTAMP,) and not args[0].is_timestamp_tz:
        raise TypeError_("to_unixtime expects a timestamp")
    return T.DOUBLE


register(ScalarFunction(
    "to_unixtime", _resolve_to_unixtime,
    lambda raws, at, rt: raws[0].astype(jnp.float64) / 1e6))


def _resolve_from_unixtime(args):
    if not is_numeric(args[0]):
        raise TypeError_("from_unixtime expects numeric seconds")
    return T.timestamp_tz_type("UTC")


register(ScalarFunction(
    "from_unixtime", _resolve_from_unixtime,
    lambda raws, at, rt: (_to_float(raws[0], at[0]) * 1e6)
    .astype(jnp.int64)))


def _resolve_ts_diff(args):
    return T.BIGINT


def _ts_diff_kernel(raws, arg_types, ret_type):
    b, a, scale = raws
    d = b.astype(jnp.int64) - a.astype(jnp.int64)
    # truncate toward zero in whole units
    return jnp.sign(d) * (jnp.abs(d) // scale.astype(jnp.int64))


register(ScalarFunction("$ts_diff", _resolve_ts_diff, _ts_diff_kernel))


# ---------------------------------------------------------------------------
# arrays (pooled composites; reference: operator/scalar/ArrayFunctions +
# ArraySubscriptOperator — here host pool LUTs like the string strategy)


def _resolve_cardinality(args):
    if not (args[0].is_array or args[0].is_map):
        raise TypeError_(
            f"cardinality expects array or map, got {args[0]}")
    return T.BIGINT


register(ScalarFunction("cardinality", _resolve_cardinality,
                        str_scalar=lambda a: len(a)))


def _element_of(a, i):
    i = int(i)
    return a[i - 1] if 1 <= i <= len(a) else None


def _resolve_element_at(args):
    if not args[0].is_array:
        raise TypeError_(f"element_at expects array, got {args[0]}")
    if not _is_int(args[1]):
        raise TypeError_("element_at index must be an integer")
    return args[0].element


# $subscript is emitted by the analyzer for base[i]; element_at is the
# two-arg function form — same host lookup (1-based, out of range NULL)
register(ScalarFunction("$subscript", _resolve_element_at,
                        str_scalar=_element_of, str_transform=_element_of))
register(ScalarFunction("element_at", _resolve_element_at,
                        str_scalar=_element_of, str_transform=_element_of))


def _resolve_contains(args):
    if not args[0].is_array:
        raise TypeError_(f"contains expects array, got {args[0]}")
    return T.BOOLEAN


register(ScalarFunction("contains", _resolve_contains,
                        str_scalar=lambda a, x: x in a))


def _resolve_split(args):
    if not (args[0].is_string and args[1].is_string):
        raise TypeError_("split expects (varchar, varchar)")
    return T.array_type(T.VARCHAR)


register(ScalarFunction("split", _resolve_split,
                        str_transform=lambda s, d: tuple(s.split(d))))


def _resolve_array_join(args):
    if not args[0].is_array:
        raise TypeError_(f"array_join expects array, got {args[0]}")
    return T.VARCHAR


register(ScalarFunction(
    "array_join", _resolve_array_join,
    str_transform=lambda a, sep, nullrepl=None: sep.join(
        (nullrepl if v is None else str(v))
        for v in a if v is not None or nullrepl is not None)))


def _resolve_array_minmax(args):
    if not args[0].is_array:
        raise TypeError_(f"expected array, got {args[0]}")
    return args[0].element


register(ScalarFunction(
    "array_min", _resolve_array_minmax,
    str_scalar=lambda a: min((v for v in a if v is not None),
                             default=None),
    str_transform=lambda a: min((v for v in a if v is not None),
                                default=None)))
register(ScalarFunction(
    "array_max", _resolve_array_minmax,
    str_scalar=lambda a: max((v for v in a if v is not None),
                             default=None),
    str_transform=lambda a: max((v for v in a if v is not None),
                                default=None)))


# maps (pooled: sorted (key, value) pair tuples)


def _resolve_map_ctor(args):
    if len(args) != 2 or not (args[0].is_array and args[1].is_array):
        raise TypeError_("map expects (array, array)")
    return T.map_type(args[0].element, args[1].element)


def _map_ctor(ks, vs):
    from ..types import TrinoError

    if len(ks) != len(vs):
        raise TrinoError("Key and value arrays must be the same length",
                         "INVALID_FUNCTION_ARGUMENT")
    if any(k is None for k in ks):
        raise TrinoError("map key cannot be null",
                         "INVALID_FUNCTION_ARGUMENT")
    if len(set(ks)) != len(ks):
        raise TrinoError("Duplicate map keys are not allowed",
                         "INVALID_FUNCTION_ARGUMENT")
    return tuple(sorted(zip(ks, vs)))


register(ScalarFunction("map", _resolve_map_ctor,
                        str_transform=_map_ctor))


def _resolve_map_get(args):
    if not args[0].is_map:
        raise TypeError_(f"expected map, got {args[0]}")
    return args[0].value


def _map_get(m, k):
    return dict(m).get(k)


register(ScalarFunction("$map_get", _resolve_map_get,
                        str_scalar=_map_get, str_transform=_map_get))


def _resolve_map_keys(args):
    if not args[0].is_map:
        raise TypeError_(f"expected map, got {args[0]}")
    return T.array_type(args[0].key)


def _resolve_map_values(args):
    if not args[0].is_map:
        raise TypeError_(f"expected map, got {args[0]}")
    return T.array_type(args[0].value)


register(ScalarFunction("map_keys", _resolve_map_keys,
                        str_transform=lambda m: tuple(k for k, _ in m)))
register(ScalarFunction("map_values", _resolve_map_values,
                        str_transform=lambda m: tuple(v for _, v in m)))


# ---------------------------------------------------------------------------
# sketch primitives: HLL (approx_distinct) + DDSketch (approx_percentile)
#
# Reference analog: ``spi/type/setdigest/`` + ``operator/aggregation/``'s
# HyperLogLog state and ``airlift/stats`` digests. TPU-first redesign:
# sketches are not opaque binary accumulator states — the logical planner
# rewrites the aggregate onto RELATIONAL algebra over these row-level
# primitives (register id / rank for HLL, log-bucket for DDSketch), so
# partial/final merging and exchange transport reuse the engine's
# ordinary distributed group-by kernels (planner/logical_planner.py
# _plan_sketch_aggs).

HLL_BITS = 11            #: m = 2048 registers -> standard error ~2.3%
HLL_M = 1 << HLL_BITS
HLL_ALPHA = 0.7213 / (1.0 + 1.079 / HLL_M)

DD_GAMMA = 1.0202027073175195   #: relative accuracy alpha = 0.01
DD_OFFSET = 40000               #: keeps positive-value buckets positive


def _splitmix64_dev(k):
    z = k + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _hash_u64_dev(raw, t):
    """Device value hash. Floats use a COLLISION-FREE bit encoding —
    the exchange path's *65536 quantization is fine for routing (a
    collision only skews partitioning) but would merge distinct values
    in a cardinality sketch. ``+0.0`` normalizes -0.0; the f64 bitcast
    runs only where f64 exists (CPU x64 — on TPU the storage is f32)."""
    import jax

    if t in (T.DOUBLE, T.REAL):
        x = raw + 0.0  # -0.0 -> +0.0
        if x.dtype == jnp.float64:
            k = jax.lax.bitcast_convert_type(x, jnp.uint64)
        else:
            k = jax.lax.bitcast_convert_type(
                x.astype(jnp.float32), jnp.uint32).astype(jnp.uint64)
    elif t == T.BOOLEAN:
        k = raw.astype(jnp.uint64)
    else:
        k = raw.astype(jnp.int64).view(jnp.uint64)
    return _splitmix64_dev(k)


def _hash_u64_host(v) -> int:
    """Host value hash for pooled (string/composite) arguments — any
    stable 64-bit digest works; bucket/rho only need consistency."""
    import hashlib

    digest = hashlib.blake2b(repr(v).encode(), digest_size=8).digest()
    return int.from_bytes(digest, "little")


def _resolve_sketchable(name):
    def resolve(args):
        (a,) = args
        if a == T.UNKNOWN:
            raise TypeError_(f"{name}() cannot hash NULL-typed input")
        return T.BIGINT

    return resolve


def _hll_bucket_kernel(raws, arg_types, ret_type):
    h = _hash_u64_dev(raws[0], arg_types[0])
    return (h & np.uint64(HLL_M - 1)).astype(jnp.int64)


def _bit_length_u64(v):
    """Vectorized bit_length by halving (exact, no float log)."""
    bl = jnp.zeros(v.shape, dtype=jnp.int64)
    x = v
    for s in (32, 16, 8, 4, 2, 1):
        m = x >= (np.uint64(1) << np.uint64(s))
        bl = bl + jnp.where(m, s, 0)
        x = jnp.where(m, x >> np.uint64(s), x)
    return bl + x.astype(jnp.int64)


def _hll_rho_kernel(raws, arg_types, ret_type):
    h = _hash_u64_dev(raws[0], arg_types[0])
    rest = h >> np.uint64(HLL_BITS)          # 53 remaining bits
    return (53 - _bit_length_u64(rest) + 1).astype(jnp.int64)


def _hll_bucket_host(v):
    return _hash_u64_host(v) & (HLL_M - 1)


def _hll_rho_host(v):
    rest = _hash_u64_host(v) >> HLL_BITS
    return 53 - rest.bit_length() + 1


register(ScalarFunction("$hll_bucket", _resolve_sketchable("$hll_bucket"),
                        _hll_bucket_kernel, str_scalar=_hll_bucket_host))
register(ScalarFunction("$hll_rho", _resolve_sketchable("$hll_rho"),
                        _hll_rho_kernel, str_scalar=_hll_rho_host))


def _resolve_dd_bucket(args):
    (a,) = args
    if not is_numeric(a):
        raise TypeError_(f"approx_percentile expects numeric, got {a}")
    return T.BIGINT


def _dd_bucket_kernel(raws, arg_types, ret_type):
    t = arg_types[0]
    x = jnp.asarray(raws[0], jnp.float64)
    if t.is_decimal:
        x = x / float(10 ** t.scale)
    mag = jnp.abs(x)
    lg = jnp.log(jnp.maximum(mag, 1e-300)) / math.log(DD_GAMMA)
    b = jnp.ceil(lg).astype(jnp.int64) + DD_OFFSET
    return jnp.where(mag < 1e-300, 0,
                     jnp.where(x > 0, b, -b)).astype(jnp.int64)


register(ScalarFunction("$dd_bucket", _resolve_dd_bucket,
                        _dd_bucket_kernel))


def _resolve_dd_value(args):
    return T.DOUBLE


def _dd_value_kernel(raws, arg_types, ret_type):
    b = raws[0]
    mag = jnp.abs(b).astype(jnp.float64) - DD_OFFSET
    # geometric midpoint of the bucket (gamma^(b-1), gamma^b]
    val = jnp.exp((mag - 0.5) * math.log(DD_GAMMA))
    return jnp.where(b == 0, 0.0, jnp.where(b > 0, val, -val))


register(ScalarFunction("$dd_value", _resolve_dd_value, _dd_value_kernel))
