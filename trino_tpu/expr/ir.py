"""Row-expression IR — the typed expression language operators execute.

Reference analog: ``io.trino.sql.relational.RowExpression`` hierarchy
(CallExpression, SpecialForm, InputReferenceExpression, ConstantExpression)
that the reference's bytecode compiler consumes (``sql/gen/``); here the
consumer is the JAX tracer in ``expr/compiler.py``.

Special forms are Calls with ``$``-prefixed names: ``$and $or $not $if
$case $coalesce $in $between $is_null $cast $like`` — they need non-default
null semantics or laziness, everything else is a registry function with
RETURN_NULL_ON_NULL convention.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from .. import types as T


@dataclass(frozen=True)
class RowExpression:
    type: T.Type


@dataclass(frozen=True)
class InputRef(RowExpression):
    """Reference to input channel (column index) of the page."""

    channel: int = 0

    def __repr__(self):
        return f"#{self.channel}:{self.type}"


@dataclass(frozen=True)
class Literal(RowExpression):
    value: Any = None  # python value; None = typed NULL

    def __repr__(self):
        return f"lit({self.value!r}:{self.type})"


@dataclass(frozen=True)
class ParamRef(RowExpression):
    """Opaque plan-template parameter: the i-th cache-marked literal of a
    normalized statement shape (``cache.normalize_statement``).

    Deliberately NOT a ``Literal`` subclass — every plan-time constant
    reader (constant folding, domain translation, rank bounds) is
    ``isinstance(_, Literal)``-gated, so a ParamRef is opaque by
    construction: one optimized plan serves every literal vector of the
    shape.  The compiler binds it to a traced input slot instead of a
    baked constant, which is what lets a same-shape batch ``vmap`` over
    the stacked literal axis.
    """

    index: int = 0

    def __repr__(self):
        return f"param({self.index}):{self.type}"


@dataclass(frozen=True)
class Call(RowExpression):
    name: str = ""
    args: Tuple[RowExpression, ...] = ()

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


def input_channels(expr: RowExpression) -> set:
    """All input channels referenced by an expression tree."""
    out = set()

    def walk(e):
        if isinstance(e, InputRef):
            out.add(e.channel)
        elif isinstance(e, Call):
            for a in e.args:
                walk(a)

    walk(expr)
    return out


def param_indices(expr: RowExpression) -> set:
    """All template-parameter indices referenced by an expression tree."""
    out = set()

    def walk(e):
        if isinstance(e, ParamRef):
            out.add(e.index)
        elif isinstance(e, Call):
            for a in e.args:
                walk(a)

    walk(expr)
    return out
