"""Time-zone rules as device arrays.

Reference analog: ``spi/type/TimestampWithTimeZoneType.java`` +
``spi/type/DateTimeEncoding.java`` (packed millis | zoneKey) and the Joda
zone rules the engine evaluates per value on the JVM.

TPU redesign: a TIMESTAMP WITH TIME ZONE column stores **UTC micros as
int64 on device** (instant semantics — comparison/join/group-by are plain
int64 ops, exactly the reference's "order by UTC instant" contract) and
carries its zone as *column metadata* on the type. Zone-rule evaluation
(wall-clock conversion for casts, EXTRACT, formatting) becomes a
vectorized ``searchsorted`` over the zone's DST transition table uploaded
once per zone — no per-value host calls, no scalar loops.

Transition tables come from parsing the binary TZif files under
``/usr/share/zoneinfo`` (RFC 8536; ``zoneinfo.ZoneInfo`` hides them), and
fixed offsets (``+05:30``) are handled directly.
"""

from __future__ import annotations

import os
import re
import struct
from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

TZDIR = "/usr/share/zoneinfo"

_FIXED_RE = re.compile(r"^(?:UTC)?([+-])(\d{1,2}):?(\d{2})$")

#: sentinel first transition: effectively -inf
_NEG_INF = np.int64(-(1 << 62))


def canonical_zone(zone: str) -> str:
    z = zone.strip()
    if z.upper() in ("UTC", "Z", "UT", "GMT", "+00:00", "-00:00"):
        return "UTC"
    return z


def parse_fixed_offset_micros(zone: str) -> Optional[int]:
    """``+HH:MM`` / ``-HH:MM`` (optionally ``UTC``-prefixed) -> micros,
    or None if the zone is not a fixed offset."""
    z = canonical_zone(zone)
    if z == "UTC":
        return 0
    m = _FIXED_RE.match(z)
    if m is None:
        return None
    sign = 1 if m.group(1) == "+" else -1
    return sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60) * 1_000_000


def _parse_tzif(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """RFC 8536 TZif -> (transition instants in UTC seconds, utc offsets
    in seconds applying from each instant). First entry is the -inf
    sentinel carrying the pre-first-transition offset."""
    with open(path, "rb") as f:
        data = f.read()

    def header(off):
        magic, version = data[off:off + 4], data[off + 4:off + 5]
        if magic != b"TZif":
            raise ValueError(f"not a TZif file: {path}")
        counts = struct.unpack(">6I", data[off + 20:off + 44])
        return version, counts  # isutcnt isstdcnt leapcnt timecnt typecnt charcnt

    version, counts = header(0)
    isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = counts
    v1_len = 44 + timecnt * 5 + typecnt * 6 + charcnt + leapcnt * 8 \
        + isstdcnt + isutcnt
    if version >= b"2":
        # second, 64-bit block follows the v1 block
        off = v1_len
        version, counts = header(off)
        isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = counts
        body = off + 44
        tsize = 8
        tfmt = ">%dq"
    else:
        body = 44
        tsize = 4
        tfmt = ">%dl"
    trans = np.array(struct.unpack(tfmt % timecnt,
                                   data[body:body + timecnt * tsize]),
                     dtype=np.int64) if timecnt else np.zeros(0, np.int64)
    p = body + timecnt * tsize
    idx = np.frombuffer(data[p:p + timecnt], dtype=np.uint8)
    p += timecnt
    ttinfo = [struct.unpack(">lBB", data[p + i * 6:p + i * 6 + 6])
              for i in range(typecnt)]
    offsets = np.array([t[0] for t in ttinfo], dtype=np.int64)
    isdst = [t[1] for t in ttinfo]
    # pre-first-transition offset: first non-DST type (RFC 8536 §3.2)
    first = next((i for i in range(typecnt) if not isdst[i]), 0)
    out_trans = np.concatenate([[_NEG_INF], trans])
    out_offs = np.concatenate([[offsets[first]],
                               offsets[idx] if timecnt
                               else np.zeros(0, np.int64)])
    return out_trans, out_offs


@lru_cache(maxsize=64)
def utc_offset_table(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """(transitions_us, offsets_us): ``offsets_us[i]`` is the UTC offset
    for instants in ``[transitions_us[i], transitions_us[i+1])``."""
    z = canonical_zone(zone)
    fixed = parse_fixed_offset_micros(z)
    if fixed is not None:
        return (np.array([_NEG_INF], dtype=np.int64),
                np.array([fixed], dtype=np.int64))
    path = os.path.join(TZDIR, z)
    if not os.path.exists(path):
        raise ValueError(f"unknown time zone: {zone}")
    trans_s, offs_s = _parse_tzif(path)
    trans = np.where(trans_s == _NEG_INF, _NEG_INF, trans_s * 1_000_000)
    return trans.astype(np.int64), (offs_s * 1_000_000).astype(np.int64)


@lru_cache(maxsize=64)
def wall_offset_table(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """Like utc_offset_table but keyed by *wall* time: entry i applies to
    wall instants ``>= trans_utc[i] + offset[i]``. Ambiguous wall times
    around backward transitions resolve to the later (post-transition)
    offset; gapped wall times resolve forward — the conventional
    single-valued inverse."""
    trans, offs = utc_offset_table(zone)
    wall = np.where(trans == _NEG_INF, _NEG_INF, trans + offs)
    # enforce monotonicity (backward transitions make wall go back)
    wall = np.maximum.accumulate(wall)
    return wall.astype(np.int64), offs


def utc_to_wall_np(vals: np.ndarray, zone: str) -> np.ndarray:
    trans, offs = utc_offset_table(zone)
    i = np.searchsorted(trans, vals, side="right") - 1
    return vals + offs[np.clip(i, 0, len(offs) - 1)]


def wall_to_utc_host(wall_micros: int, zone: str) -> int:
    """Host scalar wall-clock micros in ``zone`` -> UTC micros (literal
    analysis and other one-off host conversions)."""
    wtab, woffs = wall_offset_table(zone)
    i = int(np.searchsorted(wtab, wall_micros, side="right")) - 1
    return wall_micros - int(woffs[max(0, min(i, len(woffs) - 1))])


def offset_at(zone: str, utc_micros: int) -> int:
    trans, offs = utc_offset_table(zone)
    i = int(np.searchsorted(trans, utc_micros, side="right")) - 1
    return int(offs[max(0, min(i, len(offs) - 1))])


# -------------------------------------------------------------- device ----

def device_utc_to_wall(vals, zone: str):
    """jnp int64 UTC micros -> wall micros in ``zone`` (device op)."""
    import jax.numpy as jnp

    trans, offs = utc_offset_table(zone)
    if len(offs) == 1:  # fixed offset: no table needed
        return vals + np.int64(offs[0])
    t = jnp.asarray(trans)
    o = jnp.asarray(offs)
    i = jnp.clip(jnp.searchsorted(t, vals, side="right") - 1, 0,
                 len(offs) - 1)
    return vals + o[i]


def device_wall_to_utc(vals, zone: str):
    """jnp int64 wall micros in ``zone`` -> UTC micros (device op)."""
    import jax.numpy as jnp

    wall, offs = wall_offset_table(zone)
    if len(offs) == 1:
        return vals - np.int64(offs[0])
    t = jnp.asarray(wall)
    o = jnp.asarray(offs)
    i = jnp.clip(jnp.searchsorted(t, vals, side="right") - 1, 0,
                 len(offs) - 1)
    return vals - o[i]
