"""Time-zone rules as device arrays.

Reference analog: ``spi/type/TimestampWithTimeZoneType.java`` +
``spi/type/DateTimeEncoding.java`` (packed millis | zoneKey) and the Joda
zone rules the engine evaluates per value on the JVM.

TPU redesign: a TIMESTAMP WITH TIME ZONE column stores **UTC micros as
int64 on device** (instant semantics — comparison/join/group-by are plain
int64 ops, exactly the reference's "order by UTC instant" contract) and
carries its zone as *column metadata* on the type. Zone-rule evaluation
(wall-clock conversion for casts, EXTRACT, formatting) becomes a
vectorized ``searchsorted`` over the zone's DST transition table uploaded
once per zone — no per-value host calls, no scalar loops.

Transition tables come from parsing the binary TZif files under
``/usr/share/zoneinfo`` (RFC 8536; ``zoneinfo.ZoneInfo`` hides them), and
fixed offsets (``+05:30``) are handled directly.
"""

from __future__ import annotations

import os
import re
import struct
from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

TZDIR = "/usr/share/zoneinfo"

_FIXED_RE = re.compile(r"^(?:UTC)?([+-])(\d{1,2}):?(\d{2})$")

#: sentinel first transition: effectively -inf
_NEG_INF = np.int64(-(1 << 62))


def canonical_zone(zone: str) -> str:
    z = zone.strip()
    if z.upper() in ("UTC", "Z", "UT", "GMT", "+00:00", "-00:00"):
        return "UTC"
    return z


def parse_fixed_offset_micros(zone: str) -> Optional[int]:
    """``+HH:MM`` / ``-HH:MM`` (optionally ``UTC``-prefixed) -> micros,
    or None if the zone is not a fixed offset."""
    z = canonical_zone(zone)
    if z == "UTC":
        return 0
    m = _FIXED_RE.match(z)
    if m is None:
        return None
    sign = 1 if m.group(1) == "+" else -1
    return sign * (int(m.group(2)) * 3600 + int(m.group(3)) * 60) * 1_000_000


def _parse_tzif(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """RFC 8536 TZif -> (transition instants in UTC seconds, utc offsets
    in seconds applying from each instant). First entry is the -inf
    sentinel carrying the pre-first-transition offset."""
    with open(path, "rb") as f:
        data = f.read()

    def header(off):
        magic, version = data[off:off + 4], data[off + 4:off + 5]
        if magic != b"TZif":
            raise ValueError(f"not a TZif file: {path}")
        counts = struct.unpack(">6I", data[off + 20:off + 44])
        return version, counts  # isutcnt isstdcnt leapcnt timecnt typecnt charcnt

    version, counts = header(0)
    isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = counts
    v1_len = 44 + timecnt * 5 + typecnt * 6 + charcnt + leapcnt * 8 \
        + isstdcnt + isutcnt
    has_footer = version >= b"2"
    if has_footer:
        # second, 64-bit block follows the v1 block
        off = v1_len
        version, counts = header(off)
        isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = counts
        body = off + 44
        tsize = 8
        tfmt = ">%dq"
    else:
        body = 44
        tsize = 4
        tfmt = ">%dl"
    trans = np.array(struct.unpack(tfmt % timecnt,
                                   data[body:body + timecnt * tsize]),
                     dtype=np.int64) if timecnt else np.zeros(0, np.int64)
    p = body + timecnt * tsize
    idx = np.frombuffer(data[p:p + timecnt], dtype=np.uint8)
    p += timecnt
    ttinfo = [struct.unpack(">lBB", data[p + i * 6:p + i * 6 + 6])
              for i in range(typecnt)]
    offsets = np.array([t[0] for t in ttinfo], dtype=np.int64)
    isdst = [t[1] for t in ttinfo]
    # pre-first-transition offset: first non-DST type (RFC 8536 §3.2)
    first = next((i for i in range(typecnt) if not isdst[i]), 0)
    out_trans = np.concatenate([[_NEG_INF], trans])
    out_offs = np.concatenate([[offsets[first]],
                               offsets[idx] if timecnt
                               else np.zeros(0, np.int64)])
    if has_footer:
        # v2+ footer: a POSIX TZ string giving the rule for instants
        # past the last tabulated transition (RFC 8536 §3.3). Without
        # it the last offset freezes (~2037 for fat tzdata).
        parts = data.rsplit(b"\n", 2)
        tzstr = parts[1].decode("ascii", "replace") if len(parts) == 3 \
            else ""
        ext = _footer_transitions(tzstr, out_trans, out_offs)
        if ext is not None:
            out_trans, out_offs = ext
    return out_trans, out_offs


#: how far the footer rule is unrolled into explicit transitions
_FOOTER_END_YEAR = 2100

_TZNAME = r"(?:[A-Za-z]{3,}|<[A-Za-z0-9+-]+>)"
_TZOFF = r"([+-]?\d{1,3}(?::\d{1,2}(?::\d{1,2})?)?)"
_POSIX_TZ_RE = re.compile(
    rf"^{_TZNAME}{_TZOFF}(?:({_TZNAME}){_TZOFF}?(?:,([^,]+),([^,]+))?)?$")


def _hms_seconds(s: str) -> int:
    sign = -1 if s.startswith("-") else 1
    parts = s.lstrip("+-").split(":")
    sec = 0
    for unit, v in zip((3600, 60, 1), parts):
        sec += unit * int(v)
    return sign * sec


def _rule_instant(rule: str, year: int) -> int:
    """Local epoch-seconds (as if UTC) of one POSIX transition rule in
    ``year``: Mm.w.d, Jn, or n, with optional /time (default 02:00;
    extended range ±167h allowed)."""
    import calendar
    import datetime

    time_s = 2 * 3600
    if "/" in rule:
        rule, t = rule.split("/", 1)
        time_s = _hms_seconds(t)
    if rule.startswith("M"):
        m, w, d = (int(x) for x in rule[1:].split("."))
        first_wd = (datetime.date(year, m, 1).weekday() + 1) % 7  # Sun=0
        day = 1 + (d - first_wd) % 7 + (w - 1) * 7
        while day > calendar.monthrange(year, m)[1]:
            day -= 7
        date = datetime.date(year, m, day)
    elif rule.startswith("J"):
        n = int(rule[1:])  # 1..365, Feb 29 never counted
        date = datetime.date(year, 1, 1) + datetime.timedelta(n - 1)
        if calendar.isleap(year) and n >= 60:
            date += datetime.timedelta(1)
    else:
        n = int(rule)      # 0..365, Feb 29 counted
        date = datetime.date(year, 1, 1) + datetime.timedelta(n)
    epoch_day = (date - datetime.date(1970, 1, 1)).days
    return epoch_day * 86400 + time_s


def _footer_transitions(tzstr: str, trans: np.ndarray,
                        offs: np.ndarray):
    """Extend (trans, offs) with transitions synthesized from the footer
    POSIX TZ string through ``_FOOTER_END_YEAR``, or None if the string
    is absent/unsupported/DST-free (the frozen last offset is then
    already correct for a constant-offset tail)."""
    import datetime

    m = _POSIX_TZ_RE.match(tzstr.strip())
    if m is None:
        return None
    std_s, dst_name, dst_s, start_rule, end_rule = m.groups()
    if not dst_name or not start_rule:
        return None  # no DST tail: constant offset, nothing to extend
    std_off = -_hms_seconds(std_s)          # POSIX: positive = west
    dst_off = (-_hms_seconds(dst_s)) if dst_s else std_off + 3600
    last = int(trans[-1]) if len(trans) > 1 else 0
    y0 = datetime.datetime.fromtimestamp(
        max(last, 0), datetime.timezone.utc).year
    new = []
    for year in range(y0, _FOOTER_END_YEAR + 1):
        try:
            to_dst = _rule_instant(start_rule, year) - std_off
            to_std = _rule_instant(end_rule, year) - dst_off
        except (ValueError, IndexError):
            return None
        new.extend([(to_dst, dst_off), (to_std, std_off)])
    new = [(t, o) for (t, o) in sorted(new) if t > last]
    if not new:
        return None
    return (np.concatenate([trans, np.array([t for t, _ in new],
                                            dtype=np.int64)]),
            np.concatenate([offs, np.array([o for _, o in new],
                                           dtype=np.int64)]))


@lru_cache(maxsize=64)
def utc_offset_table(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """(transitions_us, offsets_us): ``offsets_us[i]`` is the UTC offset
    for instants in ``[transitions_us[i], transitions_us[i+1])``."""
    z = canonical_zone(zone)
    fixed = parse_fixed_offset_micros(z)
    if fixed is not None:
        return (np.array([_NEG_INF], dtype=np.int64),
                np.array([fixed], dtype=np.int64))
    path = os.path.join(TZDIR, z)
    if not os.path.exists(path):
        raise ValueError(f"unknown time zone: {zone}")
    trans_s, offs_s = _parse_tzif(path)
    trans = np.where(trans_s == _NEG_INF, _NEG_INF, trans_s * 1_000_000)
    return trans.astype(np.int64), (offs_s * 1_000_000).astype(np.int64)


@lru_cache(maxsize=64)
def wall_offset_table(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """Like utc_offset_table but keyed by *wall* time. Entry i applies to
    wall instants ``>= trans_utc[i] + max(offset[i], offset[i-1])``:
    ambiguous wall times in a fall-back overlap stay in entry i-1 (the
    EARLIER, pre-transition offset — the reference's Joda
    ``convertLocalToUTC`` pick), and nonexistent spring-forward gap
    times also resolve with the pre-transition offset (clock carried
    forward across the gap)."""
    trans, offs = utc_offset_table(zone)
    prev = np.concatenate([offs[:1], offs[:-1]])
    wall = np.where(trans == _NEG_INF, _NEG_INF,
                    trans + np.maximum(offs, prev))
    # safety: keep starts monotone for searchsorted
    wall = np.maximum.accumulate(wall)
    return wall.astype(np.int64), offs


def utc_to_wall_np(vals: np.ndarray, zone: str) -> np.ndarray:
    trans, offs = utc_offset_table(zone)
    i = np.searchsorted(trans, vals, side="right") - 1
    return vals + offs[np.clip(i, 0, len(offs) - 1)]


def wall_to_utc_host(wall_micros: int, zone: str) -> int:
    """Host scalar wall-clock micros in ``zone`` -> UTC micros (literal
    analysis and other one-off host conversions)."""
    wtab, woffs = wall_offset_table(zone)
    i = int(np.searchsorted(wtab, wall_micros, side="right")) - 1
    return wall_micros - int(woffs[max(0, min(i, len(woffs) - 1))])


def offset_at(zone: str, utc_micros: int) -> int:
    trans, offs = utc_offset_table(zone)
    i = int(np.searchsorted(trans, utc_micros, side="right")) - 1
    return int(offs[max(0, min(i, len(offs) - 1))])


# -------------------------------------------------------------- device ----

def device_utc_to_wall(vals, zone: str):
    """jnp int64 UTC micros -> wall micros in ``zone`` (device op)."""
    import jax.numpy as jnp

    trans, offs = utc_offset_table(zone)
    if len(offs) == 1:  # fixed offset: no table needed
        return vals + np.int64(offs[0])
    t = jnp.asarray(trans)
    o = jnp.asarray(offs)
    i = jnp.clip(jnp.searchsorted(t, vals, side="right") - 1, 0,
                 len(offs) - 1)
    return vals + o[i]


def device_wall_to_utc(vals, zone: str):
    """jnp int64 wall micros in ``zone`` -> UTC micros (device op)."""
    import jax.numpy as jnp

    wall, offs = wall_offset_table(zone)
    if len(offs) == 1:
        return vals - np.int64(offs[0])
    t = jnp.asarray(wall)
    o = jnp.asarray(offs)
    i = jnp.clip(jnp.searchsorted(t, vals, side="right") - 1, 0,
                 len(offs) - 1)
    return vals - o[i]
