"""Process-wide jit trace (cache-miss) counters.

The classic JAX perf bug is silent retracing: a jitted kernel whose
cache key varies page-to-page recompiles forever and the engine slides
to interpreter speed. These counters make "same-shape pages do not
retrace" an assertable invariant: every jitted hot-path function bumps
a named counter INSIDE its traced body, so the bump executes exactly
once per cache miss (trace) and never on a cache hit.

The driver snapshots ``total()`` around each operator call and
attributes the delta to that operator's stats, which flow into EXPLAIN
ANALYZE and the bench output (reference analog: the per-operator
``*CompilerStats`` / planner bytecode-compilation counters that
Trino exposes through OperatorStats metrics).
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_counts: Dict[str, int] = {}
_tls = threading.local()


def bump(name: str) -> None:
    """Record one trace of the named kernel. Call from INSIDE the
    jitted function body — the Python body only runs at trace time."""
    with _lock:
        _counts[name] = _counts.get(name, 0) + 1
    _tls.total = getattr(_tls, "total", 0) + 1


def counts() -> Dict[str, int]:
    with _lock:
        return dict(_counts)


def total() -> int:
    with _lock:
        return sum(_counts.values())


def total_for(*names: str) -> int:
    """Sum of the named counters (0 for never-traced kernels) — lets
    tests assert "this specific kernel did not retrace" without being
    perturbed by unrelated kernels tracing concurrently."""
    with _lock:
        return sum(_counts.get(n, 0) for n in names)


def thread_total() -> int:
    """Traces recorded on THIS thread. Tracing runs synchronously on
    the thread that called the jitted function, so snapshot deltas of
    this value attribute traces to the enclosing operator call exactly,
    even with concurrent task drivers (a global snapshot would charge
    thread A with thread B's traces)."""
    return getattr(_tls, "total", 0)


def reset() -> None:
    """Zero the counters (tests). Does NOT clear any jit cache: a
    kernel already compiled stays warm and will not re-bump."""
    with _lock:
        _counts.clear()
