from .operator import Operator, SourceOperator  # noqa: F401
