"""Hash aggregation, TPU-first.

Reference analog: ``operator/HashAggregationOperator.java`` +
``operator/MultiChannelGroupByHash.java`` (vectorized open-addressing
putIfAbsent) + the bytecode-compiled accumulators
(``operator/aggregation/AccumulatorCompiler.java``).

Grouping runs one of two paths:

- **hash** (default): the vectorized open-addressing table of
  ``ops/hashtable.py`` assigns each row a dense group id via bounded
  linear-probe rounds of masked scatter/gather — no sort, and state
  columns never ride through comparator operands. The segment reduce
  then runs over the hash-assigned gids (one cheap gid-only sort first
  when the Pallas TPU kernel — which requires sorted segments — is
  active). Float grouping keys and probe-budget overflow fall back to:
- **sort** (oracle/fallback): normalize key columns to (null-bit,
  uint64) operand pairs, ``lax.sort`` the batch lexicographically,
  detect group boundaries by adjacent-row comparison, cumsum dense
  group ids, segment-reduce. Forceable via the ``hash_grouping_enabled``
  session property for cross-checking.

Streaming: each input page is partially aggregated on device (bounded
output = its own row count), partials accumulate; ``finish`` re-groups the
concatenated partials and applies final projections. This mirrors the
reference's partial/final adapter split and keeps memory proportional to
groups, not input rows.

**Adaptive partial aggregation** (reference:
``adaptive_partial_aggregation_enabled``; "Partial Partial Aggregates",
PAPERS.md): a partial-step operator observes its groups-to-rows
reduction ratio; once enough rows show grouping is not reducing
(ratio above threshold), it stops aggregating and passes pages through
in the intermediate keys+states layout — the final step re-groups, so
results are unchanged while the partial stops burning time on
high-cardinality keys.

**Per-key-range decision** ("Partial Partial Aggregates" proper): the
observation window tracks the reduction ratio PER KEY-RANGE BUCKET
(the hashed key space split into ``adaptive_key_buckets`` ranges), and
the pass-through switch flips per bucket — a skewed stream keeps
aggregating its hot (duplicate-heavy) ranges while cold (mostly-
unique) ranges pass through ungrouped, instead of one all-or-nothing
stream decision.  A decided split emits two pages per input page (the
aggregated hot-range partial + the cold-range pass-through), both in
the intermediate layout the final step re-groups anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit_stats
from .. import types as T
from ..block import DevicePage, padded_size
from ..telemetry.profiler import instrument
from ..types import TypeError_
from .hashtable import (_mix_operands, hash_group_ids,
                        hash_segment_reduce, hashable_key_types)
from .operator import Operator
from .sortkeys import group_operands

#: adaptive partial aggregation: minimum observed input rows before the
#: reduction ratio is trusted (reference default: 100k rows)
ADAPTIVE_MIN_ROWS = 100_000
#: groups/rows ratio above which the partial step stops aggregating
ADAPTIVE_RATIO_THRESHOLD = 0.9
#: key-range buckets the pass-through decision is made over (1 = one
#: global per-stream decision; ``adaptive_partial_aggregation_key_
#: range_buckets``)
ADAPTIVE_KEY_BUCKETS = 8


# ---------------------------------------------------------------------------
# aggregate function descriptors
# (reference analog: operator/aggregation/* builtin implementations)


@dataclass(frozen=True)
class AggCall:
    """One aggregate in a GROUP BY: function over an input channel."""

    function: str                 # count | count_star | sum | avg | min | max
    arg_channel: Optional[int]    # None for count(*)
    arg_type: Optional[T.Type]
    output_type: T.Type
    distinct: bool = False


def resolve_agg_type(function: str, arg_type: Optional[T.Type]) -> T.Type:
    if function in ("count", "count_star"):
        return T.BIGINT
    if function == "sum":
        if arg_type.is_decimal:
            return T.decimal_type(18, arg_type.scale)
        if arg_type in (T.REAL, T.DOUBLE):
            return T.DOUBLE
        if arg_type in (T.TINYINT, T.SMALLINT, T.INTEGER, T.BIGINT):
            return T.BIGINT
        raise TypeError_(f"cannot sum {arg_type}")
    if function == "avg":
        if arg_type.is_decimal:
            return arg_type
        return T.DOUBLE
    if function in ("min", "max", "arbitrary", "any_value"):
        return arg_type
    if function in ("stddev", "stddev_samp", "stddev_pop", "variance",
                    "var_samp", "var_pop", "geometric_mean"):
        return T.DOUBLE
    if function in ("bool_and", "bool_or", "every"):
        if arg_type != T.BOOLEAN:
            raise TypeError_(f"{function} expects boolean, got {arg_type}")
        return T.BOOLEAN
    if function == "count_if":
        if arg_type != T.BOOLEAN:
            raise TypeError_(f"count_if expects boolean, got {arg_type}")
        return T.BIGINT
    if function == "approx_distinct":
        return T.BIGINT
    if function == "approx_percentile":
        # same-type contract as the reference; the sketch rewrite
        # rounds back for integers (logical_planner._plan_dd_percentile)
        if arg_type in (T.TINYINT, T.SMALLINT, T.INTEGER, T.BIGINT):
            return T.BIGINT
        if arg_type in (T.REAL, T.DOUBLE):
            return T.DOUBLE
        if arg_type.is_decimal:
            return arg_type
        raise TypeError_(
            f"approx_percentile does not support {arg_type} yet")
    raise TypeError_(f"unknown aggregate function {function}")


# Each aggregate lowers to a list of (reduce_kind, state_dtype) states:
#   sum   -> [sum(x), count(nonnull)]
#   count -> [count(nonnull)]
#   avg   -> [sum(x), count(nonnull)]
#   min   -> [min(x or +sentinel), count]
#   max   -> [max(x or -sentinel), count]
#   stddev/variance -> [sum(x), sum(x^2), count]  (as float64)


def _state_plan(agg: AggCall):
    f = agg.function
    if f in ("count_star", "count", "count_if"):
        return [("sum", jnp.int64)]
    if f in ("sum", "avg"):
        dt = jnp.float64 if (agg.arg_type in (T.REAL, T.DOUBLE)) else jnp.int64
        return [("sum", dt), ("sum", jnp.int64)]
    if f in ("min", "arbitrary", "any_value", "bool_and", "every"):
        return [("min", None), ("sum", jnp.int64)]
    if f in ("max", "bool_or"):
        return [("max", None), ("sum", jnp.int64)]
    if f == "geometric_mean":
        return [("sum", jnp.float64), ("sum", jnp.int64)]
    if f in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
             "var_pop"):
        return [("sum", jnp.float64), ("sum", jnp.float64),
                ("sum", jnp.int64)]
    raise TypeError_(f"unknown aggregate function {f}")


def intermediate_state_types(function: str,
                             arg_type: Optional[T.Type]) -> List[T.Type]:
    """SQL types of one aggregate's partial-state columns (the wire
    layout of partial-aggregation exchange pages). String min/max
    states are VARCHAR: partials carry dictionary CODES so exchanges
    unify pools; the reduce itself runs on lexicographic ranks (codes
    are pool-order, not value-order) and maps back to codes at every
    page boundary."""
    call = AggCall(function, None, arg_type, T.BIGINT)
    out: List[T.Type] = []
    for (kind, dt) in _state_plan(call):
        if kind in ("min", "max"):
            if arg_type in (T.REAL, T.DOUBLE):
                out.append(T.DOUBLE)
            elif arg_type == T.BOOLEAN:
                out.append(T.BIGINT)  # 0/1 lanes (bool_and/bool_or)
            else:
                out.append(arg_type or T.BIGINT)
        else:
            out.append(T.DOUBLE if dt == jnp.float64 else T.BIGINT)
    return out


_RANK_INV_CACHE: dict = {}


def _rank_and_inverse(dictionary):
    """(rank_lut, inverse_lut): rank_lut[code] = dense lex rank;
    inverse_lut[rank] = FIRST code of that rank (aligned pools may
    repeat values). Cached per (pool, size) — pools are append-only."""
    import numpy as np

    if dictionary is None or len(dictionary) == 0:
        return (np.zeros(1, dtype=np.int64), np.zeros(1, dtype=np.int32))
    key = (id(dictionary), len(dictionary))
    hit = _RANK_INV_CACHE.get(key)
    if hit is not None and hit[0] is dictionary:
        return hit[1], hit[2]
    ranks = dictionary.sort_rank().astype(np.int64)
    nr = int(ranks.max()) + 1 if len(ranks) else 1
    inv = np.zeros(nr, dtype=np.int32)
    # reversed scatter: the FIRST code of each rank lands last, winning
    inv[ranks[::-1]] = np.arange(len(ranks) - 1, -1, -1, dtype=np.int32)
    if len(_RANK_INV_CACHE) >= 256:
        _RANK_INV_CACHE.clear()
    _RANK_INV_CACHE[key] = (dictionary, ranks, inv)
    return ranks, inv


def _init_states(agg: AggCall, cols, nulls, valid, dicts=None,
                 rank_lut=None) -> List:
    """Per-row initial state columns for one aggregate.

    ``rank_lut``: precomputed lexicographic-rank LUT ARRAY for a pooled
    min/max arg (the batched executor passes it as a traced vmap
    operand so the host-side ``_rank_and_inverse`` pool walk never runs
    inside a trace); None = derive it from ``dicts`` on host."""
    f = agg.function
    if f == "count_star":
        return [valid.astype(jnp.int64)]
    raw = cols[agg.arg_channel]
    nl = nulls[agg.arg_channel]
    live = valid & ~nl
    if f == "count":
        return [live.astype(jnp.int64)]
    if f == "count_if":
        return [(live & raw.astype(bool)).astype(jnp.int64)]
    if f in ("bool_and", "every", "bool_or"):
        # min/max over {0,1}; dead lanes take the neutral sentinel
        neutral = 1 if f != "bool_or" else 0
        x = jnp.where(live, raw.astype(jnp.int64), neutral)
        return [x, live.astype(jnp.int64)]
    if f == "geometric_mean":
        x = raw.astype(jnp.float64)
        if agg.arg_type is not None and agg.arg_type.is_decimal:
            x = x / (10.0 ** agg.arg_type.scale)
        # log(0) = -inf => result 0; log(<0) = NaN => result NaN (the
        # reference's semantics); dead lanes are masked by `live`
        return [jnp.where(live, jnp.log(x), 0.0),
                live.astype(jnp.int64)]
    if f in ("arbitrary", "any_value"):
        f = "min"  # deterministic pick: the smallest value
        agg = AggCall("min", agg.arg_channel, agg.arg_type,
                      agg.output_type)
    if f in ("sum", "avg"):
        if agg.arg_type in (T.REAL, T.DOUBLE):
            x = raw.astype(jnp.float64)
            return [jnp.where(live, x, 0.0), live.astype(jnp.int64)]
        x = raw.astype(jnp.int64)
        return [jnp.where(live, x, 0), live.astype(jnp.int64)]
    if f in ("min", "max"):
        if agg.arg_type is not None and agg.arg_type.is_pooled:
            # reduce on lexicographic RANKS (codes are pool-order);
            # _map_rank_states restores codes after the reduce
            if rank_lut is None:
                rank_lut, _ = _rank_and_inverse(
                    dicts[agg.arg_channel] if dicts is not None else None)
            ranks = jnp.asarray(rank_lut)[raw]
            info = jnp.iinfo(jnp.int64)
            sent = info.max if f == "min" else info.min
            x = jnp.where(live, ranks, jnp.asarray(sent, dtype=jnp.int64))
            return [x, live.astype(jnp.int64)]
        if agg.arg_type in (T.REAL, T.DOUBLE):
            sent = jnp.inf if f == "min" else -jnp.inf
            x = jnp.where(live, raw.astype(jnp.float64), sent)
        else:
            if raw.dtype == jnp.bool_:
                raw = raw.astype(jnp.int64)
            info = jnp.iinfo(raw.dtype)
            sent = info.max if f == "min" else info.min
            x = jnp.where(live, raw, jnp.asarray(sent, dtype=raw.dtype))
        return [x, live.astype(jnp.int64)]
    # stddev family
    x = jnp.where(live, raw.astype(jnp.float64), 0.0)
    if agg.arg_type is not None and agg.arg_type.is_decimal:
        x = x / (10.0 ** agg.arg_type.scale)
    return [x, x * x, live.astype(jnp.int64)]


def _merge_states(agg: AggCall, state_cols, valid, state_dicts=None,
                  rank_luts=None) -> List:
    """Partial-state columns re-entering a (final) aggregation: states
    combine with their own reduce kinds. min/max values are neutralized
    to their sentinel on invalid lanes AND on empty partials (count
    state 0 — e.g. the one empty-input row a global partial emits),
    which would otherwise contribute a bogus 0. String min/max states
    arrive as codes and re-enter the reduce as lexicographic ranks.
    ``rank_luts``: per-state precomputed rank LUT arrays (traced vmap
    operands, see ``_init_states``); None = derive from
    ``state_dicts`` on host."""
    plan = _state_plan(agg)
    count = state_cols[-1]  # every aggregate's last state is its count
    is_str = agg.arg_type is not None and agg.arg_type.is_pooled
    out = []
    for j, ((kind, _dt), s) in enumerate(zip(plan, state_cols)):
        if kind == "sum":
            z = jnp.zeros((), dtype=s.dtype)
            out.append(jnp.where(valid, s, z))
        else:
            live = valid & (count > 0)
            if is_str and kind in ("min", "max"):
                rank_lut = rank_luts[j] if rank_luts is not None else None
                if rank_lut is None:
                    rank_lut, _ = _rank_and_inverse(
                        state_dicts[j] if state_dicts is not None
                        else None)
                s = jnp.asarray(rank_lut)[s]
                info = jnp.iinfo(jnp.int64)
                sent = info.max if kind == "min" else info.min
                out.append(jnp.where(live, s.astype(jnp.int64),
                                     jnp.asarray(sent, dtype=jnp.int64)))
                continue
            if kind == "min":
                sent = jnp.inf if s.dtype == jnp.float64 \
                    else jnp.iinfo(s.dtype).max
            else:
                sent = -jnp.inf if s.dtype == jnp.float64 \
                    else jnp.iinfo(s.dtype).min
            out.append(jnp.where(live, s, jnp.asarray(sent, dtype=s.dtype)))
    return out


def _final_project(agg: AggCall, states: List):
    """states (per-group reduced) -> (raw, null) in output_type storage."""
    f = agg.function
    ot = agg.output_type
    if f in ("count", "count_star", "count_if"):
        return states[0], jnp.zeros(states[0].shape, dtype=jnp.bool_)
    cnt = states[-1]
    null = cnt == 0
    if f == "sum":
        return states[0].astype(ot.storage), null
    if f == "avg":
        s = states[0]
        if ot.is_decimal:
            from ..expr.functions import div_round_half_up
            return div_round_half_up(s, jnp.maximum(cnt, 1)), null
        return s.astype(jnp.float64) / jnp.maximum(cnt, 1), null
    if f in ("min", "max", "arbitrary", "any_value"):
        return states[0].astype(ot.storage), null
    if f in ("bool_and", "every", "bool_or"):
        return (states[0] != 0), null
    if f == "geometric_mean":
        return jnp.exp(states[0] / jnp.maximum(cnt, 1)), null
    # stddev family
    s, s2 = states[0], states[1]
    n = jnp.maximum(cnt, 1).astype(jnp.float64)
    mean = s / n
    m2 = jnp.maximum(s2 / n - mean * mean, 0.0)
    pop = f in ("stddev_pop", "var_pop")
    denom = jnp.where(pop, n, jnp.maximum(n - 1, 1))
    var = m2 * n / denom
    if f.startswith("stddev"):
        var = jnp.sqrt(var)
    null = null | (~jnp.asarray(pop) & (cnt < 2))
    return var, null


# ---------------------------------------------------------------------------
# the grouping kernel


def _group_reduce_impl(key_ops: Tuple, key_raws: Tuple,
                       state_cols: Tuple, valid, num_keys: int,
                       num_states: int, kinds: Tuple, pallas: str = ""):
    """Sort-group-reduce one batch.

    key_ops: flattened (null_bit, u64) pairs for each group key
    key_raws: the raw key columns (carried through the sort)
    state_cols: per-row state columns (carried through the sort)
    Returns (group_key_raws, group_key_nullbits, reduced_states, out_valid).

    Raw implementation: the batched executor composes it under its own
    ``jit(vmap(...))`` wrappers (calling the instrumented binding
    inside a trace would run profiler host bookkeeping per lane); host
    callers use the jitted+instrumented ``_group_reduce`` below.
    """
    jit_stats.bump("sort_group_reduce")
    cap = valid.shape[0]
    # invalid lanes sort last: leading operand = ~valid
    operands = [(~valid).astype(jnp.uint8)] + list(key_ops) \
        + list(key_raws) + list(state_cols) + [valid]
    sorted_ops = jax.lax.sort(operands, num_keys=1 + 2 * num_keys,
                              is_stable=False)
    s_invalid = sorted_ops[0]
    s_keyops = sorted_ops[1:1 + 2 * num_keys]
    s_keyraws = sorted_ops[1 + 2 * num_keys:1 + 2 * num_keys + num_keys]
    s_states = sorted_ops[1 + 2 * num_keys + num_keys:-1]
    s_valid = sorted_ops[-1]

    # boundary: first row, or any key operand differs from previous row
    diff = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for op in s_keyops:
        prev = jnp.roll(op, 1)
        d = op != prev
        diff = diff | d.at[0].set(True)
    boundary = diff & s_valid
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    # invalid lanes -> dump segment
    gid = jnp.where(s_valid, gid, cap)

    # the hot scatter: Pallas kernel on TPU (lax segment ops elsewhere)
    # — see ops/pallas_kernels.py
    from .pallas_kernels import segment_reduce

    reduced = []
    for kind, col in zip(kinds, s_states):
        r = segment_reduce(col, gid, num_segments=cap + 1, kind=kind,
                           mode=pallas)
        reduced.append(r[:cap])

    # group keys: first sorted row of each segment
    first_idx = jax.ops.segment_min(
        jnp.arange(cap, dtype=jnp.int32), gid, num_segments=cap + 1)[:cap]
    ngroups = jnp.sum(boundary.astype(jnp.int32))
    out_valid = jnp.arange(cap, dtype=jnp.int32) < ngroups
    safe_idx = jnp.where(out_valid, first_idx, 0)
    out_key_raws = tuple(kr[safe_idx] for kr in s_keyraws)
    out_key_nulls = tuple(s_keyops[2 * i][safe_idx] > 0
                          for i in range(num_keys))
    return out_key_raws, out_key_nulls, tuple(reduced), out_valid


_group_reduce = instrument(
    "sort_group_reduce",
    partial(jax.jit, static_argnames=("num_states", "num_keys", "kinds",
                                      "pallas"))(_group_reduce_impl),
    static_argnames=("num_states", "num_keys", "kinds", "pallas"))


def _ranks_to_codes(state_cols: List, str_state: Sequence[bool],
                    inv_luts: Sequence) -> List:
    """String min/max value states: lexicographic RANK -> the
    representative CODE, driven by precomputed inverse LUT ARRAYS (the
    trace-safe mirror of ``HashAggregationOperator._states_rank_to_code``
    — the batched executor passes the LUTs as traced vmap operands).
    Dead/sentinel lanes clamp into range; count==0 nulls them
    downstream. LUTs keep their EXACT pool length so the clamp bound
    matches the host path bit-for-bit."""
    for k, is_str in enumerate(str_state):
        if is_str:
            inv = inv_luts[k]
            r = jnp.clip(state_cols[k], 0, inv.shape[0] - 1)
            state_cols[k] = inv[r].astype(jnp.int32)
    return state_cols


@partial(jax.jit, static_argnames=("buckets",))
def _bucket_reduction_stats(key_ops: Tuple, valid, group_rows, ngroups,
                            buckets: int):
    """(2, buckets) per-key-range observation of one page: row 0 =
    live rows per bucket, row 1 = groups (leader rows) per bucket.
    The bucket is a stable hash of the grouping operands, so a key's
    rows land in the same range bucket on every page.  Sums across
    axis 1 give the page totals, so this is the ONE host fetch the
    adaptive window pays per observed page."""
    jit_stats.bump("agg_bucket_stats")
    cap = valid.shape[0]
    b = (_mix_operands(key_ops, cap)
         % np.uint64(buckets)).astype(jnp.int32)
    rows = jnp.zeros((buckets + 1,), dtype=jnp.int32)
    rows = rows.at[jnp.where(valid, b, buckets)].add(1)
    leader = jnp.arange(cap, dtype=jnp.int32) < ngroups
    lb = b[group_rows]
    groups = jnp.zeros((buckets + 1,), dtype=jnp.int32)
    groups = groups.at[jnp.where(leader, lb, buckets)].add(1)
    return jnp.stack([rows[:buckets], groups[:buckets]])


_bucket_reduction_stats = instrument(
    "agg_bucket_stats", _bucket_reduction_stats,
    static_argnames=("buckets",))


@partial(jax.jit, static_argnames=("buckets",))
def _key_range_pass_mask(key_ops: Tuple, pass_buckets, buckets: int):
    """Per-row pass-through mask from the decided per-bucket verdicts
    (same stable hash as ``_bucket_reduction_stats``)."""
    jit_stats.bump("agg_key_range_mask")
    n = key_ops[0].shape[0]
    b = (_mix_operands(key_ops, n) % np.uint64(buckets)).astype(jnp.int32)
    return pass_buckets[b]


_key_range_pass_mask = instrument(
    "agg_key_range_mask", _key_range_pass_mask,
    static_argnames=("buckets",))


class HashAggregationOperator(Operator):
    """GROUP BY over device batches (see module docstring).

    step: 'single' (raw in, final out), 'partial' (raw in, states out),
    'final' (states in, final out) — mirroring the reference's
    PARTIAL/FINAL/SINGLE AggregationNode steps.
    """

    def __init__(self, input_types: Sequence[T.Type],
                 group_channels: Sequence[int],
                 aggregates: Sequence[AggCall], step: str = "single",
                 memory_context=None, hash_grouping: bool = True,
                 adaptive_partial: bool = True,
                 adaptive_ratio: float = ADAPTIVE_RATIO_THRESHOLD,
                 adaptive_min_rows: int = ADAPTIVE_MIN_ROWS,
                 adaptive_key_buckets: int = ADAPTIVE_KEY_BUCKETS,
                 adaptive_seed: Optional[dict] = None):
        assert step in ("single", "partial", "final")
        self.input_types = list(input_types)
        self.group_channels = list(group_channels)
        self.aggregates = list(aggregates)
        self.step = step
        self.hash_grouping = hash_grouping
        self.adaptive_partial = adaptive_partial and step == "partial"
        self.adaptive_ratio = adaptive_ratio
        self.adaptive_min_rows = adaptive_min_rows
        self.adaptive_key_buckets = max(1, int(adaptive_key_buckets)) \
            if group_channels else 1
        #: adaptive observation window (hash path only: the group count
        #: is already on host from the per-page stats fetch)
        self._adaptive_rows = 0
        self._adaptive_groups = 0
        self._adaptive_decided = False
        #: per-key-range (2, buckets) accumulated [rows, groups]
        self._bucket_stats = np.zeros((2, self.adaptive_key_buckets),
                                      dtype=np.int64)
        #: True once the partial step switched to pass-through
        self.passthrough = False
        #: per-bucket verdicts when the decision SPLIT the key space
        #: (device bool (buckets,)); None = no split decided
        self._pass_buckets = None
        self._pending: List[DevicePage] = []  # pass-through output queue
        #: pages grouped per path, for EXPLAIN/observability
        self.path_counts = {"hash": 0, "sort": 0, "passthrough": 0,
                            "range_split": 0}
        self._partials: List = []  # DevicePage | SpilledPage entries
        self._emitted = False
        self._done = False
        self._group_dicts: List = [None] * len(group_channels)
        self._kinds = tuple(k for a in self.aggregates
                            for (k, _) in _state_plan(a))
        # per-state: True for a string min/max VALUE state (reduced as a
        # rank, carried across pages as a code in the arg's pool)
        self._str_state: List[bool] = []
        for a in self.aggregates:
            is_str = a.arg_type is not None and a.arg_type.is_pooled
            for (k, _) in _state_plan(a):
                self._str_state.append(is_str and k in ("min", "max"))
        self._state_dicts: List = [None] * len(self._str_state)
        #: where the adaptive verdict came from: "observed" (this run's
        #: window decided) or "hbo" (seeded from recorded history)
        self._adaptive_source = "observed"
        if adaptive_seed and self.adaptive_partial:
            self._apply_adaptive_seed(adaptive_seed)
        self._ctx = memory_context
        if self._ctx is not None:
            self._ctx.set_revoke_callback(self._revoke)

    def _apply_adaptive_seed(self, seed: dict):
        """Pre-decide the adaptive window from a recorded verdict
        (history-based statistics): pass-through/aggregate apply
        directly; a range-split verdict applies only when the bucket
        count matches the recording (a re-tuned bucket knob re-runs
        the observation window instead of misapplying a stale mask)."""
        verdict = seed.get("verdict")
        if verdict == "passthrough":
            self.passthrough = True
        elif verdict == "range-split":
            mask = seed.get("pass_buckets")
            if not mask or len(mask) != self.adaptive_key_buckets:
                return
            self._pass_buckets = jnp.asarray(
                np.asarray(mask, dtype=bool))
        elif verdict != "aggregate":
            return
        self._adaptive_decided = True
        self._adaptive_source = "hbo"

    # output layout: group key columns, then state/final columns per agg
    @property
    def output_types(self) -> List[T.Type]:
        if self.step == "partial":
            return self._intermediate_types()
        keys = [self.input_types[c] for c in self.group_channels]
        return keys + [a.output_type for a in self.aggregates]

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, page: DevicePage):
        # capture group-key dictionaries (assumed stable pools per column)
        for i, c in enumerate(self.group_channels):
            d = page.dictionaries[c]
            if d is not None:
                prev = self._group_dicts[i]
                if prev is not None and prev is not d:
                    raise TypeError_(
                        "group key dictionaries changed across pages; "
                        "exchange must unify pools")
                self._group_dicts[i] = d
        # string min/max state pools: same stability contract
        intermediate = self.step == "final"
        nkeys = len(self.group_channels)
        k = 0
        for a in self.aggregates:
            for _ in _state_plan(a):
                if self._str_state[k]:
                    ch = (nkeys + k) if intermediate else a.arg_channel
                    d = page.dictionaries[ch]
                    if d is not None:
                        prev = self._state_dicts[k]
                        if prev is not None and prev is not d:
                            raise TypeError_(
                                "aggregate arg dictionaries changed "
                                "across pages; exchange must unify pools")
                        self._state_dicts[k] = d
                k += 1
        if self.passthrough:
            # adaptive partial aggregation tripped: emit the page in the
            # intermediate keys+states layout without grouping at all
            self.path_counts["passthrough"] += 1
            self._pending.append(self._passthrough_page(page))
            return
        key_operands = None
        if self._pass_buckets is not None:
            # per-key-range split: cold (mostly-unique) ranges pass
            # through ungrouped, hot ranges keep aggregating — the
            # final step re-groups both, so results are unchanged.
            # The grouping operands feed both the mask and the
            # aggregation below (they don't depend on validity), so
            # compute them once.
            self.path_counts["range_split"] += 1
            key_types = [self.input_types[c] for c in self.group_channels]
            key_operands = self._grouping_operands(
                page, self.group_channels, key_types)
            mask = _key_range_pass_mask(tuple(key_operands[0]),
                                        self._pass_buckets,
                                        self.adaptive_key_buckets)
            self._pending.append(self._passthrough_page(
                _masked_page(page, page.valid & mask)))
            page = _masked_page(page, page.valid & ~mask)
        partial = self._aggregate_page(page, intermediate=intermediate,
                                       key_operands=key_operands)
        if self._ctx is None:
            self._partials.append(partial)
            return
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._partials, partial)

    def _revoke(self) -> int:
        """Park device partials in host RAM (called by the pool under
        this context's lock; reference: Operator.startMemoryRevoke),
        overflowing to the disk tier when the host ledger is full."""
        from ..exec.memory import spill_pages

        return spill_pages(self._partials, self._ctx.pool,
                           self._ctx.lock)

    def _aggregate_page(self, page: DevicePage, intermediate: bool,
                        key_operands=None) -> DevicePage:
        """intermediate=False: page is raw input rows (layout:
        self.input_types, keys at self.group_channels).
        intermediate=True: page is partial-agg output (layout:
        _intermediate_types — keys at channels [0..nkeys), then states).
        ``key_operands``: precomputed (key_ops, key_raws) from the
        range-split path (raw layout only) — skips recomputing them."""
        nkeys = len(self.group_channels)
        if intermediate:
            key_channels = list(range(nkeys))
            key_types = self._intermediate_types()[:nkeys]
        else:
            key_channels = self.group_channels
            key_types = [self.input_types[c] for c in self.group_channels]

        if key_operands is not None:
            key_ops, key_raws = key_operands
        else:
            key_ops, key_raws = self._grouping_operands(
                page, key_channels, key_types)

        if intermediate:
            # states laid out after the keys
            state_cols: List = []
            idx = nkeys
            for a in self.aggregates:
                plan = _state_plan(a)
                raw_states = [page.cols[idx + j] for j in range(len(plan))]
                raw_dicts = [page.dictionaries[idx + j]
                             for j in range(len(plan))]
                idx += len(plan)
                state_cols.extend(_merge_states(a, raw_states, page.valid,
                                                raw_dicts))
        else:
            state_cols = []
            for a in self.aggregates:
                state_cols.extend(_init_states(a, page.cols, page.nulls,
                                               page.valid,
                                               page.dictionaries))

        from .pallas_kernels import pallas_mode

        mode = pallas_mode()
        result = None
        if self.hash_grouping and hashable_key_types(key_types):
            result = self._hash_group_page(page, key_ops, key_raws,
                                           key_channels, state_cols, mode,
                                           observe=not intermediate)
        if result is None:
            self.path_counts["sort"] += 1
            result = _group_reduce(
                tuple(key_ops), tuple(key_raws), tuple(state_cols),
                page.valid, num_keys=len(self.group_channels),
                num_states=len(state_cols), kinds=self._kinds,
                pallas=mode)
        out_keys, out_key_nulls, reduced, out_valid = result

        # string min/max: reduced RANK -> representative CODE in the
        # captured pool (dead/sentinel lanes clamp; count==0 nulls them)
        reduced = self._states_rank_to_code(list(reduced))

        cols, nulls = list(out_keys), [jnp.asarray(n) for n in out_key_nulls]
        for r in reduced:
            cols.append(r)
            nulls.append(jnp.zeros_like(out_valid))
        types = self._intermediate_types()
        dicts = list(self._group_dicts) + [
            self._state_dicts[k] if self._str_state[k] else None
            for k in range(len(self._str_state))]
        return DevicePage(types, cols, nulls, out_valid, dicts)

    def _grouping_operands(self, page: DevicePage, key_channels,
                           key_types):
        """(key_ops, key_raws) grouping operands of one page — pooled
        keys group by lexicographic RANK, not raw code: aligned
        (derived) pools may hold one value under several codes.  The
        representative raw code still rides along for output.  Also
        the stable per-row key identity the key-range bucketing
        hashes, so observation and split agree on every key's
        bucket."""
        key_ops: List = []
        key_raws: List = []
        for c, t in zip(key_channels, key_types):
            col = page.cols[c]
            if getattr(t, "is_pooled", False):
                rank_lut, _ = _rank_and_inverse(page.dictionaries[c])
                ops = group_operands(jnp.asarray(rank_lut)[col],
                                     page.nulls[c], T.BIGINT)
            else:
                ops = group_operands(col, page.nulls[c], t)
            key_ops.extend(ops)
            key_raws.append(col)
        return key_ops, key_raws

    def _hash_group_page(self, page: DevicePage, key_ops, key_raws,
                         key_channels, state_cols, mode: str,
                         observe: bool):
        """Hash-path grouping of one page; None => the caller falls
        back to the sort oracle (probe-budget overflow)."""
        exact = self.step != "partial"
        gid, group_rows, ngroups, overflow = hash_group_ids(
            tuple(key_ops), page.valid, exact=exact)
        key_nulls = tuple(page.nulls[c] for c in key_channels)
        # dispatch the reduce SPECULATIVELY, before the overflow sync:
        # the device chews on it while the host waits on the scalar, and
        # the (astronomically rare) overflow page just wastes one launch
        result = hash_segment_reduce(gid, group_rows, ngroups,
                                     tuple(key_raws), key_nulls,
                                     tuple(state_cols), self._kinds,
                                     pallas=mode)
        if exact:
            if bool(overflow):
                return None
        elif observe and self.adaptive_partial \
                and not self._adaptive_decided:
            self._observe_reduction(key_ops, page.valid, group_rows,
                                    ngroups)
        self.path_counts["hash"] += 1
        return result

    def _states_rank_to_code(self, state_cols: List) -> List:
        """String min/max value states: lexicographic RANK -> the
        representative CODE in the captured pool (the intermediate-page
        wire contract). Dead/sentinel lanes clamp into range; their
        count state of 0 nulls them downstream."""
        for k, is_str in enumerate(self._str_state):
            if is_str:
                _, inv = _rank_and_inverse(self._state_dicts[k])
                r = jnp.clip(state_cols[k], 0, len(inv) - 1)
                state_cols[k] = jnp.asarray(inv)[r].astype(jnp.int32)
        return state_cols

    def _observe_reduction(self, key_ops, valid, group_rows, ngroups):
        """Accumulate the groups/rows ratio PER KEY-RANGE BUCKET; once
        enough rows are observed, flip pass-through per bucket: all
        buckets non-reducing -> whole-stream pass-through (the classic
        switch), a mix -> range split (reference: adaptive partial
        aggregation; "Partial Partial Aggregates", PAPERS.md)."""
        stats = np.asarray(_bucket_reduction_stats(
            tuple(key_ops), valid, group_rows, ngroups,
            self.adaptive_key_buckets)).astype(np.int64)
        self._bucket_stats += stats
        self._adaptive_rows += int(stats[0].sum())
        self._adaptive_groups += int(stats[1].sum())
        if self._adaptive_rows < self.adaptive_min_rows:
            return
        self._adaptive_decided = True
        rows_b, groups_b = self._bucket_stats
        b = self.adaptive_key_buckets
        # a bucket flips only with its share of the evidence: a range
        # barely seen keeps aggregating (the safe default)
        evid = rows_b >= max(1, self.adaptive_min_rows // (2 * b))
        ratios = groups_b / np.maximum(rows_b, 1)
        pass_b = evid & (ratios > self.adaptive_ratio)
        if pass_b.all():
            self.passthrough = True
        elif pass_b.any():
            self._pass_buckets = jnp.asarray(pass_b)

    def _passthrough_page(self, page: DevicePage) -> DevicePage:
        """Raw input page -> intermediate keys+states layout, ungrouped
        (every row its own group; the final step re-groups, so results
        are unchanged — partial aggregation is only a reduction)."""
        state_cols: List = []
        for a in self.aggregates:
            state_cols.extend(_init_states(a, page.cols, page.nulls,
                                           page.valid, page.dictionaries))
        # string min/max states travel as CODES (same wire contract as
        # the reduced path): map rank values back through the pool
        state_cols = self._states_rank_to_code(state_cols)
        cols = [page.cols[c] for c in self.group_channels]
        nulls = [page.nulls[c] for c in self.group_channels]
        no_nulls = jnp.zeros(page.capacity, dtype=bool)
        for s in state_cols:
            cols.append(s)
            nulls.append(no_nulls)
        dicts = list(self._group_dicts) + self._state_dict_tail()
        return DevicePage(self._intermediate_types(), cols, nulls,
                          page.valid, dicts)

    def _intermediate_types(self) -> List[T.Type]:
        keys = [self.input_types[c] for c in self.group_channels]
        states: List[T.Type] = []
        for a in self.aggregates:
            states.extend(intermediate_state_types(a.function, a.arg_type))
        return keys + states

    def get_output(self) -> Optional[DevicePage]:
        if self._pending:
            return self._pending.pop(0)
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        merged = self._merge_partials()
        self._partials = []
        if self.step in ("single", "final"):
            merged = self._finalize(merged)
        if self._ctx is not None:
            self._ctx.close()  # output page is in flight, not retained
        return merged

    def _merge_partials(self) -> DevicePage:
        types = self._intermediate_types()
        nkeys = len(self.group_channels)
        # a task that saw no input never captured key dictionaries;
        # string outputs still need (empty) pools
        from ..block import Dictionary

        for i in range(nkeys):
            if self._group_dicts[i] is None and types[i].is_pooled:
                self._group_dicts[i] = Dictionary()
        if self._ctx is not None:
            # once merging starts the partials stop being revocable; if
            # the single-chunk transient (concat + result ~= 2x total)
            # wouldn't fit, prepare_finish parks everything on host and
            # the chunked merge below brings it back under budget
            from ..exec.memory import prepare_finish

            prepare_finish(self._ctx, self._partials)
        if not self._partials:
            # no input: zero groups — except global aggregation, which
            # emits exactly one group of empty-input states (count=0,
            # sum=NULL), per SQL semantics
            cap = 16
            cols = [jnp.zeros(cap, dtype=t.storage) for t in types]
            nulls = [jnp.zeros(cap, dtype=bool) for _ in types]
            valid = jnp.zeros(cap, dtype=bool)
            if nkeys == 0:
                valid = valid.at[0].set(True)
            dicts = list(self._group_dicts) + self._state_dict_tail()
            return DevicePage(types, cols, nulls, valid, dicts)
        from ..exec.memory import SpilledPage, device_page_bytes

        parts = self._partials
        if len(parts) == 1 and self.step != "partial" \
                and not isinstance(parts[0], SpilledPage):
            return parts[0]
        # merge in budget-bounded chunks: each round touches at most
        # ~budget bytes of HBM (uploads + concat), so spilled state
        # re-enters the device incrementally (reference analog:
        # MergingHashAggregationBuilder merging sorted spill runs)
        budget = None
        if self._ctx is not None:
            # each chunk's transient is 2x its bytes (concat + result):
            # cap chunks at max/4 so the transient stays under max/2
            budget = max(self._ctx.pool.max_bytes // 4, 1 << 16)
        while True:
            chunks: List[List] = []
            cur: List = []
            cur_bytes = 0
            for p in parts:
                nb = device_page_bytes(p)
                if cur and len(cur) >= 2 and budget is not None \
                        and cur_bytes + nb > budget:
                    chunks.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(p)
                cur_bytes += nb
            chunks.append(cur)
            if len(chunks) == 1:
                return self._merge_chunk(chunks[0])
            parts = [self._merge_chunk(c) for c in chunks]

    def _merge_chunk(self, chunk: List) -> DevicePage:
        """Concatenate one chunk of partials (uploading spilled ones) and
        re-group with merge semantics."""
        from ..exec.memory import SpilledPage, device_page_bytes

        types = self._intermediate_types()
        nkeys = len(self.group_channels)
        total = sum(device_page_bytes(p) for p in chunk)
        transient = 0
        if self._ctx is not None:
            # uploads (spilled entries re-entering HBM) + concat buffer +
            # result (bounded by the concat)
            uploads = sum(device_page_bytes(p) for p in chunk
                          if isinstance(p, SpilledPage))
            transient = uploads + 2 * total
            self._ctx.reserve(transient, revocable=False)
        dev = [p.to_device() if isinstance(p, SpilledPage) else p
               for p in chunk]
        if len(dev) == 1 and self.step != "partial" and \
                isinstance(chunk[0], SpilledPage):
            out = dev[0]
        else:
            cap = padded_size(sum(p.capacity for p in dev))
            cols, nulls = [], []
            for i in range(len(types)):
                c = jnp.concatenate([p.cols[i] for p in dev])
                n = jnp.concatenate([p.nulls[i] for p in dev])
                cols.append(_pad_to(c, cap))
                nulls.append(_pad_to(n, cap))
            valid = _pad_to(jnp.concatenate([p.valid for p in dev]), cap)
            page = DevicePage(
                types, cols, nulls, valid,
                list(self._group_dicts) + self._state_dict_tail())
            out = self._aggregate_page(page, intermediate=True)
        if self._ctx is not None:
            # release the transient + the chunk inputs' reservations,
            # keep the merged result reserved
            freed = transient + sum(device_page_bytes(p) for p in chunk
                                    if not isinstance(p, SpilledPage))
            self._ctx.free(freed)
            self._ctx.reserve(device_page_bytes(out), revocable=False)
        return out

    def _finalize(self, merged: DevicePage) -> DevicePage:
        nkeys = len(self.group_channels)
        if nkeys == 0:
            # global aggregation always emits exactly one row, even over
            # zero input rows (lane 0 then holds empty-input states)
            one = jnp.arange(merged.capacity) == 0
            merged = DevicePage(merged.types, merged.cols, merged.nulls,
                                merged.valid | one, merged.dictionaries)
        out_cols = list(merged.cols[:nkeys])
        out_nulls = list(merged.nulls[:nkeys])
        idx = nkeys
        for a in self.aggregates:
            plan = _state_plan(a)
            states = [merged.cols[idx + j] for j in range(len(plan))]
            idx += len(plan)
            raw, null = _final_project(a, states)
            out_cols.append(raw.astype(a.output_type.storage))
            out_nulls.append(null | ~merged.valid)
        types = self.output_types
        agg_dicts = []
        k = 0
        for a in self.aggregates:
            plan = _state_plan(a)
            agg_dicts.append(self._state_dicts[k]
                             if self._str_state[k] else None)
            k += len(plan)
        dicts = list(self._group_dicts) + agg_dicts
        return DevicePage(types, out_cols, out_nulls, merged.valid, dicts)

    def _state_dict_tail(self) -> List:
        """Dictionaries for the state columns of an intermediate-layout
        page (string min/max value states keep their pool)."""
        return [self._state_dicts[k] if self._str_state[k] else None
                for k in range(len(self._str_state))]

    def metrics(self) -> dict:
        """Grouping-path observability for EXPLAIN ANALYZE: pages per
        path and, once the adaptive window decided, what it decided
        (whole-stream pass-through vs the per-key-range split)."""
        out = {"grouping_paths": {k: v for k, v in
                                  self.path_counts.items() if v}}
        seeded = " (seeded by hbo)" \
            if self._adaptive_source == "hbo" else ""
        if self.passthrough:
            out["adaptive"] = "passthrough" + seeded
        elif self._pass_buckets is not None:
            out["adaptive"] = (
                f"range-split "
                f"{int(np.asarray(self._pass_buckets).sum())}/"
                f"{self.adaptive_key_buckets} buckets pass through"
                + seeded)
        if self.adaptive_partial and self._adaptive_decided:
            # the decided verdict, machine-readable: history-based
            # statistics store it and seed the next run's operator
            if self.passthrough:
                verdict: dict = {"verdict": "passthrough"}
            elif self._pass_buckets is not None:
                verdict = {"verdict": "range-split",
                           "pass_buckets": [
                               int(b) for b in
                               np.asarray(self._pass_buckets)]}
            else:
                verdict = {"verdict": "aggregate"}
            out["adaptive_verdict"] = verdict
        return out

    def is_finished(self) -> bool:
        return self._done


def _masked_page(page: DevicePage, valid) -> DevicePage:
    """The same page under a different validity mask (columns shared)."""
    return DevicePage(page.types, page.cols, page.nulls, valid,
                      page.dictionaries)


def _pad_to(arr, cap: int):
    n = arr.shape[0]
    if n == cap:
        return arr
    pad = jnp.zeros((cap - n,), dtype=arr.dtype)
    return jnp.concatenate([arr, pad])
