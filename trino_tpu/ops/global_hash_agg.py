"""Global-hash device aggregation: ONE table across the mesh.

Reference analog: "Global Hash Tables Strike Back!" (PAPERS.md,
arXiv 2505.04153) — a single shared hash table updated by every thread
beats partition-then-aggregate for GROUP BY across a wide NDV range.
On a TPU mesh the translation is: instead of the exchange+merge-final
shape (all_to_all of partial groups, then per-device re-grouping —
``parallel/mesh_query.q1_exchange_final_fn``), every device owns a
REPLICATED open-addressing table and updates it with collective
scatter-adds: local scatter into the table, one ``psum``/``pmin``/
``pmax`` per state column to merge the replicas.  For low-NDV grouping
the table is tiny, so the collectives move O(table) bytes instead of
O(partial groups) rows — and no re-grouping kernel runs at all.

Insert protocol (the claim loop — ``ops/hashtable.py``'s vectorized
insert-or-lookup lifted to the mesh):

- group keys pack injectively into one uint64 (``pack_keys``; the cost
  model gates on packability), hashed by the same splitmix64 finalizer
  the local GroupByHash uses;
- each probe round, unresolved rows propose slot ``(h + r) & mask``;
  the candidate key per slot is the scatter-MIN of proposers, globally
  agreed by ``lax.pmin`` over the mesh, and lands only in still-empty
  slots — every device applies the identical update, so the replicas
  never diverge;
- rows whose key owns their slot are resolved; colliders advance.
  Rows unresolved after the (static) round budget are reported so the
  caller can fall back to the exchange path — exactness first.

Single-device mode (``axis_name=None``) drops the collectives and is
the oracle the tests compare against the sort-based reduce.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit_stats
from .hashtable import splitmix64

#: empty-slot sentinel: packed keys reserve it by construction
#: (``pack_keys`` biases every operand by +1, so all-ones cannot occur
#: within the gated bit budget)
EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)

#: linear-probe round budget (mirrors ``hashtable.PROBE_ROUNDS``): with
#: load factor <= 0.5 an unresolved row after 32 probes is
#: astronomically rare; the caller falls back on overflow regardless
PROBE_ROUNDS = 32


def pack_keys(cols: Sequence, nulls: Sequence, widths: Tuple[int, ...]):
    """Injective uint64 packing of non-negative key operands: each
    column takes ``width`` bits holding value+1 (0 = NULL), so distinct
    key tuples — including NULLs — pack to distinct u64s and the
    all-ones EMPTY sentinel is unreachable.  Traced helper: call inside
    the jit'd program; the caller gates that values fit the widths."""
    acc = jnp.zeros(cols[0].shape, dtype=jnp.uint64)
    for c, nl, w in zip(cols, nulls, widths):
        v = c.astype(jnp.int64).view(jnp.uint64) + np.uint64(1)
        if nl is not None:
            v = jnp.where(nl, np.uint64(0), v)
        acc = (acc << np.uint64(w)) | v
    return acc


def unpack_keys(packed, widths: Tuple[int, ...]):
    """Inverse of ``pack_keys``: [(value_i64, null_bool)] per column."""
    out = []
    shift = 0
    for w in reversed(widths):
        v = (packed >> np.uint64(shift)) & np.uint64((1 << w) - 1)
        null = v == 0
        out.append(((v - np.uint64(1)).astype(jnp.int64)
                    & np.int64((1 << w) - 1), null))
        shift += w
    return list(reversed(out))


@partial(jax.jit, static_argnames=("table_size", "rounds", "axis_name"))
def global_hash_insert(packed, valid, table_size: int,
                       rounds: int = PROBE_ROUNDS,
                       axis_name: Optional[str] = None):
    """Claim-loop insert into the replicated global table.

    Returns (table, slot_of, resolved, unresolved): ``table`` holds the
    owning packed key per slot (EMPTY = free) — identical on every
    device; ``slot_of``/``resolved`` are this device's per-row
    assignments; ``unresolved`` is the GLOBAL count of live rows that
    exhausted the probe budget (nonzero => caller must fall back)."""
    jit_stats.bump("global_hash_insert")
    mask = np.uint64(table_size - 1)
    h = splitmix64(packed)
    slot0 = (h & mask).astype(jnp.int32)

    def probe_round(r, carry):
        table, resolved, slot_of = carry
        active = ~resolved
        slot = jnp.where(active, (slot0 + r) & jnp.int32(table_size - 1),
                         table_size)
        # candidate owner per slot: scatter-min locally (masked lanes
        # land in the dummy slot), pmin globally — all devices install
        # the identical winner into still-empty slots
        claim = jnp.full((table_size + 1,), EMPTY, dtype=jnp.uint64)
        claim = claim.at[slot].min(packed)
        claim = claim[:table_size]
        if axis_name is not None:
            claim = jax.lax.pmin(claim, axis_name)
        table = jnp.where(table == EMPTY, claim, table)
        owner = table[jnp.clip(slot, 0, table_size - 1)]
        won = active & (owner == packed)
        slot_of = jnp.where(won, slot, slot_of)
        return table, resolved | won, slot_of

    table0 = jnp.full((table_size,), EMPTY, dtype=jnp.uint64)
    table, resolved, slot_of = jax.lax.fori_loop(
        0, rounds, probe_round,
        (table0, ~valid, jnp.zeros_like(slot0)))
    unresolved = jnp.sum((valid & ~resolved).astype(jnp.int32))
    if axis_name is not None:
        unresolved = jax.lax.psum(unresolved, axis_name)
    return table, slot_of, resolved, unresolved


@partial(jax.jit, static_argnames=("table_size", "kinds", "axis_name"))
def global_hash_reduce(slot_of, resolved, valid, state_cols: Tuple,
                       kinds: Tuple, table_size: int,
                       axis_name: Optional[str] = None):
    """Collective scatter-reduce of per-row states into the global
    table: local scatter by assigned slot, then one psum/pmin/pmax per
    state column merges the replicas.  States arrive sentinel-
    neutralized (``aggregation._merge_states``/``_init_states``), so
    empty slots hold each kind's neutral element and ``_final_project``
    nulls them via the count state."""
    jit_stats.bump("global_hash_reduce")
    idx = jnp.where(resolved & valid, slot_of, table_size)
    out = []
    for kind, col in zip(kinds, state_cols):
        is_float = jnp.issubdtype(col.dtype, jnp.floating)
        if kind == "sum":
            acc = jnp.zeros((table_size + 1,), dtype=col.dtype)
            acc = acc.at[idx].add(col)[:table_size]
            if axis_name is not None:
                acc = jax.lax.psum(acc, axis_name)
        elif kind == "min":
            sent = jnp.inf if is_float else jnp.iinfo(col.dtype).max
            acc = jnp.full((table_size + 1,), sent, dtype=col.dtype)
            acc = acc.at[idx].min(col)[:table_size]
            if axis_name is not None:
                acc = jax.lax.pmin(acc, axis_name)
        else:
            sent = -jnp.inf if is_float else jnp.iinfo(col.dtype).min
            acc = jnp.full((table_size + 1,), sent, dtype=col.dtype)
            acc = acc.at[idx].max(col)[:table_size]
            if axis_name is not None:
                acc = jax.lax.pmax(acc, axis_name)
        out.append(acc)
    return tuple(out)
