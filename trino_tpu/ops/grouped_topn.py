"""Grouped top-N: per-group truncation under a ranking function.

Reference analog: ``operator/GroupedTopNBuilder.java`` /
``TopNRankingOperator.java`` — per-group heaps keeping the top
``max_rank`` rows while input streams through, so a ranking query never
materializes whole window partitions.

TPU-first redesign: no heaps. Buffered rows sort ONCE by
(partition-ops, order-ops) with XLA's lexicographic sort, group ranks
fall out of run-boundary prefix ops (the window kernel's trick), and a
second two-key sort compacts survivors to the front. The operator
flushes whenever the buffer crosses a threshold, so resident rows stay
O(groups * max_rank + flush window) instead of O(input) — the heap's
memory bound, achieved with two sorts per flush instead of per-row
pointer chasing. The partial step runs pre-exchange with the same
kernel: a row whose LOCAL rank exceeds max_rank can never reach global
rank <= max_rank (dropping rows only lowers ranks), so at most
groups*max_rank rows per task cross the wire.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, padded_size
from ..telemetry.profiler import instrument
from .operator import Operator
from .sort import _concat_pages
from .sortkeys import SortKey, group_operands, sort_operands


@partial(jax.jit, static_argnames=("n_part", "n_order", "ranking",
                                   "max_rank", "ncols"))
def _topn_kernel(part_ops, order_ops, cols, nulls, valid,
                 n_part: int, n_order: int, ranking: str,
                 max_rank: int, ncols: int):
    from .. import jit_stats

    jit_stats.bump("grouped_topn_kernel")
    n = valid.shape[0]
    operands = [(~valid).astype(jnp.uint8)] + list(part_ops) \
        + list(order_ops) + list(cols) + list(nulls) + [valid]
    s = jax.lax.sort(operands, num_keys=1 + n_part + n_order,
                     is_stable=False)
    s_part = s[1:1 + n_part]
    s_order = s[1 + n_part:1 + n_part + n_order]
    base = 1 + n_part + n_order
    s_cols = list(s[base:base + ncols])
    s_nulls = list(s[base + ncols:base + 2 * ncols])
    s_valid = s[-1]

    idx = jnp.arange(n, dtype=jnp.int64)

    def new_run(ops):
        flag = jnp.zeros(n, dtype=bool).at[0].set(True)
        for o in ops:
            flag = flag | jnp.concatenate(
                [jnp.ones(1, dtype=bool), o[1:] != o[:-1]])
        return flag

    # validity participates: the valid->padding transition starts a
    # (dead) partition, so ranks never straddle padding lanes
    pstart = new_run(list(s_part) + [s_valid])
    pstart_idx = jax.lax.cummax(jnp.where(pstart, idx, 0))
    if ranking == "rank" and n_order:
        rstart = pstart | new_run(list(s_order))
        rstart_idx = jax.lax.cummax(jnp.where(rstart, idx, 0))
        rk = rstart_idx - pstart_idx + 1
    else:
        rk = idx - pstart_idx + 1
    keep = s_valid & (rk <= max_rank)

    # compact survivors to the front, preserving the sorted order
    ops2 = [(~keep).astype(jnp.uint8), idx] + s_cols + s_nulls \
        + [keep, rk]
    c = jax.lax.sort(ops2, num_keys=2, is_stable=False)
    out_cols = tuple(c[2:2 + ncols])
    out_nulls = tuple(c[2 + ncols:2 + 2 * ncols])
    return out_cols, out_nulls, c[-2], c[-1], jnp.sum(keep)


_topn_kernel = instrument(
    "grouped_topn_kernel", _topn_kernel,
    static_argnames=("n_part", "n_order", "ranking", "max_rank",
                     "ncols"))


class GroupedTopNOperator(Operator):
    """Keeps at most ``max_rank`` rows per partition-key group under
    the ordering; appends the rank column unless ``step='partial'``."""

    FLUSH_ROWS = 1 << 16

    def __init__(self, input_types: Sequence[T.Type],
                 partition_channels: Sequence[int],
                 sort_keys: Sequence[SortKey], ranking: str,
                 max_rank: int, step: str = "single"):
        assert ranking in ("row_number", "rank")
        assert step in ("single", "partial", "final")
        self.input_types = list(input_types)
        self.partition_channels = list(partition_channels)
        self.sort_keys = list(sort_keys)
        self.ranking = ranking
        self.max_rank = max_rank
        self.step = step
        self._pages: List[DevicePage] = []
        self._buffered_rows = 0
        self._out: Optional[DevicePage] = None
        self._done = False

    @property
    def output_types(self) -> List[T.Type]:
        if self.step == "partial":
            return list(self.input_types)
        return self.input_types + [T.BIGINT]

    def add_input(self, page: DevicePage):
        self._pages.append(page)
        self._buffered_rows += page.capacity
        if self._buffered_rows >= self.FLUSH_ROWS:
            self._truncate_buffer()

    def _build_ops(self, page: DevicePage):
        part_ops: List = []
        for ch in self.partition_channels:
            t = page.types[ch]
            if getattr(t, "is_pooled", False):
                from .aggregation import _rank_and_inverse

                rank_lut, _ = _rank_and_inverse(page.dictionaries[ch])
                part_ops.extend(group_operands(
                    jnp.asarray(rank_lut)[page.cols[ch]],
                    page.nulls[ch], T.BIGINT))
            else:
                part_ops.extend(group_operands(page.cols[ch],
                                               page.nulls[ch], t))
        order_ops: List = []
        for k in self.sort_keys:
            order_ops.extend(sort_operands(
                page.cols[k.channel], page.nulls[k.channel],
                page.types[k.channel], page.dictionaries[k.channel],
                ascending=k.ascending, nulls_last=k.nulls_last))
        return part_ops, order_ops

    def _run_kernel(self, page: DevicePage):
        part_ops, order_ops = self._build_ops(page)
        cols, nulls, valid, rank, count = _topn_kernel(
            tuple(part_ops), tuple(order_ops), tuple(page.cols),
            tuple(page.nulls), page.valid,
            n_part=len(part_ops), n_order=len(order_ops),
            ranking=self.ranking, max_rank=self.max_rank,
            ncols=len(page.cols))
        return cols, nulls, valid, rank, int(np.asarray(count))

    def _truncate_buffer(self):
        """Mid-stream flush: replace the buffer with its per-group
        top-N (survivors compact into a right-sized page)."""
        if not self._pages:
            return
        cap = padded_size(sum(p.capacity for p in self._pages))
        page = _concat_pages(self._pages, cap)
        cols, nulls, valid, _rank, count = self._run_kernel(page)
        k = padded_size(max(count, 16))
        self._pages = [DevicePage(
            list(page.types), [c[:k] for c in cols],
            [x[:k] for x in nulls], valid[:k], list(page.dictionaries))]
        self._buffered_rows = k

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._done:
            return None
        self._done = True
        if not self._pages:
            return None
        cap = padded_size(sum(p.capacity for p in self._pages))
        page = _concat_pages(self._pages, cap)
        self._pages = []
        cols, nulls, valid, rank, count = self._run_kernel(page)
        k = padded_size(max(count, 16))
        out_cols = [c[:k] for c in cols]
        out_nulls = [x[:k] for x in nulls]
        out_valid = valid[:k]
        out_dicts = list(page.dictionaries)
        types_ = list(page.types)
        if self.step != "partial":
            out_cols.append(rank[:k].astype(jnp.int64))
            out_nulls.append(jnp.zeros((k,), dtype=bool))
            out_dicts.append(None)
            types_.append(T.BIGINT)
        return DevicePage(types_, out_cols, out_nulls, out_valid,
                          out_dicts)

    def is_finished(self) -> bool:
        return self._done
