"""Vectorized open-addressing GroupByHash primitive.

Reference analog: ``operator/MultiChannelGroupByHash.java`` (the
putIfAbsent loop assigning dense group ids) — redesigned as a fully
vectorized page-at-a-time kernel instead of a row-at-a-time loop, the
hash-based plan shape of "Global Hash Tables Strike Back!" (PAPERS.md).

Design:
  - keys arrive as the engine's normalized grouping operands
    (``ops/sortkeys.group_operands``: a (tag_u8, u64) pair per key
    column) — all integer lanes, so one splitmix64 mix per operand
    yields the bucket hash;
  - the table is ``2 * capacity`` slots (power of two, load factor
    <= 0.5) storing the REPRESENTATIVE ROW INDEX of the group that owns
    each slot (``capacity`` = empty sentinel), plus one dummy slot that
    absorbs masked scatters;
  - insert-or-lookup runs a bounded number of linear-probe ROUNDS, each
    round fully vectorized over the page: every unresolved row probes
    ``(h + round) & mask``, empty slots are claimed by scatter-min on
    row index, claimants re-gather the installed owner and compare full
    keys by gathering the owner row's operands — equal keys join the
    owner's group, colliders advance to the next probe;
  - dense group ids are a cumsum over "row owns itself" leaders, so gid
    order is first-occurrence order (matching the reference's
    putIfAbsent numbering), with no sort anywhere.

Rows still unresolved after the probe budget either overflow (exact
mode: the caller falls back to the sort-based oracle) or become
singleton groups (partial aggregation tolerates duplicate groups — the
final step re-groups, per "Partial Partial Aggregates", PAPERS.md).

Float keys are NOT hashed here: the TPU x64 rewriter cannot bitcast
f64<->u64 (see ops/sortkeys.py), so float grouping keys keep the
sort-based path. ``hashable_key_types`` is the gate.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit_stats
from .. import types as T
from ..telemetry.profiler import instrument

#: linear-probe rounds per page: with load factor <= 0.5 and a 64-bit
#: mixed hash, an unresolved row after 32 probes is astronomically rare
#: for non-adversarial input; adversarial input falls back / singles out.
PROBE_ROUNDS = 32

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_M3 = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio increment


def hashable_key_types(key_types: Sequence[T.Type]) -> bool:
    """True when every grouping key can take the hash path (integer
    operands only — floats keep the sort path, see module docstring)."""
    return all(t not in (T.DOUBLE, T.REAL) for t in key_types)


def splitmix64(x):
    """The splitmix64 finalizer over uint64 lanes (public-domain
    constant set; also the reference's XxHash-style mixing role)."""
    x = (x + _M3).astype(jnp.uint64)
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def _mix_operands(key_ops: Tuple, n: int):
    """Combine the flattened (tag, key) operand columns into one 64-bit
    hash per row. Zero key columns (global aggregation) hash to 0."""
    h = jnp.zeros((n,), dtype=jnp.uint64)
    for op in key_ops:
        h = splitmix64(h ^ op.astype(jnp.uint64))
    return h


def _hash_group_ids_impl(key_ops: Tuple, valid,
                         rounds: int = PROBE_ROUNDS,
                         exact: bool = True):
    """Vectorized insert-or-lookup over one page.

    Raw (un-jitted, un-instrumented) implementation: the batched
    executor composes it under its own ``jit(vmap(...))`` wrappers —
    calling the instrumented public name inside a trace would run the
    profiler's host bookkeeping per vmap lane. Host callers use the
    ``hash_group_ids`` binding below.

    key_ops: flattened (tag_u8, u64) grouping operands (integer dtypes).
    valid:   bool lane mask; invalid lanes get the dump gid ``capacity``.

    Returns (gid, group_rows, ngroups, overflow):
      gid        int32 (cap,)   dense group id per row, first-occurrence
                                order; invalid lanes get ``cap``
      group_rows int32 (cap,)   representative row index per group id
      ngroups    int32 scalar   number of groups assigned
      overflow   bool scalar    exact mode only: some row exhausted its
                                probe budget and NO gid is trustworthy
                                (caller must fall back). In non-exact
                                mode always False: unresolved rows become
                                their own singleton groups.
    """
    jit_stats.bump("hash_group_ids")
    cap = valid.shape[0]
    # 2x capacity rounded up to a power of two (pages are pow2-padded
    # already; defend against odd capacities so the & mask stays sound)
    tsize = 1 << max(2 * cap - 1, 1).bit_length()
    mask = np.uint64(tsize - 1)
    row_idx = jnp.arange(cap, dtype=jnp.int32)

    h = _mix_operands(key_ops, cap)
    slot0 = (h & mask).astype(jnp.int32)

    # slot -> owning row index; ``cap`` = empty; slot ``tsize`` is the
    # dummy that absorbs scatters from masked-off lanes
    table0 = jnp.full((tsize + 1,), cap, dtype=jnp.int32)
    rep0 = jnp.where(valid, cap, row_idx)  # resolved rows' owner row
    resolved0 = ~valid

    def probe_round(carry):
        r, table, rep, resolved = carry
        active = ~resolved
        slot = jnp.where(active, (slot0 + r) & (tsize - 1), tsize)
        owner = table[slot]
        empty = active & (owner == cap)
        # claim empty slots: smallest probing row index wins the install
        claim = jnp.full((tsize + 1,), cap, dtype=jnp.int32)
        claim = claim.at[jnp.where(empty, slot, tsize)].min(row_idx)
        winner = empty & (claim[slot] == row_idx)
        table = table.at[jnp.where(winner, slot, tsize)].set(row_idx)
        owner = table[slot]
        # full-key compare against the (possibly just-installed) owner
        owner_safe = jnp.clip(owner, 0, cap - 1)
        eq = active & (owner < cap)
        for op in key_ops:
            eq = eq & (op == op[owner_safe])
        rep = jnp.where(eq, owner, rep)
        return r + 1, table, rep, resolved | eq

    def keep_probing(carry):
        r, _table, _rep, resolved = carry
        return (r < rounds) & jnp.any(~resolved)

    # typical pages resolve in 1-3 rounds; the loop exits as soon as
    # every row found its group, paying the full budget only under
    # adversarial collision chains
    _, _, rep, resolved = jax.lax.while_loop(
        keep_probing, probe_round,
        (jnp.zeros((), dtype=jnp.int32), table0, rep0, resolved0))

    unresolved = ~resolved
    if exact:
        overflow = jnp.any(unresolved)
    else:
        # partial aggregation tolerates duplicate groups: unresolved
        # rows lead their own singleton group
        rep = jnp.where(unresolved, row_idx, rep)
        overflow = jnp.zeros((), dtype=bool)

    leader = valid & (rep == row_idx)
    prefix = jnp.cumsum(leader.astype(jnp.int32)) - 1  # leader gid
    rep_safe = jnp.clip(rep, 0, cap - 1)
    gid = jnp.where(valid & (rep < cap), prefix[rep_safe], cap)
    ngroups = jnp.sum(leader.astype(jnp.int32))
    group_rows = jnp.zeros((cap + 1,), dtype=jnp.int32)
    group_rows = group_rows.at[jnp.where(leader, prefix, cap)].set(row_idx)
    return gid, group_rows[:cap], ngroups, overflow


# profiled entry points (telemetry.profiler): cost/compile
# attribution under EXPLAIN ANALYZE VERBOSE; plain calls when off
hash_group_ids = instrument(
    "hash_group_ids",
    partial(jax.jit, static_argnames=("rounds", "exact"))(
        _hash_group_ids_impl),
    static_argnames=("rounds", "exact"))


def _hash_segment_reduce_impl(gid, group_rows, ngroups, key_raws: Tuple,
                              key_nulls: Tuple, state_cols: Tuple,
                              kinds: Tuple, pallas: str = ""):
    """Reduce state columns by hash-assigned gid and gather group keys.

    Raw implementation (see ``_hash_group_ids_impl`` for why); host
    callers use the jitted+instrumented ``hash_segment_reduce`` below.

    The Pallas segment kernel requires non-decreasing gids (steps <= 1),
    so when it is active the states take one cheap single-operand sort
    on the int32 gid — still far lighter than the sort path's
    full (1 + 2k)-operand key sort dragging raw keys along. Off-TPU,
    ``jax.ops.segment_*`` handles unsorted gids directly and no sort
    runs at all.

    Returns (group_key_raws, group_key_nulls, reduced_states, out_valid)
    in the exact shape contract of ``aggregation._group_reduce``.
    """
    jit_stats.bump("hash_segment_reduce")
    from .pallas_kernels import segment_reduce

    cap = gid.shape[0]
    # state_cols is a tuple: pytree arity is trace-static, not traced
    if pallas and state_cols:  # qlint: ignore[recompile] tuple arity is pytree structure: trace-static, never a tracer bool
        ops = [gid] + list(state_cols)
        sorted_ = jax.lax.sort(ops, num_keys=1, is_stable=False)
        r_gid, r_states = sorted_[0], sorted_[1:]
    else:
        r_gid, r_states = gid, state_cols
    reduced = []
    for kind, col in zip(kinds, r_states):
        r = segment_reduce(col, r_gid, num_segments=cap + 1, kind=kind,
                           mode=pallas)
        reduced.append(r[:cap])

    out_valid = jnp.arange(cap, dtype=jnp.int32) < ngroups
    safe_idx = jnp.where(out_valid, group_rows, 0)
    out_key_raws = tuple(kr[safe_idx] for kr in key_raws)
    out_key_nulls = tuple(kn[safe_idx] & out_valid for kn in key_nulls)
    return out_key_raws, out_key_nulls, tuple(reduced), out_valid


hash_segment_reduce = instrument(
    "hash_segment_reduce",
    partial(jax.jit, static_argnames=("kinds", "pallas"))(
        _hash_segment_reduce_impl),
    static_argnames=("kinds", "pallas"))
