"""Hash joins, TPU-first.

Reference analog: ``operator/join/HashBuilderOperator.java`` (build side:
PagesIndex + JoinHash open-addressing) + ``LookupJoinOperator.java`` /
``JoinProbe`` (probe side), plus ``SetBuilderOperator``/``ChannelSet`` for
semi joins.

TPU redesign: open-addressing probes are scatter/gather-chase loops that
map poorly to XLA. Instead the build side becomes a **sorted index**: key
columns normalize to uint64 (exact for single keys; packed or hashed for
multi-key), ``lax.sort`` orders the build rows, and probing is two
``searchsorted`` calls (XLA-native vectorized binary search) giving each
probe row its candidate range. Matches expand via cumsum offsets into a
static-capacity output whose size is GUESSED from a running expansion
ratio (jit shapes are static, so some host value must pick the
capacity); the exact total rides along as an unread device scalar and is
checked only when the probe pipeline is already ``pipeline_depth`` pages
deep — the host never blocks on the page it just enqueued, and an
overflowing guess (rare) re-expands at the exact size. Candidates are
verified against the raw key columns, so hash collisions cost only
capacity, never correctness. Unmatched-probe lanes for LEFT/ANTI come
from a segment-OR over verified matches.

Two-operator split with a JoinBridge mirrors the reference; the physical
planner runs the build pipeline to completion before the probe pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, padded_size
from ..telemetry.profiler import instrument
from .operator import Operator
from .sortkeys import group_operands


def _canonical_codes(codes, dictionary):
    """Map dictionary codes to the FIRST code of their value, so equal
    strings in an aligned (duplicate-valued) pool compare equal by code."""
    if dictionary is None or len(dictionary) == 0:
        return codes
    canon = np.fromiter(
        (dictionary.lookup(v) for v in dictionary.values),
        dtype=np.int32, count=len(dictionary))
    if (canon == np.arange(len(canon), dtype=np.int32)).all():
        return codes  # already canonical (the common, dedup'd pool)
    return jnp.asarray(canon)[codes]


def _key_u64(cols, nulls, types_, mode: str) -> Tuple:
    """(key_u64, any_null): combined uint64 join key per row.

    mode (STATIC, decided once on the build side and shared via the
    bridge so both sides encode identically):
    - 'single': one key, exact order-preserving u64
    - 'packed': two keys, both known to fit 32 bits — exact pack
    - 'hashed': splitmix-combined (collisions verified against raw keys)
    """
    ops = []
    anynull = None
    for c, nl, t in zip(cols, nulls, types_):
        null_bit, key = group_operands(c, nl, t)
        if key.dtype == jnp.float64:
            # float join keys: frexp-based u64 (no f64 bitcast on TPU);
            # 2 dropped mantissa bits => rare extra candidates, all
            # filtered by the raw-key verify pass
            m, e = jnp.frexp(key)
            mant = (jnp.abs(m) * np.float64(1 << 53)).astype(jnp.int64) >> 2
            sign = (key < 0).astype(jnp.int64)
            key = (((e.astype(jnp.int64) + 1100) << np.int64(52))
                   | mant | (sign << np.int64(63))).view(jnp.uint64)
        ops.append(key)
        anynull = null_bit.astype(bool) if anynull is None \
            else (anynull | null_bit.astype(bool))
    if mode == "single":
        return ops[0], anynull
    if mode == "packed":
        hi, lo = ops[0], ops[1]
        return (hi << np.uint64(32)) | (lo & np.uint64(0xFFFFFFFF)), anynull
    return _hash_combine(ops), anynull


def _hash_combine(ops):
    acc = jnp.zeros(ops[0].shape, dtype=jnp.uint64)
    for k in ops:
        z = (k + np.uint64(0x9E3779B97F4A7C15)) * np.uint64(0xBF58476D1CE4E5B9)
        z = z ^ (z >> np.uint64(29))
        acc = (acc * np.uint64(31)) ^ z
    return acc


@jax.jit
def _build_sorted(key_u64, anynull, cols, nulls, valid):
    """Sort the build rows by key; null-key or invalid lanes sort last.
    ``valid`` rides along so FULL OUTER can emit unmatched build rows
    (including null-key rows, which are never ``usable``)."""
    from .. import jit_stats

    jit_stats.bump("join_build_sorted")
    usable = valid & ~anynull if anynull is not None else valid
    sort_key = jnp.where(usable, key_u64, np.uint64(0xFFFFFFFFFFFFFFFF))
    operands = [sort_key, usable, valid] + list(cols) + list(nulls)
    s = jax.lax.sort(operands, num_keys=1, is_stable=False)
    n = len(cols)
    return s[0], s[1], s[2], tuple(s[3:3 + n]), tuple(s[3 + n:])


# profiled entry point (telemetry.profiler): cost/compile attribution
# under EXPLAIN ANALYZE VERBOSE; a plain call when profiling is off
_build_sorted = instrument("join_build_sorted", _build_sorted)


# Raw (un-jitted, un-instrumented) probe-kernel implementations: the
# batched executor composes them under its own jit(vmap(...)) wrappers
# with the build arrays broadcast (in_axes=None), so one param-free
# build serves every lane of a literal batch. Host callers use the
# jitted+instrumented bindings below.
def _probe_counts_impl(build_keys, build_usable, probe_keys,
                       probe_usable):
    from .. import jit_stats

    jit_stats.bump("join_probe_counts")
    lo = jnp.searchsorted(build_keys, probe_keys, side="left")
    hi = jnp.searchsorted(build_keys, probe_keys, side="right")
    count = jnp.where(probe_usable, hi - lo, 0)
    return lo, count


_probe_counts = instrument("join_probe_counts",
                           jax.jit(_probe_counts_impl))


def _expand_matches_impl(lo, count, out_cap: int):
    """Candidate pairs: output lane j -> (probe_row, build_row)."""
    from .. import jit_stats

    jit_stats.bump("join_expand_matches")
    off_end = jnp.cumsum(count)
    total = off_end[-1]
    j = jnp.arange(out_cap, dtype=jnp.int64)
    probe_idx = jnp.searchsorted(off_end, j, side="right")
    probe_idx = jnp.clip(probe_idx, 0, count.shape[0] - 1)
    start = off_end[probe_idx] - count[probe_idx]
    build_idx = lo[probe_idx] + (j - start)
    lane_valid = j < total
    return (probe_idx.astype(jnp.int32),
            jnp.clip(build_idx, 0, None).astype(jnp.int32), lane_valid)


_expand_matches = instrument(
    "join_expand_matches",
    partial(jax.jit, static_argnames=("out_cap",))(_expand_matches_impl),
    static_argnames=("out_cap",))


@dataclass
class BuildSide:
    key_sorted: "jax.Array"
    usable_sorted: "jax.Array"
    valid_sorted: "jax.Array"
    cols: Tuple
    nulls: Tuple
    types: List
    dictionaries: List
    key_channels: List
    key_mode: str = "single"


class JoinBridge:
    """Hand-off from the build pipeline to the probe pipeline (reference:
    operator/join/JoinBridge.java / PartitionedLookupSourceFactory)."""

    def __init__(self):
        self.build: Optional[BuildSide] = None
        self.release = None  # set by the builder; probe calls at finish

    def set_build(self, b: BuildSide):
        self.build = b

    def destroy(self):
        """Probe side is done: drop the build index + its memory
        reservation (reference: LookupSourceFactory destroy)."""
        self.build = None
        if self.release is not None:
            self.release()
            self.release = None


class HashBuilderOperator(Operator):
    """Accumulates the build side and publishes a sorted index."""

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], bridge: JoinBridge,
                 memory_context=None, dynamic_filters: Sequence = ()):
        self.input_types = list(input_types)
        self.key_channels = list(key_channels)
        self.bridge = bridge
        # [(channel, DynamicFilter)] to fill at publish (reference:
        # DynamicFilterSourceOperator collecting build values)
        self.dynamic_filters = list(dynamic_filters)
        self._pages: List = []  # DevicePage | SpilledPage
        self._done = False
        self._ctx = memory_context
        if self._ctx is not None:
            self._ctx.set_revoke_callback(self._revoke)

    def add_input(self, page: DevicePage):
        if self._ctx is None:
            self._pages.append(page)
            return
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._pages, page)

    def _revoke(self) -> int:
        """Park build pages in host RAM until publish (reference:
        HashBuilderOperator's CONSUMING_INPUT -> SPILLING_INPUT states —
        with the disk tier below host RAM when the ledger overflows)."""
        from ..exec.memory import spill_pages

        return spill_pages(self._pages, self._ctx.pool, self._ctx.lock)

    def get_output(self):
        if self._finishing and not self._done:
            self._publish()
            self._done = True
        return None

    def _publish(self):
        from ..exec.memory import SpilledPage, device_page_bytes

        if self._ctx is not None:
            # publish owns the state; the build index it creates is
            # retained (non-revocable) for the probe's lifetime
            from ..exec.memory import prepare_finish

            total, uploads = prepare_finish(self._ctx, self._pages)
            all_spilled = bool(self._pages) and all(
                isinstance(p, SpilledPage) for p in self._pages)
            # transient: concat + sorted copy, plus per-page re-uploads
            # on the mixed path (the all-spilled path concatenates in
            # host RAM and uploads once — no per-page residency)
            self._ctx.reserve((2 * total if all_spilled
                               else uploads + 2 * total), revocable=False)
        if self._pages:
            spilled = [p for p in self._pages if isinstance(p, SpilledPage)]
            if spilled and len(spilled) == len(self._pages):
                # pressure path: concatenate in host RAM, upload once
                # (host() loads disk-parked pages back into RAM first)
                hosts = [p.host() for p in self._pages]
                cap = padded_size(sum(p.capacity for p in hosts))
                cols, nulls = [], []
                nch = len(self.input_types)
                for i in range(nch):
                    c = np.concatenate([p.cols[i] for p in hosts])
                    n = np.concatenate([p.nulls[i] for p in hosts])
                    cols.append(jnp.asarray(_np_pad(c, cap)))
                    nulls.append(jnp.asarray(_np_pad(n, cap, fill=True)))
                v = np.concatenate([p.valid for p in hosts])
                valid = jnp.asarray(_np_pad(v, cap))
                dicts = self._unified_dicts(hosts)
            else:
                pages = [p.to_device() if isinstance(p, SpilledPage) else p
                         for p in self._pages]
                cap = padded_size(sum(p.capacity for p in pages))
                cols, nulls = [], []
                nch = len(self.input_types)
                for i in range(nch):
                    cols.append(_pad_concat([p.cols[i] for p in pages], cap))
                    nulls.append(_pad_concat([p.nulls[i] for p in pages],
                                             cap, fill=True))
                valid = _pad_concat([p.valid for p in pages], cap)
                dicts = self._unified_dicts(pages)
        else:
            from ..block import Dictionary

            cap = 16
            cols = [jnp.zeros(cap, dtype=t.storage) for t in self.input_types]
            nulls = [jnp.ones(cap, dtype=bool) for _ in self.input_types]
            valid = jnp.zeros(cap, dtype=bool)
            dicts = [Dictionary() if t.is_pooled else None
                     for t in self.input_types]
        for ch, df in self.dynamic_filters:
            df.collect(cols[ch], nulls[ch], valid)
        kc = self.key_channels
        # pooled keys (strings AND array/map/row composites) join on
        # dictionary CODES in the build's pool: the build side uses its
        # own codes as plain ints; the probe side remaps its codes into
        # this pool (LookupJoinOperator._remap), so both sides feed
        # _key_u64 the same integer key space.
        # CANONICALIZE build key codes first: aligned pools (derived by
        # transforms) may map one value to several codes, and
        # code-equality must mean value-equality for the join keys.
        # Canonical codes decode to the same values, so rewriting the
        # stored column is output-safe.
        for c in kc:
            if self.input_types[c].is_pooled:
                cols[c] = _canonical_codes(cols[c], dicts[c])
        key_types = [T.BIGINT if self.input_types[c].is_pooled
                     else self.input_types[c] for c in kc]
        mode = "single" if len(kc) == 1 else "hashed"
        if len(kc) == 2:
            # static decision — no device sync: pack two keys iff both
            # are provably 32-bit lanes (4-byte integer/bool storage, or
            # pooled codes, int32 by construction; sign-extension keeps
            # the low 32 bits injective). Floats are excluded: their
            # frexp encoding uses all 64 bits, so truncation would mass-
            # collide. The u64 key is only a bucketing function —
            # candidates are verified against raw keys — so a
            # conservative choice is safe either way.
            fits32 = [
                self.input_types[c].is_pooled
                or (t.storage is not None
                    and np.dtype(t.storage).kind in "iub"
                    and np.dtype(t.storage).itemsize <= 4)
                for c, t in zip(kc, key_types)]
            mode = "packed" if all(fits32) else "hashed"
        key, anynull = _key_u64([cols[c] for c in kc],
                                [nulls[c] for c in kc], key_types, mode)
        ks, us, vs, scols, snulls = _build_sorted(
            key, anynull if anynull is not None
            else jnp.zeros(cap, dtype=bool), tuple(cols), tuple(nulls),
            valid)
        self.bridge.set_build(BuildSide(ks, us, vs, scols, snulls,
                                        self.input_types, dicts, kc, mode))
        self._pages = []  # release the input pages; only the index remains
        if self._ctx is not None:
            # retain only the published index: sorted key (8B) + usable
            # + valid (1B each) + per-channel data/null lanes
            retained = cap * (10 + sum(c.dtype.itemsize + 1 for c in scols))
            self._ctx.close()
            self._ctx.reserve(retained, revocable=False)
            self.bridge.release = self._ctx.close

    def _unified_dicts(self, pages):
        from ..block import unify_dictionaries

        return unify_dictionaries(pages, len(self.input_types))

    def is_finished(self) -> bool:
        return self._done


class LookupJoinOperator(Operator):
    """Probe side. join_type: inner | left | full | semi | anti.

    Output layout: all probe channels, then (inner/left/full) all build
    channels — build channels NULL on unmatched left rows. semi/anti emit
    probe channels only. FULL OUTER additionally OR-accumulates a
    matched flag per (sorted) build row across all probe pages and, once
    the probe side finishes, emits one final page of unmatched build rows
    with NULL probe channels (reference: LookupJoinOperator's
    OuterLookupSource / buildOuter position iterator,
    operator/join/LookupJoinOperator.java:36)."""

    #: bound on candidate-expansion lanes per kernel launch: a probe page
    #: whose total match count pads beyond this is sliced into contiguous
    #: row chunks (greedy, from the per-row counts pulled to host ONCE)
    #: and joined one chunk per driver quantum, so skewed or high-fanout
    #: joins never materialize all pairs — neither in one buffer nor as a
    #: backlog of pending output pages (round-2 verdict: unbounded
    #: _expand_matches blows HBM at scale)
    max_lanes = 1 << 20

    #: probe pages whose guessed-capacity outputs are enqueued on device
    #: but not yet overflow-checked. The oldest is checked — ONE scalar
    #: read, computed pipeline_depth-1 pages ago and thus long since
    #: done — only when the pipeline is full or upstream stalls, so the
    #: host never blocks on kernels it just enqueued (round-3 verdict:
    #: int(jnp.sum(count)) serialized host and device per probe page)
    pipeline_depth = 4

    def __init__(self, probe_types: Sequence[T.Type],
                 probe_key_channels: Sequence[int], bridge: JoinBridge,
                 join_type: str = "inner",
                 filter_fn=None, max_lanes: Optional[int] = None,
                 memory_limited: bool = False):
        assert join_type in ("inner", "left", "full", "semi", "anti")
        self.probe_types = list(probe_types)
        self.probe_keys = list(probe_key_channels)
        self.bridge = bridge
        self.join_type = join_type
        self.filter_fn = filter_fn  # optional post-join residual filter
        if max_lanes is not None:
            self.max_lanes = max_lanes
        if memory_limited:
            # pool-governed query: the pending buffers are invisible to
            # the memory manager's reserve/revoke machinery, so keep the
            # pre-pipelining one-page-in-flight footprint
            self.pipeline_depth = 1
        self._pending: List[dict] = []   # awaiting overflow check
        self._ready: List[DevicePage] = []
        # EWMA lanes-per-probe-row for the capacity guess. Starts below
        # 1 so the first guess lands in the page's own pow2 bucket (N:1
        # joins then never overflow and never double the page); a
        # fan-out join overflows once, the ratio learns, later pages
        # guess right. pow2 padding gives the headroom.
        self._ratio = 0.75
        self._added_since_get = False
        self._done = False
        # FULL OUTER state: per-sorted-build-row matched flag (device,
        # cap+1 lanes — the last is the dead-lane sink) + the dictionary
        # pools of the last probe page (the unmatched-build page's probe
        # channels are all-NULL, but string channels still need a pool)
        self._build_matched = None
        self._probe_dicts = None
        self._emitted_unmatched = False
        # probe-dict -> build-dict code remap LUTs for pooled join keys
        self._remap_cache: dict = {}

    @property
    def output_types(self) -> List[T.Type]:
        b = self.bridge.build
        if self.join_type in ("semi", "anti"):
            return list(self.probe_types)
        return list(self.probe_types) + list(b.types)

    def needs_input(self) -> bool:
        return (not self._ready
                and len(self._pending) < self.pipeline_depth
                and not self._finishing)

    def add_input(self, page: DevicePage):
        """Enqueue the whole probe chain for this page — counts,
        guessed-capacity expansion, finalize — WITHOUT reading anything
        back; the overflow check happens in get_output once the
        pipeline is deep enough to have hidden this page's latency."""
        b = self.bridge.build
        assert b is not None, "probe started before build finished"
        kc = self.probe_keys
        pkey_cols, key_types = self._probe_key_cols(page, b)
        pkey, panynull = _key_u64(pkey_cols,
                                  [page.nulls[c] for c in kc],
                                  key_types, b.key_mode)
        pusable = page.valid & ~panynull if panynull is not None \
            else page.valid
        direct = self._probe_direct(page, b, pkey, pusable)
        if direct is not None:
            self._ready.append(direct)
            self._added_since_get = True
            return
        lo, count = self._probe_lo_count(b, pkey, pusable)
        rows = int(page.valid.shape[0])
        cap = padded_size(max(16, int(rows * self._ratio * 1.1)))
        while cap > self.max_lanes and cap > 16:
            cap >>= 1  # budget is checked POST-padding, like every path
        out, keep, bidx = self._make_out(page, pkey_cols, pusable, lo,
                                         count, cap)
        self._pending.append({
            "page": page, "pkey_cols": pkey_cols, "pusable": pusable,
            "lo": lo, "count": count, "rows": rows, "cap": cap,
            "total": jnp.sum(count), "out": out, "keep": keep,
            "bidx": bidx})
        self._added_since_get = True

    def _probe_direct(self, page: DevicePage, b: "BuildSide", pkey,
                      pusable):
        """Strategy seam: a complete output page computed straight from
        the probe keys (no candidate expansion), or None to run the
        lo/count path below.  The matmul strategy
        (``ops/matmul_join.py``) answers semi/anti membership here."""
        return None

    def _probe_lo_count(self, b: "BuildSide", pkey, pusable):
        """Strategy seam: each probe row's candidate range (lo, count)
        against the sorted build index — here two XLA-native vectorized
        binary searches; the matmul strategy overrides with the blocked
        one-hot matmul probe."""
        return _probe_counts(b.key_sorted, b.usable_sorted, pkey,
                             pusable)

    def get_output(self):
        if self._ready:
            return self._ready.pop(0)
        if self._pending and (self._finishing
                              or len(self._pending) >= self.pipeline_depth
                              or not self._added_since_get):
            self._verify_oldest()
            self._added_since_get = False
            if self._ready:
                return self._ready.pop(0)
        self._added_since_get = False
        if self._finishing and not self._pending:
            if self.join_type == "full" and not self._emitted_unmatched:
                self._emitted_unmatched = True
                return self._unmatched_build_page()
            if not self._done:
                self.bridge.destroy()
            self._done = True
        return None

    def _verify_oldest(self):
        """Overflow-check the oldest pending page: the deferred scalar
        read. Fits the guess (common) -> emit as-is; overflowed (rare)
        -> re-expand at the now-known exact size, chunked under the
        lane budget."""
        rec = self._pending.pop(0)
        tot = int(rec["total"])
        self._ratio = 0.75 * self._ratio \
            + 0.25 * (tot / max(rec["rows"], 1))
        if tot <= rec["cap"]:
            self._mark_full(rec["keep"], rec["bidx"],
                            rec["page"].dictionaries)
            self._ready.append(rec["out"])
            return
        for unit in self._chunk_units(rec, tot):
            out, keep, bidx = self._make_out(*unit)
            self._mark_full(keep, bidx, rec["page"].dictionaries)
            self._ready.append(out)

    def _chunk_units(self, rec: dict, total: int) -> List:
        """(page, pkey_cols, pusable, lo, count, lane_cap) units whose
        expansions fit the lane budget; greedy contiguous row chunks
        from the per-row counts (host copy only on this over-budget
        path). A single row exceeding the budget still becomes its own
        unit: out_cap grows to its fan-out, which no slicing avoids."""
        page, pkey_cols, pusable = rec["page"], rec["pkey_cols"], \
            rec["pusable"]
        lo, count = rec["lo"], rec["count"]
        if padded_size(max(total, 16)) <= self.max_lanes:
            return [(page, pkey_cols, pusable, lo, count,
                     padded_size(max(total, 16)))]
        counts = np.asarray(count)
        units: List = []
        n = counts.shape[0]
        i = 0
        while i < n:
            j = i
            run = 0
            while j < n and (j == i or
                             padded_size(max(run + int(counts[j]), 16))
                             <= self.max_lanes):
                run += int(counts[j])
                j += 1
            cap = padded_size(j - i)
            sl = slice(i, j)
            sub = DevicePage(page.types,
                             [_pad_dev(c[sl], cap) for c in page.cols],
                             [_pad_dev(x[sl], cap) for x in page.nulls],
                             _pad_dev(page.valid[sl], cap),
                             page.dictionaries)
            units.append((sub, [_pad_dev(k[sl], cap) for k in pkey_cols],
                          _pad_dev(pusable[sl], cap),
                          _pad_dev(lo[sl], cap), _pad_dev(count[sl], cap),
                          padded_size(max(run, 16))))
            i = j
        return units

    def _mark_full(self, keep, build_idx, pdicts):
        """FULL OUTER bookkeeping, applied only AFTER the overflow check
        passed (a truncated expansion must not mark build rows)."""
        if self.join_type != "full" or keep is None:
            return
        b = self.bridge.build
        bcap = int(b.valid_sorted.shape[0])
        if self._build_matched is None:
            self._build_matched = jnp.zeros(bcap + 1, dtype=bool)
        self._build_matched = _mark_build_matched(
            self._build_matched, keep, build_idx)
        self._probe_dicts = pdicts

    def _unmatched_build_page(self) -> DevicePage:
        """FULL OUTER tail: build rows no kept lane ever matched, with
        all probe channels NULL."""
        from ..block import Dictionary

        b = self.bridge.build
        cap = int(b.valid_sorted.shape[0])
        unmatched = b.valid_sorted if self._build_matched is None \
            else b.valid_sorted & ~self._build_matched[:cap]
        pcols = [jnp.zeros(cap, dtype=t.storage) for t in self.probe_types]
        pnulls = [jnp.ones(cap, dtype=bool) for _ in self.probe_types]
        pdicts = self._probe_dicts
        if pdicts is None:
            pdicts = [Dictionary() if t.is_pooled else None
                      for t in self.probe_types]
        return DevicePage(self.output_types, pcols + list(b.cols),
                          pnulls + list(b.nulls), unmatched,
                          list(pdicts) + list(b.dictionaries))

    def is_finished(self) -> bool:
        return self._done

    def _remap(self, probe_dict, build_dict):
        """Probe-pool code -> build-pool code LUT (-1 = absent, matches
        nothing; always canonical first-occurrence codes, so aligned
        pools with duplicate values compare correctly). Host work once
        per (probe pool, build pool) pair; the gather runs on device.
        The cache entry pins both dict objects: bare id() keys would go
        stale if a pool were GC'd and its address reused."""
        key = (id(probe_dict), len(probe_dict) if probe_dict else 0,
               id(build_dict), len(build_dict) if build_dict else 0)
        hit = self._remap_cache.get(key)
        if hit is not None:
            return hit[0]
        if build_dict is None:
            lut = np.full(max(1, len(probe_dict or ())), -1,
                          dtype=np.int64)
        else:
            lut = np.fromiter(
                (build_dict.lookup(v) for v in probe_dict.values),
                dtype=np.int64,
                count=len(probe_dict)) if probe_dict and \
                len(probe_dict) else np.full(1, -1, dtype=np.int64)
        lut = jnp.asarray(lut)
        if len(self._remap_cache) >= 128:  # evict BEFORE inserting
            self._remap_cache.clear()
        self._remap_cache[key] = (lut, probe_dict, build_dict)
        return lut

    def _probe_key_cols(self, page: DevicePage, b: "BuildSide"):
        """Per key channel: the probe column transformed into the build's
        key space (identity for unpooled types; canonical code remap for
        pooled keys — also when pools are shared, since an aligned pool
        may hold duplicate values under distinct codes)."""
        out = []
        types_ = []
        for i, c in enumerate(self.probe_keys):
            t = self.probe_types[c]
            if t.is_pooled:
                pd = page.dictionaries[c]
                bd = b.dictionaries[b.key_channels[i]]
                out.append(self._remap(pd, bd)[page.cols[c]])
                types_.append(T.BIGINT)
            else:
                out.append(page.cols[c])
                types_.append(t)
        return out, types_

    def _make_out(self, page: DevicePage, pkey_cols, pusable, lo, count,
                  lane_cap: int) -> Tuple:
        """One expansion at static capacity ``lane_cap``: returns
        (out_page, keep, build_idx). keep/build_idx feed the FULL OUTER
        marker — applied by the caller only after the overflow check —
        and are None for semi/anti (no build channels in the output)."""
        b = self.bridge.build

        if self.join_type in ("semi", "anti"):
            if self.filter_fn is None:
                matched = _semi_matched(
                    lo, count,
                    tuple(pkey_cols),
                    tuple(b.cols[c] for c in b.key_channels),
                    page.valid.shape[0], out_cap=lane_cap)
            else:
                # residual-filtered semi/anti (q21's l3.l_suppkey <>
                # l1.l_suppkey): expand candidate lanes, verify keys,
                # evaluate the filter over the combined probe+build row,
                # then segment-OR back onto probe rows
                probe_idx, build_idx, keep = _expand_verified(
                    lo, count,
                    tuple(pkey_cols),
                    tuple(b.cols[c] for c in b.key_channels),
                    out_cap=lane_cap)
                lanes = _gather_lanes(page, b, probe_idx, build_idx, keep)
                matched = _segment_any(self.filter_fn(lanes).valid,
                                       probe_idx, page.valid.shape[0])
            if self.join_type == "semi":
                new_valid = page.valid & matched
            else:
                new_valid = page.valid & ~matched
            return (DevicePage(page.types, page.cols, page.nulls,
                               new_valid, page.dictionaries), None, None)

        probe_idx, build_idx, keep = _expand_verified(
            lo, count,
            tuple(pkey_cols),
            tuple(b.cols[c] for c in b.key_channels), out_cap=lane_cap)
        if self.filter_fn is not None:
            # ON-clause residual runs BEFORE left-join padding: lanes
            # failing it make the probe row unmatched, not dropped
            lanes = _gather_lanes(page, b, probe_idx, build_idx, keep)
            keep = self.filter_fn(lanes).valid
        out_cols, out_nulls, out_valid = _finalize_join(
            tuple(page.cols), tuple(page.nulls), page.valid,
            tuple(b.cols), tuple(b.nulls),
            probe_idx, build_idx, keep,
            left=self.join_type in ("left", "full"))
        types = self.output_types
        dicts = list(page.dictionaries) + list(b.dictionaries)
        return (DevicePage(types, list(out_cols), list(out_nulls),
                           out_valid, dicts), keep, build_idx)


def _finalize_join_impl(pcols, pnulls, pvalid, bcols, bnulls,
                        probe_idx, build_idx, keep, left: bool):
    """Gather joined output lanes; for LEFT, append one lane per probe
    row, valid iff the row matched no kept lane (NULL build columns).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_finalize_join`` binding below."""
    lane_cap = probe_idx.shape[0]
    if left:
        matched = _segment_any_impl(keep, probe_idx, pvalid.shape[0])
        n_extra = pvalid.shape[0]
        extra_probe = jnp.arange(n_extra, dtype=probe_idx.dtype)
        probe_idx = jnp.concatenate([probe_idx, extra_probe])
        build_idx = jnp.concatenate(
            [build_idx, jnp.zeros(n_extra, dtype=build_idx.dtype)])
        keep = jnp.concatenate([keep, pvalid & ~matched])
        build_is_null = jnp.concatenate(
            [jnp.zeros(lane_cap, dtype=bool),
             jnp.ones(n_extra, dtype=bool)])
    else:
        build_is_null = jnp.zeros(lane_cap, dtype=bool)

    out_cols = tuple(c[probe_idx] for c in pcols) + \
        tuple(c[build_idx] for c in bcols)
    out_nulls = tuple(n[probe_idx] for n in pnulls) + \
        tuple(n[build_idx] | build_is_null for n in bnulls)
    return out_cols, out_nulls, keep


_finalize_join = partial(jax.jit, static_argnames=("left",))(
    _finalize_join_impl)


def _gather_lanes(page: DevicePage, b: "BuildSide", probe_idx, build_idx,
                  keep) -> DevicePage:
    """Combined probe+build rows for candidate lanes (residual-filter
    evaluation layout: probe channels, then build channels)."""
    return DevicePage(
        list(page.types) + list(b.types),
        [c[probe_idx] for c in page.cols]
        + [c[build_idx] for c in b.cols],
        [n[probe_idx] for n in page.nulls]
        + [n[build_idx] for n in b.nulls],
        keep,
        list(page.dictionaries) + list(b.dictionaries))


def _expand_verified_impl(lo, count, pkey_cols, bkey_cols, out_cap: int):
    """Candidate lanes with raw-key verification applied (for
    residual-filtered semi/anti joins).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_expand_verified`` binding below."""
    probe_idx, build_idx, lane_valid = _expand_matches_impl(
        lo, count, out_cap)
    keep = lane_valid
    for pc, bc in zip(pkey_cols, bkey_cols):
        keep = keep & (pc[probe_idx] == bc[build_idx])
    return probe_idx, build_idx, keep


_expand_verified = partial(jax.jit, static_argnames=("out_cap",))(
    _expand_verified_impl)


@jax.jit
def _mark_build_matched(acc, keep, build_idx):
    """OR kept lanes into the per-sorted-build-row matched accumulator
    (last lane of ``acc`` is the dead-lane sink)."""
    sink = acc.shape[0] - 1
    return acc.at[jnp.where(keep, build_idx, sink)].max(True)


def _segment_any_impl(keep, probe_idx, probe_cap: int):
    """OR of ``keep`` lanes per probe row."""
    matched = jnp.zeros(probe_cap + 1, dtype=bool)
    matched = matched.at[jnp.where(keep, probe_idx, probe_cap)].max(True)
    return matched[:-1]


_segment_any = partial(jax.jit, static_argnames=("probe_cap",))(
    _segment_any_impl)


def _semi_matched_impl(lo, count, pkey_cols, bkey_cols, probe_cap: int,
                       out_cap: int):
    """Per-probe-row matched flag: expand candidates, verify raw keys,
    segment-OR back onto probe rows (collision-safe for any key mode).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_semi_matched`` binding below."""
    probe_idx, build_idx, lane_valid = _expand_matches_impl(
        lo, count, out_cap)
    keep = lane_valid
    for pc, bc in zip(pkey_cols, bkey_cols):
        keep = keep & (pc[probe_idx] == bc[build_idx])
    matched = jnp.zeros(probe_cap + 1, dtype=bool)
    matched = matched.at[jnp.where(keep, probe_idx, probe_cap)].max(True)
    return matched[:-1]


_semi_matched = partial(jax.jit, static_argnames=("probe_cap", "out_cap"))(
    _semi_matched_impl)


def _pad_dev(arr, cap: int):
    """Pad a device array slice to cap lanes with zeros/False (padding
    lanes are dead: valid False, count 0)."""
    n = arr.shape[0]
    if n == cap:
        return arr
    return jnp.concatenate(
        [arr, jnp.zeros((cap - n,), dtype=arr.dtype)])


def _np_pad(arr: np.ndarray, cap: int, fill: bool = False) -> np.ndarray:
    n = arr.shape[0]
    if n == cap:
        return arr
    out = np.full(cap, fill, dtype=bool) if arr.dtype == bool \
        else np.zeros(cap, dtype=arr.dtype)
    out[:n] = arr
    return out


def _pad_concat(arrays, cap: int, fill: bool = False):
    cat = jnp.concatenate(list(arrays))
    n = cat.shape[0]
    if n == cap:
        return cat
    pad = jnp.full((cap - n,), fill, dtype=cat.dtype) if cat.dtype == bool \
        else jnp.zeros((cap - n,), dtype=cat.dtype)
    return jnp.concatenate([cat, pad])
