"""Hash joins, TPU-first.

Reference analog: ``operator/join/HashBuilderOperator.java`` (build side:
PagesIndex + JoinHash open-addressing) + ``LookupJoinOperator.java`` /
``JoinProbe`` (probe side), plus ``SetBuilderOperator``/``ChannelSet`` for
semi joins.

TPU redesign: open-addressing probes are scatter/gather-chase loops that
map poorly to XLA. Instead the build side becomes a **sorted index**: key
columns normalize to uint64 (exact for single keys; packed or hashed for
multi-key), ``lax.sort`` orders the build rows, and probing is two
``searchsorted`` calls (XLA-native vectorized binary search) giving each
probe row its candidate range. Matches expand via cumsum offsets into a
static-capacity output whose size is GUESSED from a running expansion
ratio (jit shapes are static, so some host value must pick the
capacity); the exact total rides along as an unread device scalar and is
checked only when the probe pipeline is already ``pipeline_depth`` pages
deep — the host never blocks on the page it just enqueued, and an
overflowing guess (rare) re-expands at the exact size. Candidates are
verified against the raw key columns, so hash collisions cost only
capacity, never correctness. Unmatched-probe lanes for LEFT/ANTI come
from a segment-OR over verified matches.

Two-operator split with a JoinBridge mirrors the reference; the physical
planner runs the build pipeline to completion before the probe pipeline.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, padded_size
from ..telemetry.profiler import instrument
from .operator import Operator
from .sortkeys import group_operands


def _canonical_codes(codes, dictionary):
    """Map dictionary codes to the FIRST code of their value, so equal
    strings in an aligned (duplicate-valued) pool compare equal by code."""
    if dictionary is None or len(dictionary) == 0:
        return codes
    canon = np.fromiter(
        (dictionary.lookup(v) for v in dictionary.values),
        dtype=np.int32, count=len(dictionary))
    if (canon == np.arange(len(canon), dtype=np.int32)).all():
        return codes  # already canonical (the common, dedup'd pool)
    return jnp.asarray(canon)[codes]


def _key_u64(cols, nulls, types_, mode: str) -> Tuple:
    """(key_u64, any_null): combined uint64 join key per row.

    mode (STATIC, decided once on the build side and shared via the
    bridge so both sides encode identically):
    - 'single': one key, exact order-preserving u64
    - 'packed': two keys, both known to fit 32 bits — exact pack
    - 'hashed': splitmix-combined (collisions verified against raw keys)
    """
    ops = []
    anynull = None
    for c, nl, t in zip(cols, nulls, types_):
        null_bit, key = group_operands(c, nl, t)
        if key.dtype == jnp.float64:
            # float join keys: frexp-based u64 (no f64 bitcast on TPU);
            # 2 dropped mantissa bits => rare extra candidates, all
            # filtered by the raw-key verify pass
            m, e = jnp.frexp(key)
            mant = (jnp.abs(m) * np.float64(1 << 53)).astype(jnp.int64) >> 2
            sign = (key < 0).astype(jnp.int64)
            key = (((e.astype(jnp.int64) + 1100) << np.int64(52))
                   | mant | (sign << np.int64(63))).view(jnp.uint64)
        ops.append(key)
        anynull = null_bit.astype(bool) if anynull is None \
            else (anynull | null_bit.astype(bool))
    if mode == "single":
        return ops[0], anynull
    if mode == "packed":
        hi, lo = ops[0], ops[1]
        return (hi << np.uint64(32)) | (lo & np.uint64(0xFFFFFFFF)), anynull
    return _hash_combine(ops), anynull


def _hash_combine(ops):
    acc = jnp.zeros(ops[0].shape, dtype=jnp.uint64)
    for k in ops:
        z = (k + np.uint64(0x9E3779B97F4A7C15)) * np.uint64(0xBF58476D1CE4E5B9)
        z = z ^ (z >> np.uint64(29))
        acc = (acc * np.uint64(31)) ^ z
    return acc


@jax.jit
def _build_sorted(key_u64, anynull, cols, nulls, valid):
    """Sort the build rows by key; null-key or invalid lanes sort last.
    ``valid`` rides along so FULL OUTER can emit unmatched build rows
    (including null-key rows, which are never ``usable``)."""
    from .. import jit_stats

    jit_stats.bump("join_build_sorted")
    usable = valid & ~anynull if anynull is not None else valid
    sort_key = jnp.where(usable, key_u64, np.uint64(0xFFFFFFFFFFFFFFFF))
    operands = [sort_key, usable, valid] + list(cols) + list(nulls)
    s = jax.lax.sort(operands, num_keys=1, is_stable=False)
    n = len(cols)
    return s[0], s[1], s[2], tuple(s[3:3 + n]), tuple(s[3 + n:])


# profiled entry point (telemetry.profiler): cost/compile attribution
# under EXPLAIN ANALYZE VERBOSE; a plain call when profiling is off
_build_sorted = instrument("join_build_sorted", _build_sorted)


# Raw (un-jitted, un-instrumented) probe-kernel implementations: the
# batched executor composes them under its own jit(vmap(...)) wrappers
# with the build arrays broadcast (in_axes=None), so one param-free
# build serves every lane of a literal batch. Host callers use the
# jitted+instrumented bindings below.
def _probe_counts_impl(build_keys, build_usable, probe_keys,
                       probe_usable):
    from .. import jit_stats

    jit_stats.bump("join_probe_counts")
    lo = jnp.searchsorted(build_keys, probe_keys, side="left")
    hi = jnp.searchsorted(build_keys, probe_keys, side="right")
    count = jnp.where(probe_usable, hi - lo, 0)
    return lo, count


_probe_counts = instrument("join_probe_counts",
                           jax.jit(_probe_counts_impl))


def _expand_matches_impl(lo, count, out_cap: int):
    """Candidate pairs: output lane j -> (probe_row, build_row)."""
    from .. import jit_stats

    jit_stats.bump("join_expand_matches")
    off_end = jnp.cumsum(count)
    total = off_end[-1]
    j = jnp.arange(out_cap, dtype=jnp.int64)
    probe_idx = jnp.searchsorted(off_end, j, side="right")
    probe_idx = jnp.clip(probe_idx, 0, count.shape[0] - 1)
    start = off_end[probe_idx] - count[probe_idx]
    build_idx = lo[probe_idx] + (j - start)
    lane_valid = j < total
    return (probe_idx.astype(jnp.int32),
            jnp.clip(build_idx, 0, None).astype(jnp.int32), lane_valid)


_expand_matches = instrument(
    "join_expand_matches",
    partial(jax.jit, static_argnames=("out_cap",))(_expand_matches_impl),
    static_argnames=("out_cap",))


@dataclass
class BuildSide:
    key_sorted: "jax.Array"
    usable_sorted: "jax.Array"
    valid_sorted: "jax.Array"
    cols: Tuple
    nulls: Tuple
    types: List
    dictionaries: List
    key_channels: List
    key_mode: str = "single"


class JoinBridge:
    """Hand-off from the build pipeline to the probe pipeline (reference:
    operator/join/JoinBridge.java / PartitionedLookupSourceFactory)."""

    def __init__(self):
        self.build: Optional[BuildSide] = None
        self.release = None  # set by the builder; probe calls at finish
        #: HybridJoinState once the builder entered partitioned mode
        #: under memory pressure; None on the (common) fully-resident
        #: path.  The probe routes rows by it and runs the deferred
        #: per-partition unspill->probe passes at finish.
        self.hybrid: Optional["HybridJoinState"] = None

    def set_build(self, b: BuildSide):
        self.build = b

    def destroy(self):
        """Probe side is done: drop the build index + its memory
        reservation (reference: LookupSourceFactory destroy)."""
        self.build = None
        if self.release is not None:
            self.release()
            self.release = None


# -- dynamic hybrid hash join ------------------------------------------------
#
# Grace/hybrid-style degradation ("Design Trade-offs for a Robust Dynamic
# Hybrid Hash Join"): under memory pressure the build input is partitioned
# by a splitmix64 sub-hash of the join key; hot partitions stay resident on
# device and feed the normal sorted-index path, cold partitions park
# page-at-a-time through the spill tiers (host ledger -> CRC-framed disk
# files).  Probe rows of cold partitions spill alongside their build
# partition and join in per-partition unspill->probe passes at finish; a
# partition that still exceeds the pool on unspill recursively repartitions
# with a depth-salted hash.


def _splitmix64_np(z: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over a uint64 numpy array (wraps mod 2^64)."""
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _salt_for_depth(depth: int) -> int:
    """Per-recursion-level hash salt: the same key must land in DIFFERENT
    sub-partitions when an oversized partition repartitions, or recursion
    could never split it."""
    return (0x9E3779B97F4A7C15 * (depth + 1)) & 0xFFFFFFFFFFFFFFFF


class HybridJoinState:
    """Resident-set bookkeeping shared by the build and probe operators
    of one hybrid hash join.

    ``_lock`` guards the partition table: ``resident`` (the device-
    resident partition ids) and the cold-partition spill lists mutate
    under it from several threads — the build driver routing input, the
    pool's revocation callback demoting partitions (any reserving
    thread), and the probe driver spilling cold probe rows."""

    def __init__(self, fanout: int, max_depth: int = 3,
                 source: str = "local", depth: int = 0):
        self._lock = threading.RLock()
        self.fanout = fanout
        self.max_depth = max_depth
        self.source = source        # fanout provenance: hbo|session|local
        self.depth = depth
        self.salt = _salt_for_depth(depth)
        self.resident = frozenset(range(fanout))
        #: pid -> [SpilledPage] (build rows of demoted partitions)
        self.spilled_build: Dict[int, List] = {}
        #: pid -> [SpilledPage] (probe rows parked beside their build)
        self.spilled_probe: Dict[int, List] = {}
        self.demotions = 0          # revocation-driven partition demotions
        self.repartitions = 0       # recursive splits on unspill
        self.max_depth_seen = depth
        self.spilled_build_rows = 0
        self.total_build_rows = 0
        #: the build's memory context (set by the builder): the probe's
        #: deferred passes reserve partition transients against it
        self.ctx = None
        #: pooled-key value-hash LUT cache (dict objects pinned so a
        #: reused id() can never alias a dead pool)
        self._hash_luts: Dict[tuple, tuple] = {}

    # -- partition table mutations (all under _lock) --------------------

    def demote(self, pid: int, pages: List, rows: int):
        """Revocation demoted partition ``pid``: drop it from the
        resident set and park its build pages."""
        with self._lock:
            self.resident = self.resident - {pid}
            self.spilled_build.setdefault(pid, []).extend(pages)
            self.spilled_build_rows += rows
            self.demotions += 1

    def route_build_spill(self, pid: int, page, rows: int):
        """A build page arriving for an already-cold partition parks
        directly (the page-at-a-time path — no device residency)."""
        with self._lock:
            self.resident = self.resident - {pid}
            self.spilled_build.setdefault(pid, []).append(page)
            self.spilled_build_rows += rows

    def add_probe_spill(self, pid: int, page):
        with self._lock:
            self.spilled_probe.setdefault(pid, []).append(page)

    def count_build_rows(self, rows: int):
        with self._lock:
            self.total_build_rows += rows

    def note_depth(self, depth: int):
        with self._lock:
            self.repartitions += 1
            self.max_depth_seen = max(self.max_depth_seen, depth)

    def spill_fraction(self) -> float:
        with self._lock:
            return self.spilled_build_rows / max(1, self.total_build_rows)

    # -- partition hash --------------------------------------------------

    def _value_hash_lut(self, d) -> np.ndarray:
        """code -> stable-within-process value hash for one pool, so
        both sides partition pooled keys by VALUE (their code spaces
        differ until the probe-side remap, which happens later)."""
        key = (id(d), len(d) if d else 0)
        hit = self._hash_luts.get(key)
        if hit is not None:
            return hit[0]
        if d is None or len(d) == 0:
            lut = np.zeros(1, dtype=np.uint64)
        else:
            lut = np.fromiter(
                (hash(v) & 0xFFFFFFFFFFFFFFFF for v in d.values),
                dtype=np.uint64, count=len(d))
        self._hash_luts[key] = (lut, d)
        return lut

    def partition_ids(self, cols: List[np.ndarray],
                      nulls: List[np.ndarray], types_, dicts,
                      salt: Optional[int] = None,
                      fanout: Optional[int] = None) -> np.ndarray:
        """Per-row partition id from the raw key VALUES (host arrays).

        Value-based — not code- or storage-based — so build and probe
        rows with join-equal keys land in the same partition even when
        their dictionaries or integer widths differ.  Null keys hash to
        partition of key 0; they are routed resident by the callers
        (they match nothing, and LEFT/ANTI must emit them exactly
        once)."""
        salt = self.salt if salt is None else salt
        fanout = self.fanout if fanout is None else fanout
        acc = np.zeros(cols[0].shape[0], dtype=np.uint64)
        for c, nl, t, d in zip(cols, nulls, types_, dicts):
            if t.is_pooled:
                lut = self._value_hash_lut(d)
                codes = np.clip(c.astype(np.int64), 0, len(lut) - 1)
                k = lut[codes]
            elif np.issubdtype(c.dtype, np.floating):
                f = c.astype(np.float64)
                f = np.where(f == 0.0, 0.0, f)   # -0.0 joins +0.0
                k = f.view(np.uint64)
                k = np.where(np.isnan(f),
                             np.uint64(0x7FF8000000000000), k)
            elif c.dtype == bool:
                k = c.astype(np.uint64)
            else:
                k = c.astype(np.int64).view(np.uint64)
            k = np.where(nl, np.uint64(0), k)
            acc = (acc * np.uint64(31)) ^ _splitmix64_np(
                k + np.uint64(0x9E3779B97F4A7C15))
        pid = _splitmix64_np(acc ^ np.uint64(salt)) \
            & np.uint64(fanout - 1)
        return pid.astype(np.int64)


def _host_spilled(types_, cols: List[np.ndarray], nulls: List[np.ndarray],
                  k: int, dicts):
    """An in-RAM SpilledPage over k extracted host rows (pow2-padded),
    charge-able to the ledger and demotable to the disk tier like any
    other parked page."""
    from ..block import padded_size
    from ..exec.memory import SpilledPage

    cap = padded_size(max(int(k), 1))
    page = SpilledPage.__new__(SpilledPage)
    page.types = list(types_)
    page.dictionaries = list(dicts)
    page.cols = [_np_pad(c, cap) for c in cols]
    page.nulls = [_np_pad(n, cap, fill=True) for n in nulls]
    v = np.zeros(cap, dtype=bool)
    v[:k] = True
    page.valid = v
    return page


def _assemble_build_side(input_types, key_channels, cols, nulls, valid,
                         cap: int, dicts) -> BuildSide:
    """Canonicalize key codes, pick the key mode, normalize to u64 and
    sort: the tail of the build publish, shared by the resident index
    and each deferred cold-partition index (the hybrid join builds one
    per unspilled partition; the mode decision is type-static, so every
    partition encodes identically)."""
    kc = list(key_channels)
    cols = list(cols)
    # pooled keys (strings AND array/map/row composites) join on
    # dictionary CODES in the build's pool: the build side uses its
    # own codes as plain ints; the probe side remaps its codes into
    # this pool (LookupJoinOperator._remap), so both sides feed
    # _key_u64 the same integer key space.
    # CANONICALIZE build key codes first: aligned pools (derived by
    # transforms) may map one value to several codes, and
    # code-equality must mean value-equality for the join keys.
    # Canonical codes decode to the same values, so rewriting the
    # stored column is output-safe.
    for c in kc:
        if input_types[c].is_pooled:
            cols[c] = _canonical_codes(cols[c], dicts[c])
    key_types = [T.BIGINT if input_types[c].is_pooled
                 else input_types[c] for c in kc]
    mode = "single" if len(kc) == 1 else "hashed"
    if len(kc) == 2:
        # static decision — no device sync: pack two keys iff both
        # are provably 32-bit lanes (4-byte integer/bool storage, or
        # pooled codes, int32 by construction; sign-extension keeps
        # the low 32 bits injective). Floats are excluded: their
        # frexp encoding uses all 64 bits, so truncation would mass-
        # collide. The u64 key is only a bucketing function —
        # candidates are verified against raw keys — so a
        # conservative choice is safe either way.
        fits32 = [
            input_types[c].is_pooled
            or (t.storage is not None
                and np.dtype(t.storage).kind in "iub"
                and np.dtype(t.storage).itemsize <= 4)
            for c, t in zip(kc, key_types)]
        mode = "packed" if all(fits32) else "hashed"
    key, anynull = _key_u64([cols[c] for c in kc],
                            [nulls[c] for c in kc], key_types, mode)
    ks, us, vs, scols, snulls = _build_sorted(
        key, anynull if anynull is not None
        else jnp.zeros(cap, dtype=bool), tuple(cols), tuple(nulls),
        valid)
    return BuildSide(ks, us, vs, scols, snulls, list(input_types),
                     dicts, kc, mode)


def _build_side_from_spilled(input_types, key_channels,
                             pages: List) -> BuildSide:
    """One cold partition's sorted index from its parked pages: host
    concat (disk-parked pages stream back through serde.read_spill_file
    via host()), one upload, then the shared assembly tail."""
    from ..block import unify_dictionaries

    hosts = [p.host() for p in pages]
    cap = padded_size(sum(p.capacity for p in hosts))
    cols, nulls = [], []
    for i in range(len(input_types)):
        c = np.concatenate([p.cols[i] for p in hosts])
        n = np.concatenate([p.nulls[i] for p in hosts])
        cols.append(jnp.asarray(_np_pad(c, cap)))
        nulls.append(jnp.asarray(_np_pad(n, cap, fill=True)))
    v = np.concatenate([p.valid for p in hosts])
    valid = jnp.asarray(_np_pad(v, cap))
    dicts = unify_dictionaries(hosts, len(input_types))
    return _assemble_build_side(input_types, key_channels, cols, nulls,
                                valid, cap, dicts)


class HashBuilderOperator(Operator):
    """Accumulates the build side and publishes a sorted index."""

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], bridge: JoinBridge,
                 memory_context=None, dynamic_filters: Sequence = (),
                 hybrid: Optional[dict] = None):
        self.input_types = list(input_types)
        self.key_channels = list(key_channels)
        self.bridge = bridge
        # [(channel, DynamicFilter)] to fill at publish (reference:
        # DynamicFilterSourceOperator collecting build values)
        self.dynamic_filters = list(dynamic_filters)
        #: hybrid-hash-join options from the planner: {"fanout": session
        #: override (0=auto), "max_depth": recursion bound, "hint": the
        #: HBO spill record of this node's last run (sizes fan-out with
        #: source=hbo), or None when hybrid degradation is off (FULL
        #: OUTER, or disabled by session property)
        self._hybrid = hybrid
        self._hstate: Optional[HybridJoinState] = None
        #: parallel to _pages in partitioned mode: the partition id of
        #: each device page, or -1 for a not-yet-split mixed page
        self._page_pid: List[int] = []
        self._pages: List = []  # DevicePage | SpilledPage
        self._done = False
        self._ctx = memory_context
        if self._ctx is not None:
            self._ctx.set_revoke_callback(self._revoke)

    def add_input(self, page: DevicePage):
        if self._ctx is None:
            self._pages.append(page)
            return
        if self._hstate is not None:
            self._add_input_partitioned(page)
            return
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._pages, page)
        with self._ctx.lock:
            if self._hstate is not None:
                # the reserve above fired the FIRST revocation:
                # partitioned mode began mid-append, so _init_partitions
                # counted only the pages before this one — pair and
                # count this page now or the spill fraction overshoots
                # (and a later _split_mixed would drop the page)
                while len(self._page_pid) < len(self._pages):
                    self._page_pid.append(-1)
                self._hstate.count_build_rows(int(
                    np.count_nonzero(np.asarray(page.valid))))

    def _revoke(self) -> int:
        """Memory revocation (runs under the context lock, on whatever
        thread needed the bytes).  Hybrid path: enter partitioned mode
        on the first call and demote the LARGEST resident partition —
        the resident set shrinks IN PLACE and the query keeps building.
        Fallback (hybrid off / FULL OUTER): park everything in host RAM
        wholesale (the pre-hybrid CONSUMING_INPUT -> SPILLING_INPUT
        transition, with the disk tier below host RAM when the ledger
        overflows)."""
        from ..exec.memory import spill_pages

        if self._hybrid is None:
            return spill_pages(self._pages, self._ctx.pool,
                               self._ctx.lock)
        if self._hstate is None:
            self._init_partitions()
        return self._demote_next()

    # -- hybrid: partitioned build --------------------------------------

    def _init_partitions(self):
        """First revocation: decide the fan-out and enter partitioned
        mode.  Fan-out precedence: explicit session property, then the
        HBO spill hint of this node's previous run (source=hbo — the
        second run sizes fan-out right), then pool headroom vs bytes
        accumulated so far; always pow2 via KERNEL_SIZING."""
        from ..exec.memory import device_page_bytes
        from .kernel_sizing import KERNEL_SIZING

        opts = self._hybrid or {}
        hint = opts.get("hint") or {}
        if opts.get("fanout"):
            fanout, source = int(opts["fanout"]), "session"
        elif hint.get("fanout"):
            # size from the previous run's observed spill: a build that
            # spilled a meaningful fraction gets a finer fan-out so each
            # partition fits without recursion; one that barely spilled
            # keeps its grain
            fanout, source = int(hint["fanout"]), "hbo"
            frac = float(hint.get("fraction") or 0.0)
            if frac > 0.5:
                fanout *= 4
            elif frac > 0.125:
                fanout *= 2
            if int(hint.get("repartitions") or 0) > 0:
                fanout *= 2
        else:
            pool = self._ctx.pool
            dev_bytes = sum(device_page_bytes(p) for p in self._pages
                            if isinstance(p, DevicePage))
            # target: one partition should fit in ~1/4 of the pool; the
            # build is typically mid-stream when pressure hits, so the
            # seen bytes are doubled as the cardinality guess
            per_part = max(1, pool.max_bytes // 4)
            need = max(4, -(-dev_bytes * 2 // per_part))
            fanout = KERNEL_SIZING.suggest(
                ("hybrid_join_fanout", len(self.key_channels)),
                need, minimum=4)
            source = "local"
        fanout = max(2, min(int(fanout), 256))
        self._hstate = HybridJoinState(
            fanout, max_depth=int(opts.get("max_depth", 3)),
            source=source)
        # the probe's deferred per-partition passes charge their
        # transients (and spilled probe pages) to the build's context,
        # which stays open for the probe's lifetime via bridge.release
        self._hstate.ctx = self._ctx
        self.bridge.hybrid = self._hstate
        self._page_pid = [-1] * len(self._pages)
        self._hstate.count_build_rows(sum(
            int(np.count_nonzero(np.asarray(p.valid)))
            for p in self._pages))

    def _key_cols_host(self, cols, nulls, dicts):
        """(cols, nulls, types, dicts) of the key channels as host
        arrays, feeding HybridJoinState.partition_ids."""
        kc = self.key_channels
        return ([np.asarray(cols[c]) for c in kc],
                [np.asarray(nulls[c]) for c in kc],
                [self.input_types[c] for c in kc],
                [dicts[c] for c in kc])

    def _split_mixed(self):
        """Split every mixed (-1) page into per-partition pages: rows of
        resident partitions repack into one device page per partition
        present; rows of cold partitions park as SpilledPages (caller
        holds the context lock)."""
        from ..exec.memory import SpilledPage

        hs = self._hstate
        pages, pids = self._pages, self._page_pid
        if len(pids) < len(pages):
            # a page appended by a reserve whose own revocation rewrote
            # these lists has no pid yet — it is mixed by construction;
            # dropping it (the old zip truncation) lost build rows
            pids = pids + [-1] * (len(pages) - len(pids))
        out_pages: List = []
        out_pids: List[int] = []
        buckets: Dict[int, List[tuple]] = {}
        for pg, pid in zip(pages, pids):
            if pid != -1 or isinstance(pg, SpilledPage):
                out_pages.append(pg)
                out_pids.append(pid)
                continue
            cols = [np.asarray(c) for c in pg.cols]
            nulls = [np.asarray(n) for n in pg.nulls]
            valid = np.asarray(pg.valid)
            kcols, knulls, ktypes, kdicts = self._key_cols_host(
                cols, nulls, pg.dictionaries)
            rowpid = hs.partition_ids(kcols, knulls, ktypes, kdicts)
            for pid_ in np.unique(rowpid[valid]):
                pid_ = int(pid_)
                keep = np.nonzero(valid & (rowpid == pid_))[0]
                rows = ([c[keep] for c in cols],
                        [n[keep] for n in nulls], len(keep),
                        pg.dictionaries, pg.types)
                buckets.setdefault(pid_, []).append(rows)
        for pid_, parts in sorted(buckets.items()):
            cols = [np.concatenate([p[0][i] for p in parts])
                    for i in range(len(self.input_types))]
            nulls = [np.concatenate([p[1][i] for p in parts])
                     for i in range(len(self.input_types))]
            k = sum(p[2] for p in parts)
            sp = _host_spilled(parts[0][4], cols, nulls, k, parts[0][3])
            if pid_ in hs.resident:
                out_pages.append(sp.to_device())
                out_pids.append(pid_)
            else:
                self._park_spilled(pid_, sp, k, probe=False)
        self._pages[:] = out_pages
        self._page_pid[:] = out_pids

    def _park_spilled(self, pid: int, sp, rows: int, probe: bool):
        """Charge one cold-partition page to the host ledger and demote
        through the disk tier when the ledger overflows (caller holds
        the context lock)."""
        hs = self._hstate
        pool = self._ctx.pool
        if probe:
            hs.add_probe_spill(pid, sp)
            plist = hs.spilled_probe[pid]
        else:
            hs.route_build_spill(pid, sp, rows)
            plist = hs.spilled_build[pid]
        pool.host_ledger.charge(sp)
        pool.host_ledger.track(plist, self._ctx.lock, pool)
        pool.maybe_demote(plist)

    def _demote_next(self) -> int:
        """Demote resident partitions LARGEST-first until device bytes
        actually came free; returns the bytes freed (the partial-
        revocation contract: one demotion per loop round, repeated by
        revoke_up_to while more is needed).  Caller holds the context
        lock."""
        from ..exec.memory import SpilledPage, device_page_bytes

        hs = self._hstate
        before = sum(device_page_bytes(p) for p in self._pages
                     if isinstance(p, DevicePage))
        self._split_mixed()
        pool = self._ctx.pool
        freed_any = False
        while True:
            sizes: Dict[int, int] = {}
            for pg, pid in zip(self._pages, self._page_pid):
                if pid >= 0 and pid in hs.resident \
                        and isinstance(pg, DevicePage):
                    sizes[pid] = sizes.get(pid, 0) \
                        + device_page_bytes(pg)
            after = sum(device_page_bytes(p) for p in self._pages
                        if isinstance(p, DevicePage))
            if before - after > 0 and freed_any:
                break
            if not sizes:
                break
            victim = max(sizes, key=lambda p: sizes[p])
            vpages, vrows = [], 0
            keep_pages, keep_pids = [], []
            for pg, pid in zip(self._pages, self._page_pid):
                if pid == victim and isinstance(pg, DevicePage):
                    sp = SpilledPage(pg)
                    vrows += int(np.count_nonzero(sp.valid))
                    vpages.append(sp)
                else:
                    keep_pages.append(pg)
                    keep_pids.append(pid)
            self._pages[:] = keep_pages
            self._page_pid[:] = keep_pids
            hs.demote(victim, vpages, vrows)
            for sp in vpages:
                pool.host_ledger.charge(sp)
            pool.host_ledger.track(hs.spilled_build[victim],
                                   self._ctx.lock, pool)
            pool.maybe_demote(hs.spilled_build[victim])
            pool.record_partition_spill(sizes[victim], 1)
            freed_any = True
        after = sum(device_page_bytes(p) for p in self._pages
                    if isinstance(p, DevicePage))
        return max(before - after, 0)

    def _add_input_partitioned(self, page: DevicePage):
        """Partitioned-mode input routing: resident-partition rows stay
        on device (one compacted page), cold-partition rows park
        directly beside their partition — page-at-a-time, never
        resident."""
        from ..exec.memory import device_page_bytes

        hs = self._hstate
        cols = [np.asarray(c) for c in page.cols]
        nulls = [np.asarray(n) for n in page.nulls]
        valid = np.asarray(page.valid)
        kcols, knulls, ktypes, kdicts = self._key_cols_host(
            cols, nulls, page.dictionaries)
        rowpid = hs.partition_ids(kcols, knulls, ktypes, kdicts)
        hs.count_build_rows(int(np.count_nonzero(valid)))
        with hs._lock:
            resident = hs.resident
        cold_pids = [int(p) for p in np.unique(rowpid[valid])
                     if int(p) not in resident]
        if not cold_pids:
            self.add_input_resident(page)
            return
        cold_rows = np.isin(rowpid, np.asarray(cold_pids))
        res_valid = valid & ~cold_rows
        dev = None
        if res_valid.any():
            sp = _host_spilled(
                page.types, [c[res_valid] for c in cols],
                [n[res_valid] for n in nulls],
                int(np.count_nonzero(res_valid)), page.dictionaries)
            dev = sp.to_device()
            self._ctx.reserve(device_page_bytes(dev))
        with self._ctx.lock:
            if dev is not None:
                self._pages.append(dev)
                self._page_pid.append(-1)
            for pid_ in cold_pids:
                keep = np.nonzero(valid & (rowpid == pid_))[0]
                sp = _host_spilled(
                    page.types, [c[keep] for c in cols],
                    [n[keep] for n in nulls], len(keep),
                    page.dictionaries)
                self._park_spilled(pid_, sp, len(keep), probe=False)

    def add_input_resident(self, page: DevicePage):
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._pages, page)
        with self._ctx.lock:
            # the reserve above may have revoked: _split_mixed rewrites
            # both lists to arbitrary lengths, so resync rather than
            # compare against a pre-reserve snapshot (unpaired pages
            # are always trailing appends, mixed by construction)
            while len(self._page_pid) < len(self._pages):
                self._page_pid.append(-1)

    def metrics(self) -> dict:
        hs = self._hstate
        if hs is None:
            return {}
        with hs._lock:
            return {"hybrid_spill": {
                "fanout": hs.fanout,
                "source": hs.source,
                "fraction": round(hs.spilled_build_rows
                                  / max(1, hs.total_build_rows), 4),
                "partitions_spilled": len(hs.spilled_build),
                "demotions": hs.demotions,
                "repartitions": hs.repartitions,
                "max_depth": hs.max_depth_seen,
            }}

    def get_output(self):
        if self._finishing and not self._done:
            self._publish()
            self._done = True
        return None

    def _publish(self):
        from ..exec.memory import SpilledPage, device_page_bytes

        if self._ctx is not None and self._hybrid is not None:
            # publish owns the state; hybrid path: when the index +
            # its concat/sort transients do not fit the pool, shrink
            # the RESIDENT SET instead of parking the whole build —
            # demoted partitions move to the probe's deferred
            # per-partition passes, so the published index covers
            # exactly what fits
            from ..exec.memory import MemoryExceededError

            with self._ctx.lock:
                self._ctx.set_revoke_callback(None)
                if self._hstate is not None \
                        and self._hstate.spilled_build:
                    # straggler mixed pages: a page appended by the very
                    # reserve call whose revocation demoted a partition
                    # still carries that partition's rows under pid -1.
                    # Route them now — a cold row baked into the
                    # resident index would never be probed (its probe
                    # rows all park for the deferred pass, which reads
                    # only spilled_build).
                    self._split_mixed()

            def _demote_once() -> int:
                with self._ctx.lock:
                    if self._hstate is None:
                        self._init_partitions()
                    freed = self._demote_next()
                if freed > 0:
                    self._ctx.pool.record_spill(freed)
                    self._ctx.free(freed)
                return freed

            budget = max(1, self._ctx.pool.max_bytes // 4)
            while True:
                total = sum(device_page_bytes(p) for p in self._pages)
                uploads = sum(device_page_bytes(p) for p in self._pages
                              if isinstance(p, SpilledPage))
                if total > budget and _demote_once() > 0:
                    # the RETAINED index must leave headroom for the
                    # probe and everything downstream — same 1/4-pool
                    # target the fan-out sizing uses
                    continue
                try:
                    self._ctx.reserve(uploads + 2 * total,
                                      revocable=False)
                    break
                except MemoryExceededError:
                    if _demote_once() <= 0:
                        raise
        elif self._ctx is not None:
            # publish owns the state; the build index it creates is
            # retained (non-revocable) for the probe's lifetime
            from ..exec.memory import prepare_finish

            total, uploads = prepare_finish(self._ctx, self._pages)
            all_spilled = bool(self._pages) and all(
                isinstance(p, SpilledPage) for p in self._pages)
            # transient: concat + sorted copy, plus per-page re-uploads
            # on the mixed path (the all-spilled path concatenates in
            # host RAM and uploads once — no per-page residency)
            self._ctx.reserve((2 * total if all_spilled
                               else uploads + 2 * total), revocable=False)
        if self._pages:
            spilled = [p for p in self._pages if isinstance(p, SpilledPage)]
            if spilled and len(spilled) == len(self._pages):
                # pressure path: concatenate in host RAM, upload once
                # (host() loads disk-parked pages back into RAM first)
                hosts = [p.host() for p in self._pages]
                cap = padded_size(sum(p.capacity for p in hosts))
                cols, nulls = [], []
                nch = len(self.input_types)
                for i in range(nch):
                    c = np.concatenate([p.cols[i] for p in hosts])
                    n = np.concatenate([p.nulls[i] for p in hosts])
                    cols.append(jnp.asarray(_np_pad(c, cap)))
                    nulls.append(jnp.asarray(_np_pad(n, cap, fill=True)))
                v = np.concatenate([p.valid for p in hosts])
                valid = jnp.asarray(_np_pad(v, cap))
                dicts = self._unified_dicts(hosts)
            else:
                pages = [p.to_device() if isinstance(p, SpilledPage) else p
                         for p in self._pages]
                cap = padded_size(sum(p.capacity for p in pages))
                cols, nulls = [], []
                nch = len(self.input_types)
                for i in range(nch):
                    cols.append(_pad_concat([p.cols[i] for p in pages], cap))
                    nulls.append(_pad_concat([p.nulls[i] for p in pages],
                                             cap, fill=True))
                valid = _pad_concat([p.valid for p in pages], cap)
                dicts = self._unified_dicts(pages)
        else:
            from ..block import Dictionary

            cap = 16
            cols = [jnp.zeros(cap, dtype=t.storage) for t in self.input_types]
            nulls = [jnp.ones(cap, dtype=bool) for _ in self.input_types]
            valid = jnp.zeros(cap, dtype=bool)
            dicts = [Dictionary() if t.is_pooled else None
                     for t in self.input_types]
        self._collect_dynamic_filters(cols, nulls, valid)
        self.bridge.set_build(_assemble_build_side(
            self.input_types, self.key_channels, cols, nulls, valid,
            cap, dicts))
        self._pages = []  # release the input pages; only the index remains
        if self._ctx is not None:
            # retain only the published index: sorted key (8B) + usable
            # + valid (1B each) + per-channel data/null lanes
            retained = cap * (10 + sum(c.dtype.itemsize + 1 for c in cols))
            self._ctx.close()
            self._ctx.reserve(retained, revocable=False)
            self.bridge.release = self._ctx.close

    def _collect_dynamic_filters(self, cols, nulls, valid):
        """Fill the join's dynamic filters over ALL build rows — the
        resident arrays plus every cold-partition page: a filter built
        from the resident set alone would wrongly prune probe rows that
        match only spilled build rows."""
        if not self.dynamic_filters:
            return
        hs = self._hstate
        spilled = []
        if hs is not None:
            with hs._lock:
                spilled = [p for ps in hs.spilled_build.values()
                           for p in ps]
        if not spilled:
            for ch, df in self.dynamic_filters:
                df.collect(cols[ch], nulls[ch], valid)
            return
        hosts = [p.host() for p in spilled]
        sv = np.concatenate([np.asarray(valid)]
                            + [h.valid for h in hosts])
        for ch, df in self.dynamic_filters:
            c = np.concatenate([np.asarray(cols[ch])]
                               + [h.cols[ch] for h in hosts])
            n = np.concatenate([np.asarray(nulls[ch])]
                               + [h.nulls[ch] for h in hosts])
            df.collect(c, n, sv)

    def _unified_dicts(self, pages):
        from ..block import unify_dictionaries

        return unify_dictionaries(pages, len(self.input_types))

    def is_finished(self) -> bool:
        return self._done


class LookupJoinOperator(Operator):
    """Probe side. join_type: inner | left | full | semi | anti.

    Output layout: all probe channels, then (inner/left/full) all build
    channels — build channels NULL on unmatched left rows. semi/anti emit
    probe channels only. FULL OUTER additionally OR-accumulates a
    matched flag per (sorted) build row across all probe pages and, once
    the probe side finishes, emits one final page of unmatched build rows
    with NULL probe channels (reference: LookupJoinOperator's
    OuterLookupSource / buildOuter position iterator,
    operator/join/LookupJoinOperator.java:36)."""

    #: bound on candidate-expansion lanes per kernel launch: a probe page
    #: whose total match count pads beyond this is sliced into contiguous
    #: row chunks (greedy, from the per-row counts pulled to host ONCE)
    #: and joined one chunk per driver quantum, so skewed or high-fanout
    #: joins never materialize all pairs — neither in one buffer nor as a
    #: backlog of pending output pages (round-2 verdict: unbounded
    #: _expand_matches blows HBM at scale)
    max_lanes = 1 << 20

    #: probe pages whose guessed-capacity outputs are enqueued on device
    #: but not yet overflow-checked. The oldest is checked — ONE scalar
    #: read, computed pipeline_depth-1 pages ago and thus long since
    #: done — only when the pipeline is full or upstream stalls, so the
    #: host never blocks on kernels it just enqueued (round-3 verdict:
    #: int(jnp.sum(count)) serialized host and device per probe page)
    pipeline_depth = 4

    def __init__(self, probe_types: Sequence[T.Type],
                 probe_key_channels: Sequence[int], bridge: JoinBridge,
                 join_type: str = "inner",
                 filter_fn=None, max_lanes: Optional[int] = None,
                 memory_limited: bool = False):
        assert join_type in ("inner", "left", "full", "semi", "anti")
        self.probe_types = list(probe_types)
        self.probe_keys = list(probe_key_channels)
        self.bridge = bridge
        self.join_type = join_type
        self.filter_fn = filter_fn  # optional post-join residual filter
        if max_lanes is not None:
            self.max_lanes = max_lanes
        if memory_limited:
            # pool-governed query: the pending buffers are invisible to
            # the memory manager's reserve/revoke machinery, so keep the
            # pre-pipelining one-page-in-flight footprint
            self.pipeline_depth = 1
        self._pending: List[dict] = []   # awaiting overflow check
        self._ready: List[DevicePage] = []
        # EWMA lanes-per-probe-row for the capacity guess. Starts below
        # 1 so the first guess lands in the page's own pow2 bucket (N:1
        # joins then never overflow and never double the page); a
        # fan-out join overflows once, the ratio learns, later pages
        # guess right. pow2 padding gives the headroom.
        self._ratio = 0.75
        self._added_since_get = False
        self._done = False
        #: deferred cold-partition work queue (hybrid join): None until
        #: the probe input finished, then [{"depth", "build", "probe"}]
        #: processed one partition per get_output call
        self._deferred: Optional[List[dict]] = None
        # FULL OUTER state: per-sorted-build-row matched flag (device,
        # cap+1 lanes — the last is the dead-lane sink) + the dictionary
        # pools of the last probe page (the unmatched-build page's probe
        # channels are all-NULL, but string channels still need a pool)
        self._build_matched = None
        self._probe_dicts = None
        self._emitted_unmatched = False
        # probe-dict -> build-dict code remap LUTs for pooled join keys
        self._remap_cache: dict = {}

    @property
    def output_types(self) -> List[T.Type]:
        b = self.bridge.build
        if self.join_type in ("semi", "anti"):
            return list(self.probe_types)
        return list(self.probe_types) + list(b.types)

    def needs_input(self) -> bool:
        return (not self._ready
                and len(self._pending) < self.pipeline_depth
                and not self._finishing)

    def add_input(self, page: DevicePage):
        """Enqueue the whole probe chain for this page — counts,
        guessed-capacity expansion, finalize — WITHOUT reading anything
        back; the overflow check happens in get_output once the
        pipeline is deep enough to have hidden this page's latency."""
        b = self.bridge.build
        assert b is not None, "probe started before build finished"
        hs = self.bridge.hybrid
        if hs is not None and hs.spilled_build:
            # hybrid join: rows of cold build partitions park beside
            # their partition for the deferred unspill->probe pass;
            # null-key rows always stay resident (they match nothing
            # and LEFT/ANTI must emit them exactly once)
            page = self._route_probe(page, hs)
            if page is None:
                self._added_since_get = True
                return
        kc = self.probe_keys
        pkey_cols, key_types = self._probe_key_cols(page, b)
        pkey, panynull = _key_u64(pkey_cols,
                                  [page.nulls[c] for c in kc],
                                  key_types, b.key_mode)
        pusable = page.valid & ~panynull if panynull is not None \
            else page.valid
        direct = self._probe_direct(page, b, pkey, pusable)
        if direct is not None:
            self._ready.append(direct)
            self._added_since_get = True
            return
        lo, count = self._probe_lo_count(b, pkey, pusable)
        rows = int(page.valid.shape[0])
        cap = padded_size(max(16, int(rows * self._ratio * 1.1)))
        while cap > self.max_lanes and cap > 16:
            cap >>= 1  # budget is checked POST-padding, like every path
        out, keep, bidx = self._make_out(b, page, pkey_cols, pusable, lo,
                                         count, cap)
        self._pending.append({
            "b": b,
            "page": page, "pkey_cols": pkey_cols, "pusable": pusable,
            "lo": lo, "count": count, "rows": rows, "cap": cap,
            "total": jnp.sum(count), "out": out, "keep": keep,
            "bidx": bidx})
        self._added_since_get = True

    def _route_probe(self, page: DevicePage,
                     hs: HybridJoinState) -> Optional[DevicePage]:
        """Split one probe page by build partition: cold-partition rows
        spill beside their build partition, the rest probe the resident
        index now (valid-mask restriction — each probe row joins in
        exactly one pass)."""
        kc = self.probe_keys
        kcols = [np.asarray(page.cols[c]) for c in kc]
        knulls = [np.asarray(page.nulls[c]) for c in kc]
        ktypes = [self.probe_types[c] for c in kc]
        kdicts = [page.dictionaries[c] for c in kc]
        valid = np.asarray(page.valid)
        anynull = np.zeros_like(valid)
        for nl in knulls:
            anynull |= nl
        rowpid = hs.partition_ids(kcols, knulls, ktypes, kdicts)
        with hs._lock:
            cold_pids = np.fromiter(hs.spilled_build, dtype=np.int64)
        cold = valid & ~anynull & np.isin(rowpid, cold_pids)
        if not cold.any():
            return page
        hcols = [np.asarray(c) for c in page.cols]
        hnulls = [np.asarray(n) for n in page.nulls]
        ctx = hs.ctx
        for pid_ in np.unique(rowpid[cold]):
            pid_ = int(pid_)
            keep = np.nonzero(cold & (rowpid == pid_))[0]
            sp = _host_spilled(page.types, [c[keep] for c in hcols],
                               [n[keep] for n in hnulls], len(keep),
                               page.dictionaries)
            hs.add_probe_spill(pid_, sp)
            if ctx is not None:
                pool = ctx.pool
                pool.host_ledger.charge(sp)
                with ctx.lock:
                    pool.host_ledger.track(hs.spilled_probe[pid_],
                                           ctx.lock, pool)
                    pool.maybe_demote(hs.spilled_probe[pid_])
        res_valid = valid & ~cold
        if not res_valid.any():
            return None
        return DevicePage(page.types, page.cols, page.nulls,
                          jnp.asarray(res_valid), page.dictionaries)

    def _probe_direct(self, page: DevicePage, b: "BuildSide", pkey,
                      pusable):
        """Strategy seam: a complete output page computed straight from
        the probe keys (no candidate expansion), or None to run the
        lo/count path below.  The matmul strategy
        (``ops/matmul_join.py``) answers semi/anti membership here."""
        return None

    def _probe_lo_count(self, b: "BuildSide", pkey, pusable):
        """Strategy seam: each probe row's candidate range (lo, count)
        against the sorted build index — here two XLA-native vectorized
        binary searches; the matmul strategy overrides with the blocked
        one-hot matmul probe."""
        return _probe_counts(b.key_sorted, b.usable_sorted, pkey,
                             pusable)

    def get_output(self):
        if self._ready:
            return self._ready.pop(0)
        if self._pending and (self._finishing
                              or len(self._pending) >= self.pipeline_depth
                              or not self._added_since_get):
            self._verify_oldest()
            self._added_since_get = False
            if self._ready:
                return self._ready.pop(0)
        self._added_since_get = False
        if self._finishing and not self._pending:
            hs = self.bridge.hybrid
            if hs is not None and self._deferred is None:
                self._init_deferred(hs)
            while self._deferred and not self._ready:
                self._advance_deferred(hs)
            if self._ready:
                return self._ready.pop(0)
            if self.join_type == "full" and not self._emitted_unmatched:
                self._emitted_unmatched = True
                return self._unmatched_build_page()
            if not self._done:
                self.bridge.destroy()
            self._done = True
        return None

    def _verify_oldest(self):
        """Overflow-check the oldest pending page: the deferred scalar
        read. Fits the guess (common) -> emit as-is; overflowed (rare)
        -> re-expand at the now-known exact size, chunked under the
        lane budget."""
        rec = self._pending.pop(0)
        tot = int(rec["total"])
        self._ratio = 0.75 * self._ratio \
            + 0.25 * (tot / max(rec["rows"], 1))
        if tot <= rec["cap"]:
            self._mark_full(rec["keep"], rec["bidx"],
                            rec["page"].dictionaries)
            self._ready.append(rec["out"])
            return
        for unit in self._chunk_units(rec, tot):
            out, keep, bidx = self._make_out(rec["b"], *unit)
            self._mark_full(keep, bidx, rec["page"].dictionaries)
            self._ready.append(out)

    def _chunk_units(self, rec: dict, total: int) -> List:
        """(page, pkey_cols, pusable, lo, count, lane_cap) units whose
        expansions fit the lane budget; greedy contiguous row chunks
        from the per-row counts (host copy only on this over-budget
        path). A single row exceeding the budget still becomes its own
        unit: out_cap grows to its fan-out, which no slicing avoids."""
        page, pkey_cols, pusable = rec["page"], rec["pkey_cols"], \
            rec["pusable"]
        lo, count = rec["lo"], rec["count"]
        if padded_size(max(total, 16)) <= self.max_lanes:
            return [(page, pkey_cols, pusable, lo, count,
                     padded_size(max(total, 16)))]
        counts = np.asarray(count)
        units: List = []
        n = counts.shape[0]
        i = 0
        while i < n:
            j = i
            run = 0
            while j < n and (j == i or
                             padded_size(max(run + int(counts[j]), 16))
                             <= self.max_lanes):
                run += int(counts[j])
                j += 1
            cap = padded_size(j - i)
            sl = slice(i, j)
            sub = DevicePage(page.types,
                             [_pad_dev(c[sl], cap) for c in page.cols],
                             [_pad_dev(x[sl], cap) for x in page.nulls],
                             _pad_dev(page.valid[sl], cap),
                             page.dictionaries)
            units.append((sub, [_pad_dev(k[sl], cap) for k in pkey_cols],
                          _pad_dev(pusable[sl], cap),
                          _pad_dev(lo[sl], cap), _pad_dev(count[sl], cap),
                          padded_size(max(run, 16))))
            i = j
        return units

    # -- hybrid: deferred cold-partition passes --------------------------

    def _init_deferred(self, hs: HybridJoinState):
        """Snapshot the cold-partition work queue once the probe input
        finished (the resident set is frozen after build publish, so
        the snapshot is race-free)."""
        with hs._lock:
            pids = sorted(set(hs.spilled_build) | set(hs.spilled_probe))
            self._deferred = [
                {"depth": hs.depth,
                 "build": list(hs.spilled_build.get(pid, ())),
                 "probe": list(hs.spilled_probe.get(pid, ()))}
                for pid in pids]

    def _advance_deferred(self, hs: HybridJoinState):
        """Unspill one cold partition and probe it: build a
        per-partition sorted index from the parked build pages, then
        run every parked probe page against it.  A partition whose
        index would not fit the pool repartitions with a depth-salted
        hash instead (children joined depth-first, recursion bounded
        by hybrid_join_max_depth)."""
        from ..exec.memory import MemoryExceededError, device_page_bytes

        entry = self._deferred.pop(0)
        if not entry["probe"]:
            # probe-driven join types only (FULL OUTER never goes
            # hybrid): no parked probe rows means no output
            return
        ctx = hs.ctx
        est = sum(device_page_bytes(p) for p in entry["build"])
        # index + sort transients ~4x the partition bytes; an oversized
        # partition repartitions rather than thrash the pool
        need = 4 * max(est, 1)
        if ctx is not None and entry["depth"] < hs.max_depth \
                and need > ctx.pool.max_bytes:
            self._split_deferred(hs, entry)
            return
        if ctx is not None:
            try:
                ctx.reserve(need, revocable=False)
            except MemoryExceededError:
                if entry["depth"] < hs.max_depth:
                    self._split_deferred(hs, entry)
                    return
                raise
        try:
            b = self.bridge.build
            bp = _build_side_from_spilled(
                b.types, b.key_channels, entry["build"]) \
                if entry["build"] else self._empty_build_side(b)
            for sp in entry["probe"]:
                self._probe_spilled_page(bp, sp)
        finally:
            if ctx is not None:
                ctx.free(need, revocable=False)

    def _split_deferred(self, hs: HybridJoinState, entry: dict):
        """Recursive repartition: re-hash the partition's build AND
        probe pages at depth+1 with a fresh salt; children go to the
        FRONT of the queue (depth-first keeps the parked-page peak
        bounded by one partition's lineage)."""
        depth = entry["depth"] + 1
        hs.note_depth(depth)
        salt = _salt_for_depth(depth)
        sub_fanout = 4  # quarters per level: depth 3 = 64x the fan-out
        b = self.bridge.build
        bsplit = self._split_spilled(hs, entry["build"], b.types,
                                     b.key_channels, salt, sub_fanout)
        psplit = self._split_spilled(hs, entry["probe"],
                                     self.probe_types, self.probe_keys,
                                     salt, sub_fanout)
        for q in sorted(set(bsplit) | set(psplit), reverse=True):
            self._deferred.insert(0, {
                "depth": depth,
                "build": bsplit.get(q, []),
                "probe": psplit.get(q, [])})

    def _split_spilled(self, hs: HybridJoinState, pages: List, types_,
                       key_channels, salt: int, fanout: int) -> dict:
        """Partition parked pages by a re-salted key hash (host work;
        disk-parked pages stream back through host())."""
        buckets: dict = {}
        for p in pages:
            h = p.host()
            kcols = [h.cols[c] for c in key_channels]
            knulls = [h.nulls[c] for c in key_channels]
            ktypes = [types_[c] for c in key_channels]
            kdicts = [h.dictionaries[c] for c in key_channels]
            rowpid = hs.partition_ids(kcols, knulls, ktypes, kdicts,
                                      salt=salt, fanout=fanout)
            for q in np.unique(rowpid[h.valid]):
                q = int(q)
                keep = np.nonzero(h.valid & (rowpid == q))[0]
                buckets.setdefault(q, []).append(_host_spilled(
                    h.types, [c[keep] for c in h.cols],
                    [n[keep] for n in h.nulls], len(keep),
                    h.dictionaries))
        return buckets

    def _empty_build_side(self, b: "BuildSide") -> "BuildSide":
        """A zero-row index (recursive splits can leave a probe-only
        sub-bucket; LEFT/ANTI must still emit its rows unmatched)."""
        from ..block import Dictionary

        cap = 16
        cols = [jnp.zeros(cap, dtype=t.storage) for t in b.types]
        nulls = [jnp.ones(cap, dtype=bool) for _ in b.types]
        valid = jnp.zeros(cap, dtype=bool)
        dicts = [Dictionary() if t.is_pooled else None for t in b.types]
        return _assemble_build_side(b.types, b.key_channels, cols,
                                    nulls, valid, cap, dicts)

    def _probe_spilled_page(self, b: "BuildSide", sp):
        """One parked probe page against one per-partition index —
        straight through the base sorted-index kernels.  The strategy
        seams (_probe_direct/_probe_lo_count) are deliberately
        bypassed: the matmul strategy caches ONE table from the
        resident build side and must not see per-partition indexes."""
        page = sp.to_device()
        kc = self.probe_keys
        pkey_cols, key_types = self._probe_key_cols(page, b)
        pkey, panynull = _key_u64(pkey_cols,
                                  [page.nulls[c] for c in kc],
                                  key_types, b.key_mode)
        pusable = page.valid & ~panynull if panynull is not None \
            else page.valid
        lo, count = _probe_counts(b.key_sorted, b.usable_sorted, pkey,
                                  pusable)
        tot = int(jnp.sum(count))
        rec = {"b": b, "page": page, "pkey_cols": pkey_cols,
               "pusable": pusable, "lo": lo, "count": count}
        for unit in self._chunk_units(rec, tot):
            out, keep, bidx = self._make_out(b, *unit)
            self._ready.append(out)

    def _mark_full(self, keep, build_idx, pdicts):
        """FULL OUTER bookkeeping, applied only AFTER the overflow check
        passed (a truncated expansion must not mark build rows)."""
        if self.join_type != "full" or keep is None:
            return
        b = self.bridge.build
        bcap = int(b.valid_sorted.shape[0])
        if self._build_matched is None:
            self._build_matched = jnp.zeros(bcap + 1, dtype=bool)
        self._build_matched = _mark_build_matched(
            self._build_matched, keep, build_idx)
        self._probe_dicts = pdicts

    def _unmatched_build_page(self) -> DevicePage:
        """FULL OUTER tail: build rows no kept lane ever matched, with
        all probe channels NULL."""
        from ..block import Dictionary

        b = self.bridge.build
        cap = int(b.valid_sorted.shape[0])
        unmatched = b.valid_sorted if self._build_matched is None \
            else b.valid_sorted & ~self._build_matched[:cap]
        pcols = [jnp.zeros(cap, dtype=t.storage) for t in self.probe_types]
        pnulls = [jnp.ones(cap, dtype=bool) for _ in self.probe_types]
        pdicts = self._probe_dicts
        if pdicts is None:
            pdicts = [Dictionary() if t.is_pooled else None
                      for t in self.probe_types]
        return DevicePage(self.output_types, pcols + list(b.cols),
                          pnulls + list(b.nulls), unmatched,
                          list(pdicts) + list(b.dictionaries))

    def is_finished(self) -> bool:
        return self._done

    def _remap(self, probe_dict, build_dict):
        """Probe-pool code -> build-pool code LUT (-1 = absent, matches
        nothing; always canonical first-occurrence codes, so aligned
        pools with duplicate values compare correctly). Host work once
        per (probe pool, build pool) pair; the gather runs on device.
        The cache entry pins both dict objects: bare id() keys would go
        stale if a pool were GC'd and its address reused."""
        key = (id(probe_dict), len(probe_dict) if probe_dict else 0,
               id(build_dict), len(build_dict) if build_dict else 0)
        hit = self._remap_cache.get(key)
        if hit is not None:
            return hit[0]
        if build_dict is None:
            lut = np.full(max(1, len(probe_dict or ())), -1,
                          dtype=np.int64)
        else:
            lut = np.fromiter(
                (build_dict.lookup(v) for v in probe_dict.values),
                dtype=np.int64,
                count=len(probe_dict)) if probe_dict and \
                len(probe_dict) else np.full(1, -1, dtype=np.int64)
        lut = jnp.asarray(lut)
        if len(self._remap_cache) >= 128:  # evict BEFORE inserting
            self._remap_cache.clear()
        self._remap_cache[key] = (lut, probe_dict, build_dict)
        return lut

    def _probe_key_cols(self, page: DevicePage, b: "BuildSide"):
        """Per key channel: the probe column transformed into the build's
        key space (identity for unpooled types; canonical code remap for
        pooled keys — also when pools are shared, since an aligned pool
        may hold duplicate values under distinct codes)."""
        out = []
        types_ = []
        for i, c in enumerate(self.probe_keys):
            t = self.probe_types[c]
            if t.is_pooled:
                pd = page.dictionaries[c]
                bd = b.dictionaries[b.key_channels[i]]
                out.append(self._remap(pd, bd)[page.cols[c]])
                types_.append(T.BIGINT)
            else:
                out.append(page.cols[c])
                types_.append(t)
        return out, types_

    def _make_out(self, b: "BuildSide", page: DevicePage, pkey_cols,
                  pusable, lo, count, lane_cap: int) -> Tuple:
        """One expansion at static capacity ``lane_cap`` against build
        side ``b`` (the resident index, or a per-partition index during
        the deferred hybrid pass): returns (out_page, keep, build_idx).
        keep/build_idx feed the FULL OUTER marker — applied by the
        caller only after the overflow check — and are None for
        semi/anti (no build channels in the output)."""
        if self.join_type in ("semi", "anti"):
            if self.filter_fn is None:
                matched = _semi_matched(
                    lo, count,
                    tuple(pkey_cols),
                    tuple(b.cols[c] for c in b.key_channels),
                    page.valid.shape[0], out_cap=lane_cap)
            else:
                # residual-filtered semi/anti (q21's l3.l_suppkey <>
                # l1.l_suppkey): expand candidate lanes, verify keys,
                # evaluate the filter over the combined probe+build row,
                # then segment-OR back onto probe rows
                probe_idx, build_idx, keep = _expand_verified(
                    lo, count,
                    tuple(pkey_cols),
                    tuple(b.cols[c] for c in b.key_channels),
                    out_cap=lane_cap)
                lanes = _gather_lanes(page, b, probe_idx, build_idx, keep)
                matched = _segment_any(self.filter_fn(lanes).valid,
                                       probe_idx, page.valid.shape[0])
            if self.join_type == "semi":
                new_valid = page.valid & matched
            else:
                new_valid = page.valid & ~matched
            return (DevicePage(page.types, page.cols, page.nulls,
                               new_valid, page.dictionaries), None, None)

        probe_idx, build_idx, keep = _expand_verified(
            lo, count,
            tuple(pkey_cols),
            tuple(b.cols[c] for c in b.key_channels), out_cap=lane_cap)
        if self.filter_fn is not None:
            # ON-clause residual runs BEFORE left-join padding: lanes
            # failing it make the probe row unmatched, not dropped
            lanes = _gather_lanes(page, b, probe_idx, build_idx, keep)
            keep = self.filter_fn(lanes).valid
        out_cols, out_nulls, out_valid = _finalize_join(
            tuple(page.cols), tuple(page.nulls), page.valid,
            tuple(b.cols), tuple(b.nulls),
            probe_idx, build_idx, keep,
            left=self.join_type in ("left", "full"))
        types = self.output_types
        dicts = list(page.dictionaries) + list(b.dictionaries)
        return (DevicePage(types, list(out_cols), list(out_nulls),
                           out_valid, dicts), keep, build_idx)


def _finalize_join_impl(pcols, pnulls, pvalid, bcols, bnulls,
                        probe_idx, build_idx, keep, left: bool):
    """Gather joined output lanes; for LEFT, append one lane per probe
    row, valid iff the row matched no kept lane (NULL build columns).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_finalize_join`` binding below."""
    lane_cap = probe_idx.shape[0]
    if left:
        matched = _segment_any_impl(keep, probe_idx, pvalid.shape[0])
        n_extra = pvalid.shape[0]
        extra_probe = jnp.arange(n_extra, dtype=probe_idx.dtype)
        probe_idx = jnp.concatenate([probe_idx, extra_probe])
        build_idx = jnp.concatenate(
            [build_idx, jnp.zeros(n_extra, dtype=build_idx.dtype)])
        keep = jnp.concatenate([keep, pvalid & ~matched])
        build_is_null = jnp.concatenate(
            [jnp.zeros(lane_cap, dtype=bool),
             jnp.ones(n_extra, dtype=bool)])
    else:
        build_is_null = jnp.zeros(lane_cap, dtype=bool)

    out_cols = tuple(c[probe_idx] for c in pcols) + \
        tuple(c[build_idx] for c in bcols)
    out_nulls = tuple(n[probe_idx] for n in pnulls) + \
        tuple(n[build_idx] | build_is_null for n in bnulls)
    return out_cols, out_nulls, keep


_finalize_join = partial(jax.jit, static_argnames=("left",))(
    _finalize_join_impl)


def _gather_lanes(page: DevicePage, b: "BuildSide", probe_idx, build_idx,
                  keep) -> DevicePage:
    """Combined probe+build rows for candidate lanes (residual-filter
    evaluation layout: probe channels, then build channels)."""
    return DevicePage(
        list(page.types) + list(b.types),
        [c[probe_idx] for c in page.cols]
        + [c[build_idx] for c in b.cols],
        [n[probe_idx] for n in page.nulls]
        + [n[build_idx] for n in b.nulls],
        keep,
        list(page.dictionaries) + list(b.dictionaries))


def _expand_verified_impl(lo, count, pkey_cols, bkey_cols, out_cap: int):
    """Candidate lanes with raw-key verification applied (for
    residual-filtered semi/anti joins).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_expand_verified`` binding below."""
    probe_idx, build_idx, lane_valid = _expand_matches_impl(
        lo, count, out_cap)
    keep = lane_valid
    for pc, bc in zip(pkey_cols, bkey_cols):
        keep = keep & (pc[probe_idx] == bc[build_idx])
    return probe_idx, build_idx, keep


_expand_verified = partial(jax.jit, static_argnames=("out_cap",))(
    _expand_verified_impl)


@jax.jit
def _mark_build_matched(acc, keep, build_idx):
    """OR kept lanes into the per-sorted-build-row matched accumulator
    (last lane of ``acc`` is the dead-lane sink)."""
    sink = acc.shape[0] - 1
    return acc.at[jnp.where(keep, build_idx, sink)].max(True)


def _segment_any_impl(keep, probe_idx, probe_cap: int):
    """OR of ``keep`` lanes per probe row."""
    matched = jnp.zeros(probe_cap + 1, dtype=bool)
    matched = matched.at[jnp.where(keep, probe_idx, probe_cap)].max(True)
    return matched[:-1]


_segment_any = partial(jax.jit, static_argnames=("probe_cap",))(
    _segment_any_impl)


def _semi_matched_impl(lo, count, pkey_cols, bkey_cols, probe_cap: int,
                       out_cap: int):
    """Per-probe-row matched flag: expand candidates, verify raw keys,
    segment-OR back onto probe rows (collision-safe for any key mode).

    Raw implementation (see ``_probe_counts_impl``); host callers use
    the jitted ``_semi_matched`` binding below."""
    probe_idx, build_idx, lane_valid = _expand_matches_impl(
        lo, count, out_cap)
    keep = lane_valid
    for pc, bc in zip(pkey_cols, bkey_cols):
        keep = keep & (pc[probe_idx] == bc[build_idx])
    matched = jnp.zeros(probe_cap + 1, dtype=bool)
    matched = matched.at[jnp.where(keep, probe_idx, probe_cap)].max(True)
    return matched[:-1]


_semi_matched = partial(jax.jit, static_argnames=("probe_cap", "out_cap"))(
    _semi_matched_impl)


def _pad_dev(arr, cap: int):
    """Pad a device array slice to cap lanes with zeros/False (padding
    lanes are dead: valid False, count 0)."""
    n = arr.shape[0]
    if n == cap:
        return arr
    return jnp.concatenate(
        [arr, jnp.zeros((cap - n,), dtype=arr.dtype)])


def _np_pad(arr: np.ndarray, cap: int, fill: bool = False) -> np.ndarray:
    n = arr.shape[0]
    if n == cap:
        return arr
    out = np.full(cap, fill, dtype=bool) if arr.dtype == bool \
        else np.zeros(cap, dtype=arr.dtype)
    out[:n] = arr
    return out


def _pad_concat(arrays, cap: int, fill: bool = False):
    cat = jnp.concatenate(list(arrays))
    n = cat.shape[0]
    if n == cap:
        return cat
    pad = jnp.full((cap - n,), fill, dtype=cat.dtype) if cat.dtype == bool \
        else jnp.zeros((cap - n,), dtype=cat.dtype)
    return jnp.concatenate([cat, pad])
