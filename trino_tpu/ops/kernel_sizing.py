"""Per-kernel-shape sizing history: remembered static capacities so
repeat shapes reuse compiled programs.

The matmul-join key-domain table and the global-hash aggregation table
are jit'd at a STATIC capacity (one-hot width / table slots).  A
capacity derived freshly from each query's data would drift run to run
— padded_size buckets absorb most of it, but a workload oscillating
around a pow2 boundary would still alternate between two compiled
programs.  This history is the kernel-capacity analog of
``parallel.device_exchange.ExchangeSizingHistory``: grow IMMEDIATELY on
a larger observation (an undersized table means a fallback or an extra
claim round; an oversized one only pads lanes), decay by EWMA so a
transient spike doesn't pin the capacity forever, and always emit
through ``padded_size`` so a stable workload re-lands on the identical
jit cache entry.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..block import padded_size


class ShapeSizingHistory:
    """Process-wide remembered capacity per kernel shape key."""

    def __init__(self, alpha: float = 0.5):
        self.alpha = alpha
        self._lock = threading.Lock()
        self._ewma: Dict[tuple, float] = {}

    def suggest(self, key: tuple, need: int, minimum: int = 16) -> int:
        """The pow2-bucketed capacity for this shape: at least ``need``
        (exactness first), grown to the remembered level so a repeat
        shape whose need shrank a little keeps its compiled program.
        Records the observation."""
        with self._lock:
            prev = self._ewma.get(key)
            if prev is None or need >= prev:
                self._ewma[key] = float(need)
            else:
                self._ewma[key] = (self.alpha * need
                                   + (1 - self.alpha) * prev)
            remembered = int(round(self._ewma[key]))
        return padded_size(max(need, remembered, minimum))

    def reset(self) -> None:
        with self._lock:
            self._ewma.clear()


#: one history per process, like the jit caches it protects
KERNEL_SIZING = ShapeSizingHistory()
