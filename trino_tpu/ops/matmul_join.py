"""MXU-native matmul join: the probe as a blocked one-hot matmul.

Reference analog: "Density-optimized Intersection-free Mapping and
Matrix Multiplication for Join-Project Operations" (PAPERS.md,
arXiv 2206.04995) — equi-join over low-NDV keys expressed as dense
matrix products over one-hot key encodings, with a density-optimized
mapping of the (sparse) key domain onto matrix indices.

Adaptation to this engine's join machinery (``ops/join.py``):

- **Mapping** (the paper's density-optimized, intersection-free map):
  keys normalize to order-preserving uint64 (the build side already
  did, for its sorted index), and the observed build key range
  ``[klo, khi]`` maps identically onto dense codes ``key - klo``.
  Chosen by the COST MODEL from connector NDV/min-max stats
  (``planner/optimizer.choose_join_strategy``); the operator re-checks
  the actual range at build time and falls back to the sorted-index
  probe when the mapping would not be dense enough.  Dictionary-coded
  (string/composite) keys are already dense codes in the build's pool,
  so the same range map covers them with no special case.
- **Build aggregate matrix**: a one-time ``(K, 2)`` table over the key
  domain — ``cnt[k]`` (build rows with code k) and ``first[k]`` (their
  first position in the code-sorted build).  Because the u64 map is
  monotone, the existing sorted build index IS code-sorted, and
  ``(first, cnt)`` are bit-identical to the oracle's two
  ``searchsorted`` results.
- **Probe** (the hot path, per page): blocked one-hot encode the probe
  codes and one f32 matmul against the build table yields ``(count,
  lo)`` per probe row — the MXU replaces the binary-search gather
  chase.  f32 accumulation is EXACT: each one-hot row has exactly one
  nonzero lane and table values stay below 2^24 (build size is gated).
  Semi/anti joins finish right there (``matched = count > 0`` — the
  paper's join-project-as-matmul membership); inner/left joins feed
  the byte-identical (lo, count) into the shared candidate-expansion
  and finalize kernels of the sorted-index operator.

Static one-hot width (the jit cache key) rides ``KERNEL_SIZING`` so
repeat queries with a jittering key range reuse the compiled program.

Batched execution (round 17): ``exec/batched.py`` probes every join —
matmul-strategy or not — through the shared sorted-index impls
(``_probe_counts_impl`` et al.) under one ``jit(vmap(...))`` program.
That is sound precisely because of the bit-identity above: ``(lo,
count)`` from the matmul probe equals the sorted-index result byte for
byte, so a burst may ride the masked sorted-index lane while the
serial path keeps the MXU probe, with byte-equal demuxed pages.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit_stats
from .. import types as T
from ..block import DevicePage
from ..telemetry.profiler import instrument
from .join import BuildSide, JoinBridge, LookupJoinOperator
from .kernel_sizing import KERNEL_SIZING

#: default cap on the dense key domain (``matmul_join_max_key_range``):
#: the one-hot width, i.e. per-probe-row MACs — the density knob that
#: bounds the matmul's O(rows * range) work to its low-NDV win region
DEFAULT_MAX_KEY_RANGE = 1024

#: builds past this lose f32-exact counts/positions (2^24) — THE one
#: definition; the cost model (planner/optimizer.choose_join_strategy)
#: imports it so planner estimate and operator re-check cannot drift
MAX_BUILD_ROWS = 1 << 24

#: probe-row / key-domain block sizes of the one-hot matmul (pow2, so
#: they divide every padded page capacity and table width)
_MB = 1024
_KB = 512

_U64_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


@partial(jax.jit, static_argnames=("kp",))
def _build_code_table(key_sorted, klo, k_range, kp: int):
    """The (kp, 2) f32 build aggregate matrix over dense key codes:
    column 0 = cnt[k] (usable build rows with code k), column 1 =
    first[k] (their first sorted position).  One-time per build; codes
    beyond the observed range (padding lanes) hold zeros.  Bit-equal to
    the oracle's searchsorted pair: unusable rows sort to the u64
    sentinel, past every in-range probe value."""
    jit_stats.bump("matmul_join_build_table")
    codes = jnp.arange(kp, dtype=jnp.uint64)
    ks = klo + codes  # wraps past k_range; masked below
    lo = jnp.searchsorted(key_sorted, ks, side="left")
    hi = jnp.searchsorted(key_sorted, ks, side="right")
    live = codes < k_range
    cnt = jnp.where(live, hi - lo, 0)
    first = jnp.where(live, lo, 0)
    return jnp.stack([cnt, first], axis=1).astype(jnp.float32)


# profiled entry points (telemetry.profiler): cost/compile
# attribution under EXPLAIN ANALYZE VERBOSE; plain calls when off
_build_code_table = instrument("matmul_join_build_table",
                               _build_code_table,
                               static_argnames=("kp",))


def _blocked_onehot_matmul(codes, table):
    """(m, C) = OneHot(codes) @ table, blocked (_MB x _KB): out[i, :] =
    table[codes[i], :] computed as dense f32 dots — the MXU form of the
    probe (codes == kp select the all-zero no-match row).  HIGHEST
    precision keeps f32 matmuls off the MXU's bf16 passes so integer
    payloads below 2^24 stay exact."""
    m = codes.shape[0]
    kp, c = table.shape
    mb, kb = min(m, _MB), min(kp, _KB)
    n_mb, n_kb = m // mb, kp // kb
    lanes = jnp.arange(kb, dtype=codes.dtype)

    def body(g, acc):
        mi, ki = g // n_kb, g % n_kb
        c_blk = jax.lax.dynamic_slice(codes, (mi * mb,), (mb,))
        t_blk = jax.lax.dynamic_slice(table, (ki * kb, 0), (kb, c))
        onehot = (c_blk[:, None] == ki * kb + lanes[None, :]).astype(
            jnp.float32)
        part = jnp.dot(onehot, t_blk,
                       precision=jax.lax.Precision.HIGHEST)
        cur = jax.lax.dynamic_slice(acc, (mi * mb, 0), (mb, c))
        return jax.lax.dynamic_update_slice(acc, cur + part, (mi * mb, 0))

    acc = jnp.zeros((m, c), dtype=jnp.float32)
    return jax.lax.fori_loop(0, n_mb * n_kb, body, acc)


@jax.jit
def _matmul_lo_count(pkey, pusable, klo, k_range, table):
    """Per-probe-row (lo, count) via the blocked one-hot matmul —
    byte-identical to ``join._probe_counts`` for every usable row
    (dead/unmatched rows get count 0 and a clamped lo no kernel
    reads)."""
    jit_stats.bump("matmul_join_probe")
    kp = table.shape[0]
    off = pkey - klo  # u64: wraps below klo -> huge -> out of range
    in_range = pusable & (off < k_range)
    codes = jnp.where(in_range, off,
                      jnp.uint64(kp)).astype(jnp.int32)
    out = _blocked_onehot_matmul(codes, table)
    # int64, matching the searchsorted oracle: a high-fanout page's
    # count SUM must not wrap int32 in the expansion cumsum
    count = out[:, 0].astype(jnp.int64)
    lo = out[:, 1].astype(jnp.int64)
    return lo, count


_matmul_lo_count = instrument("matmul_join_probe", _matmul_lo_count)


@partial(jax.jit, static_argnames=("anti",))
def _membership_page_valid(valid, count, anti: bool):
    """Semi/anti output mask straight from the matmul counts (exact
    codes: count > 0 IS raw-key membership, no expansion or verify)."""
    jit_stats.bump("matmul_join_membership")
    matched = count > 0
    return valid & ~matched if anti else valid & matched


_membership_page_valid = instrument(
    "matmul_join_membership", _membership_page_valid,
    static_argnames=("anti",))


class MatmulJoinOperator(LookupJoinOperator):
    """The matmul strategy: identical operator contract and output to
    ``LookupJoinOperator`` (it IS one), with the probe's candidate
    lookup replaced by the blocked one-hot matmul and semi/anti
    finishing directly on the membership counts.  Falls back to the
    inherited sorted-index probe — per build, with the reason surfaced
    in metrics — whenever the density map is infeasible (multi-key
    build, empty/oversized build, key range past ``max_key_range``)."""

    def __init__(self, probe_types: Sequence[T.Type],
                 probe_key_channels: Sequence[int], bridge: JoinBridge,
                 join_type: str = "inner", filter_fn=None,
                 max_lanes: Optional[int] = None,
                 memory_limited: bool = False,
                 max_key_range: int = DEFAULT_MAX_KEY_RANGE,
                 strategy_detail: str = ""):
        super().__init__(probe_types, probe_key_channels, bridge,
                         join_type, filter_fn, max_lanes, memory_limited)
        self.max_key_range = max_key_range
        #: the cost-model estimate that picked this strategy (rendered
        #: into EXPLAIN ANALYZE next to what actually ran)
        self.strategy_detail = strategy_detail
        self._mm = None  # (klo u64, k_range u64, table) once built
        self._fallback_reason: Optional[str] = None

    def metrics(self) -> dict:
        out = {"strategy": "matmul" if self._fallback_reason is None
               else "matmul->sorted-index"}
        if self._fallback_reason is not None:
            out["fallback"] = self._fallback_reason
        elif self._mm is not None:
            out["key_range"] = int(self._mm[1])
            out["onehot_width"] = int(self._mm[2].shape[0])
        if self.strategy_detail:
            out["estimate"] = self.strategy_detail
        return out

    def _ensure_table(self, b: BuildSide) -> bool:
        """Build the (K, 2) aggregate matrix once per build; False =>
        fall back to the inherited sorted-index probe."""
        if self._mm is not None:
            return True
        if self._fallback_reason is not None:
            return False
        reason = None
        klo = khi = np.uint64(0)
        if b.key_mode != "single":
            reason = f"{b.key_mode} key mode (needs one equi key)"
        else:
            n_usable = int(jnp.sum(b.usable_sorted))
            if n_usable == 0:
                reason = "empty build"
            elif n_usable > MAX_BUILD_ROWS:
                reason = f"build {n_usable} rows > f32-exact bound"
            else:
                # usable rows sort first: [0, n_usable) spans the range
                klo = np.uint64(b.key_sorted[0])
                khi = np.uint64(b.key_sorted[n_usable - 1])
                if khi == _U64_SENTINEL:
                    reason = "key at the u64 sentinel"
                elif int(khi - klo) + 1 > self.max_key_range:
                    reason = (f"key range {int(khi - klo) + 1} > "
                              f"max {self.max_key_range}")
        if reason is not None:
            self._fallback_reason = reason
            return False
        k_range = int(khi - klo) + 1
        # history key = the JOIN's shape, not just the key type: the
        # probe layout + the planner's estimate string distinguish
        # unrelated joins (whose ranges would otherwise contaminate one
        # another's EWMA) while staying stable across repeat queries
        key_t = self.probe_types[self.probe_keys[0]]
        shape_key = ("matmul-join", str(key_t),
                     tuple(str(t) for t in self.probe_types),
                     tuple(self.probe_keys), self.strategy_detail)
        kp = KERNEL_SIZING.suggest(shape_key, k_range, minimum=_KB)
        table = _build_code_table(b.key_sorted, klo,
                                  np.uint64(k_range), kp=kp)
        self._mm = (klo, np.uint64(k_range), table)
        return True

    # -- the strategy seams of LookupJoinOperator ----------------------

    def _probe_direct(self, page: DevicePage, b: BuildSide, pkey,
                      pusable) -> Optional[DevicePage]:
        """Semi/anti without a residual filter: membership IS the
        matmul count — emit the masked page with no expansion at all."""
        if self.join_type not in ("semi", "anti") \
                or self.filter_fn is not None \
                or not self._ensure_table(b):
            return None
        klo, k_range, table = self._mm
        _lo, count = _matmul_lo_count(pkey, pusable, klo, k_range, table)
        valid = _membership_page_valid(page.valid, count,
                                       anti=self.join_type == "anti")
        return DevicePage(page.types, page.cols, page.nulls, valid,
                          page.dictionaries)

    def _probe_lo_count(self, b: BuildSide, pkey, pusable):
        if not self._ensure_table(b):
            return super()._probe_lo_count(b, pkey, pusable)
        klo, k_range, table = self._mm
        return _matmul_lo_count(pkey, pusable, klo, k_range, table)
