"""K-way merge exchange source: order-preserving distributed gather.

Reference analog: ``operator/MergeOperator.java`` +
``exchange/LocalMergeSourceOperator.java`` — the consumer of a merging
exchange k-way merges its producers' pre-sorted streams instead of
re-sorting the gathered whole.

TPU-first redesign: no per-row heap. Each round takes the HEAD page of
every stream plus the carry of the previous round, sorts that bounded
window with one ``lax.sort`` over the same normalized sort operands the
producers ordered by, and emits the prefix whose keys are <= the
watermark — the smallest "largest seen key" among streams that may
still deliver more rows (everything they send later is >= it, so the
prefix is globally final). Working set stays O(k pages), not O(n), and
output streams incrementally (blocked-token parking while streams are
empty).

Dictionary pools: producers encode strings against their own pools, so
pages re-encode into stable per-channel target pools before comparison
(same contract as ExchangeSourceOperator)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, Dictionary, Page, padded_size
from .operator import SourceOperator
from .sort import _concat_pages
from .sortkeys import SortKey, sort_operands


def _lex_le(ops: Sequence, watermark: Sequence):
    """row_ops <= watermark, lexicographically, vectorized over rows."""
    res = jnp.zeros(ops[0].shape, dtype=bool)
    tie = jnp.ones(ops[0].shape, dtype=bool)
    for o, w in zip(ops, watermark):
        res = res | (tie & (o < w))
        tie = tie & (o == w)
    return res | tie


class _Stream:
    """One producer's page FIFO (channel = streaming poll/at_end, or a
    thunk revealing a prebuilt page list one page per poll)."""

    def __init__(self, source):
        self._chan = source if hasattr(source, "poll") else None
        self._thunk = None if self._chan is not None else source
        self._pages: Optional[List] = None if self._chan is None else []
        self.head: Optional[DevicePage] = None
        self.finished = False

    def _materialize(self):
        if self._thunk is not None and self._pages is None:
            self._pages = list(self._thunk())
            self._thunk = None

    def advance(self) -> bool:
        """Try to fill ``head``; True if state changed."""
        if self.head is not None or self.finished:
            return False
        if self._chan is None:
            self._materialize()
            if self._pages:
                self.head = self._pages.pop(0)
                return True
            self.finished = True
            return True
        item = self._chan.poll()
        if item is not None:
            self.head = item
            return True
        if self._chan.at_end():
            self.finished = True
            return True
        return False

    def blocked_token(self):
        if self._chan is not None and self.head is None \
                and not self.finished:
            token = self._chan.listen()
            if self._chan.at_end() or self._chan.has_page():
                return None
            return token
        return None


class MergeExchangeSourceOperator(SourceOperator):
    """Merges k sorted streams into one sorted stream of pages."""

    def __init__(self, sources: Sequence, types_: Sequence[T.Type],
                 sort_keys: Sequence[SortKey]):
        self.types = list(types_)
        self.sort_keys = list(sort_keys)
        self.streams = [_Stream(s) for s in sources]
        self._carry: Optional[DevicePage] = None
        self._target_dicts: List[Optional[Dictionary]] = \
            [None] * len(self.types)
        self._done = False

    def add_split(self, split):
        raise AssertionError("merge exchange source has no splits")

    # -- pool unification (ExchangeSourceOperator contract) -------------

    def _unify(self, item) -> DevicePage:
        page = item.to_page() if isinstance(item, DevicePage) else item
        from ..block import Block

        blocks = []
        changed = False
        for c, t in enumerate(self.types):
            b = page.block(c).numpy()
            if not t.is_pooled or b.dictionary is None:
                blocks.append(b)
                continue
            tgt = self._target_dicts[c]
            if tgt is None:
                self._target_dicts[c] = b.dictionary
                blocks.append(b)
                continue
            if b.dictionary is tgt:
                blocks.append(b)
                continue
            remap = (np.asarray(tgt.encode(list(b.dictionary.values)),
                                dtype=np.int32)
                     if len(b.dictionary) else np.zeros(1, np.int32))
            blocks.append(Block(t, remap[b.data], b.nulls, tgt))
            changed = True
        host = Page(blocks, page.num_rows) if changed else page
        return DevicePage.from_page(host)

    # -- merge rounds ----------------------------------------------------

    def _ops_of(self, page: DevicePage):
        ops: List = []
        for k in self.sort_keys:
            ops.extend(sort_operands(
                page.cols[k.channel], page.nulls[k.channel],
                page.types[k.channel], page.dictionaries[k.channel],
                ascending=k.ascending, nulls_last=k.nulls_last))
        return ops

    def _stream_max_key(self, page: DevicePage):
        """Operands of the LARGEST valid row (pages are sorted, but the
        valid lanes need not be a prefix after wire transport)."""
        ops = self._ops_of(page)
        idx = jnp.arange(page.capacity)
        i_last = jnp.max(jnp.where(page.valid, idx, -1))
        safe = jnp.clip(i_last, 0, page.capacity - 1)
        return [o[safe] for o in ops], int(np.asarray(
            jnp.sum(page.valid.astype(jnp.int32))))

    def get_output(self) -> Optional[DevicePage]:
        if self._done:
            return None
        # fill heads; a round needs every unfinished stream to have one
        for s in self.streams:
            s.advance()
        if any(s.head is None and not s.finished for s in self.streams):
            return None  # parked on blocked_token
        batch: List[DevicePage] = []
        watermark = None  # lexicographic MIN over streams-with-more
        unconstrained = False  # an unfinished stream gave no key bound
        for s in self.streams:
            if s.head is None:
                continue
            page = self._unify(s.head)
            s.head = None
            more = bool(s._pages) if s._chan is None else not s.finished
            if page.count() == 0:
                # an unfinished stream revealing no rows bounds nothing:
                # emitting anything could race ahead of its future keys
                unconstrained = unconstrained or more
                continue
            batch.append(page)
            if more:
                key, cnt = self._stream_max_key(page)
                if cnt and (watermark is None or bool(np.asarray(
                        _lex_le(tuple(k[None] for k in key),
                                watermark)[0]))):
                    watermark = key
        if self._carry is not None:
            batch.insert(0, self._carry)
            self._carry = None
        if not batch:
            if all(s.finished and not (s._pages or s.head)
                   for s in self.streams):
                self._done = True
            return None

        cap = padded_size(sum(p.capacity for p in batch))
        merged = _concat_pages(batch, cap)
        ops = self._ops_of(merged)
        operands = [(~merged.valid).astype(jnp.uint8)] + list(ops) \
            + list(merged.cols) + list(merged.nulls) + [merged.valid]
        s = jax.lax.sort(operands, num_keys=1 + len(ops),
                         is_stable=False)
        nops = len(ops)
        s_ops = s[1:1 + nops]
        ncols = len(merged.cols)
        s_cols = list(s[1 + nops:1 + nops + ncols])
        s_nulls = list(s[1 + nops + ncols:1 + nops + 2 * ncols])
        s_valid = s[-1]
        if unconstrained:
            # hold everything until every live stream shows a key
            self._carry = DevicePage(list(merged.types),
                                     list(s_cols), list(s_nulls),
                                     s_valid, list(merged.dictionaries))
            return None
        if watermark is None:
            emit_valid = s_valid
            carry_valid = jnp.zeros_like(s_valid)
        else:
            safe = _lex_le(s_ops, watermark)
            emit_valid = s_valid & safe
            carry_valid = s_valid & ~safe
        n_carry = int(np.asarray(jnp.sum(carry_valid.astype(jnp.int32))))
        if n_carry:
            self._carry = DevicePage(list(merged.types), s_cols,
                                     s_nulls, carry_valid,
                                     list(merged.dictionaries))
        n_emit = int(np.asarray(jnp.sum(emit_valid.astype(jnp.int32))))
        if n_emit == 0:
            return None  # watermark below every buffered row: wait
        return DevicePage(list(merged.types), s_cols, s_nulls,
                          emit_valid, list(merged.dictionaries))

    def metrics(self) -> Optional[dict]:
        """Aggregated per-stream channel stats (the merge consumes one
        channel per producer): flow counters plus the ack/replay
        machinery's reconnect counters when it engaged — the same
        surface ExchangeSourceOperator exposes for single channels."""
        out = {"kind": "merge-stream", "streams": len(self.streams)}
        rows = pages = reconnects = replayed = 0
        seen = False
        for s in self.streams:
            st = getattr(s._chan, "stats", None) if s._chan is not None \
                else None
            if not st:
                continue
            seen = True
            rows += st.get("rows", 0)
            pages += st.get("pages", 0)
            reconnects += st.get("reconnects", 0)
            replayed += st.get("replayed_frames", 0)
        if not seen:
            return None
        out["rows"] = rows
        out["pages"] = pages
        if reconnects:
            out["reconnects"] = reconnects
            out["replayed_frames"] = replayed
        return out

    def blocked_token(self):
        if self._done:
            return None
        toks = [t for t in (s.blocked_token() for s in self.streams)
                if t is not None]
        return toks[0] if toks else None

    def is_finished(self) -> bool:
        return self._done
