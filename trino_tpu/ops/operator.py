"""Operator protocol + simple relational operators.

Reference analog: ``core/trino-main/.../operator/Operator.java:21-93``
(needsInput/addInput/getOutput/finish/isBlocked) and the simple operators
(LimitOperator, ValuesOperator, TableScanOperator, ScanFilterAndProject).

Pages flowing between operators are ``DevicePage``s — padded device
batches with validity masks — so a pipeline's hot ops chain on device
without host round-trips. Host boundaries are scans (numpy -> device) and
output (device -> numpy).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..block import DevicePage, Page
from ..connectors.spi import ColumnHandle, Connector, ConnectorSplit
from ..expr.compiler import PageProcessor


class Operator:
    """One stage of a pipeline (reference: operator/Operator.java)."""

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, page: DevicePage):
        raise NotImplementedError

    def get_output(self) -> Optional[DevicePage]:
        return None

    def finish(self):
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    def blocked_token(self):
        """Non-None when the operator cannot progress until an external
        event fires; the token's ``on_ready(cb)`` re-schedules the
        parked task (reference: Operator.java isBlocked returning a
        ListenableFuture)."""
        return None

    _finishing = False


class SourceOperator(Operator):
    """Pipeline head driven by splits (reference: SourceOperator.java)."""

    def add_split(self, split: ConnectorSplit):
        raise NotImplementedError

    def no_more_splits(self):
        pass

    def add_input(self, page):
        raise AssertionError("source operators take splits, not pages")

    def needs_input(self) -> bool:
        return False


class TableScanOperator(SourceOperator):
    """Pulls pages from connector page sources and uploads them to device
    (reference: operator/TableScanOperator.java).

    Small pages (split tails: a table cut into many splits yields pages
    far below the connector's page size) COALESCE on host up to
    ``coalesce_rows`` before the upload, so downstream kernels see one
    full device batch instead of one launch per fragment (reference:
    ``operator/MergePages.java`` — the min-page-size rewindow in front
    of expensive operators)."""

    def __init__(self, connector: Connector, columns: Sequence[ColumnHandle],
                 dynamic_filters: Sequence = (),
                 coalesce_rows: Optional[int] = None,
                 progress=None):
        self.connector = connector
        self.columns = list(columns)
        #: telemetry.progress.QueryProgress fed host-page row counts as
        #: splits are read — a plain int add, never a device sync
        self.progress = progress
        # [(channel, DynamicFilter)] — join build-side domains applied to
        # every scanned page as a lane-mask update (reference analog:
        # dynamic-filter TupleDomains pushed into ConnectorPageSource)
        self.dynamic_filters = list(dynamic_filters)
        self.coalesce_rows = coalesce_rows
        self._buffer: List[Page] = []
        self._buffered_rows = 0
        self._splits: List[ConnectorSplit] = []
        self._source = None
        self._no_more_splits = False
        self._done = False

    def add_split(self, split: ConnectorSplit):
        self._splits.append(split)

    def no_more_splits(self):
        self._no_more_splits = True

    def _upload(self, page: Page) -> DevicePage:
        dp = DevicePage.from_page(page)
        for ch, df in self.dynamic_filters:
            dp = DevicePage(dp.types, dp.cols, dp.nulls,
                            df.apply(dp.cols[ch], dp.nulls[ch],
                                     dp.valid),
                            dp.dictionaries)
        return dp

    def _flush(self) -> DevicePage:
        pages, self._buffer = self._buffer, []
        self._buffered_rows = 0
        return self._upload(pages[0] if len(pages) == 1
                            else Page.concat(pages))

    def get_output(self) -> Optional[DevicePage]:
        while True:
            if self._source is None:
                if self._splits:
                    split = self._splits.pop(0)
                    self._source = self.connector.page_source(
                        split, self.columns)
                elif self._no_more_splits or self._finishing:
                    if self._buffer:
                        return self._flush()
                    self._done = True
                    return None
                else:
                    return self._flush() if self._buffer else None
            page = self._source.get_next_page()
            if page is None:
                if self._source.is_finished():
                    self._source.close()
                    self._source = None
                    continue
                # source stalled: don't sit on buffered rows
                return self._flush() if self._buffer else None
            if page.num_rows == 0:
                continue
            if self.progress is not None:
                self.progress.add_rows(page.num_rows)
            target = self.coalesce_rows
            if target and page.num_rows < target:
                self._buffer.append(page)
                self._buffered_rows += page.num_rows
                if self._buffered_rows >= target:
                    return self._flush()
                continue
            if self._buffer:
                self._buffer.append(page)
                self._buffered_rows += page.num_rows
                return self._flush()
            return self._upload(page)

    def is_finished(self) -> bool:
        return self._done


class FilterProjectOperator(Operator):
    """Fused filter+project via a compiled PageProcessor (reference:
    ScanFilterAndProjectOperator / FilterAndProjectOperator +
    operator/project/PageProcessor.java)."""

    def __init__(self, processor: PageProcessor, params: tuple = ()):
        self.processor = processor
        #: template-parameter bindings (round 16): raw scalars for the
        #: processor's consumed slots — a template plan executed for one
        #: statement binds its literal vector here instead of retracing
        self.params = tuple(params)
        self._pending: Optional[DevicePage] = None
        self._done = False

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, page: DevicePage):
        assert self._pending is None
        self._pending = self.processor.process(page, self.params)

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and self._finishing:
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


def _running_valid_kernel():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(valid, seen, lo, hi):
        """Keep live lanes whose running ordinal (seen so far + position
        within this page) lands in (lo, hi]; returns the new mask and
        the updated device-resident total."""
        run = jnp.cumsum(valid.astype(jnp.int64)) + seen
        new_valid = valid & (run > lo) & (run <= hi)
        return new_valid, run[-1]

    return kernel


_RUNNING_VALID = None


def _running_valid(valid, seen, lo, hi):
    global _RUNNING_VALID
    if _RUNNING_VALID is None:
        _RUNNING_VALID = _running_valid_kernel()
    return _RUNNING_VALID(valid, seen, lo, hi)


class LimitOperator(Operator):
    """LIMIT n (reference: operator/LimitOperator.java).

    Device-resident: the running row count stays a device scalar and the
    mask trim is one fused kernel — no per-page host pull of the valid
    mask (round-2 verdict weak #5). Early exit still works: the scalar
    is fetched ASYNC after each page and read one page later, so the
    driver stops pulling input at most one page after the limit fills,
    without ever stalling on a device round-trip."""

    def __init__(self, limit: int):
        self.limit = limit
        self._seen = None          # device scalar: rows passed so far
        self._known_seen = 0       # host view, one page stale
        self._pending: Optional[DevicePage] = None
        self._done = False

    def needs_input(self) -> bool:
        if self._seen is not None:
            # the async copy issued in add_input has usually landed;
            # this read is then free
            self._known_seen = int(np.asarray(self._seen))
        return (self._pending is None and self._known_seen < self.limit
                and not self._finishing)

    def add_input(self, page: DevicePage):
        if self._known_seen >= self.limit:
            return
        import jax.numpy as jnp

        seen = jnp.int64(0) if self._seen is None else self._seen
        new_valid, self._seen = _running_valid(
            page.valid, seen, jnp.int64(0), jnp.int64(self.limit))
        try:
            self._seen.copy_to_host_async()
        except AttributeError:
            pass
        self._pending = DevicePage(page.types, page.cols, page.nulls,
                                   new_valid, page.dictionaries)

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and (self._finishing
                            or self._known_seen >= self.limit):
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


class ValuesOperator(SourceOperator):
    """Inline literal rows (reference: operator/ValuesOperator.java).
    ``coalesce_rows`` applies the scan's small-page coalescing to
    pre-materialized host pages (the bench's values-fed pipelines)."""

    def __init__(self, pages: Sequence[Page],
                 coalesce_rows: Optional[int] = None):
        self._pages = list(pages)
        self.coalesce_rows = coalesce_rows
        self._done = False

    def add_split(self, split):
        raise AssertionError("values has no splits")

    def get_output(self) -> Optional[DevicePage]:
        if not self._pages:
            self._done = True
            return None
        if not self.coalesce_rows:
            return DevicePage.from_page(self._pages.pop(0))
        batch, rows = [], 0
        while self._pages and rows < self.coalesce_rows:
            batch.append(self._pages.pop(0))
            rows += batch[-1].num_rows
        return DevicePage.from_page(batch[0] if len(batch) == 1
                                    else Page.concat(batch))

    def is_finished(self) -> bool:
        return self._done


class OffsetOperator(Operator):
    """OFFSET n: drops the first n live rows (reference:
    operator/OffsetOperator.java). Fully device-resident — no control
    flow depends on the running count, so it never syncs to host."""

    def __init__(self, offset: int):
        self.offset = offset
        self._seen = None
        self._pending: Optional[DevicePage] = None
        self._done = False

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, page: DevicePage):
        import jax.numpy as jnp

        seen = jnp.int64(0) if self._seen is None else self._seen
        new_valid, self._seen = _running_valid(
            page.valid, seen, jnp.int64(self.offset),
            jnp.int64(np.iinfo(np.int64).max))
        self._pending = DevicePage(page.types, page.cols, page.nulls,
                                   new_valid, page.dictionaries)

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and self._finishing:
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


class EnforceSingleRowOperator(Operator):
    """Scalar-subquery guard: exactly one output row — errors on more,
    emits an all-NULL row on zero (reference:
    operator/EnforceSingleRowOperator.java)."""

    def __init__(self, types):
        self.types = list(types)
        self._rows = 0
        self._pages: List[DevicePage] = []
        self._emitted = False
        self._done = False

    def add_input(self, page: DevicePage):
        n = page.count()
        if not n:
            return
        self._rows += n
        if self._rows > 1:  # fail fast, don't buffer the stream
            from ..types import TrinoError

            raise TrinoError("Scalar sub-query has returned multiple rows",
                             "SUBQUERY_MULTIPLE_ROWS")
        self._pages.append(page)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        if self._rows == 1:
            return self._pages[0]
        # one all-NULL row
        row = Page.from_pylists(self.types,
                                [[None]] * len(self.types) or [])
        if not self.types:
            return None
        return DevicePage.from_page(row)

    def is_finished(self) -> bool:
        return self._done


class DeferredPagesSourceOperator(SourceOperator):
    """Source over host pages produced by earlier pipelines of the same
    task (union inputs, materialized intermediates). The thunk is called
    at first poll — after upstream pipelines completed."""

    def __init__(self, pages_thunk):
        self._thunk = pages_thunk
        self._pages = None
        self._done = False

    def add_split(self, split):
        raise AssertionError("deferred source has no splits")

    def get_output(self) -> Optional[DevicePage]:
        if self._pages is None:
            self._pages = list(self._thunk())
        if self._pages:
            page = self._pages.pop(0)
            if page.num_rows == 0:
                return self.get_output()
            return DevicePage.from_page(page)
        self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done


class TableWriterOperator(Operator):
    """Feeds pages to a ConnectorPageSink; at finish emits one row with
    the written count (reference: operator/TableWriterOperator.java +
    TableFinishOperator.java — commit folded into sink.finish())."""

    def __init__(self, sink):
        self.sink = sink
        self.rows = 0
        self._emitted = False
        self._done = False

    def add_input(self, page: DevicePage):
        host = page.to_page()
        if host.num_rows:
            self.rows += host.num_rows
            self.sink.append_page(host)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        self.sink.finish()
        from .. import types as T

        return DevicePage.from_page(
            Page.from_pylists([T.BIGINT], [[self.rows]]))

    def is_finished(self) -> bool:
        return self._done


class OutputCollectorOperator(Operator):
    """Pipeline sink: densifies device pages back to host Pages
    (reference analog: TaskOutputOperator feeding the OutputBuffer)."""

    def __init__(self):
        self.pages: List[Page] = []
        self._done = False

    def add_input(self, page: DevicePage):
        host = page.to_page()
        if host.num_rows:
            self.pages.append(host)

    def get_output(self):
        return None

    def finish(self):
        super().finish()
        self._done = True

    def is_finished(self) -> bool:
        return self._done
