"""Operator protocol + simple relational operators.

Reference analog: ``core/trino-main/.../operator/Operator.java:21-93``
(needsInput/addInput/getOutput/finish/isBlocked) and the simple operators
(LimitOperator, ValuesOperator, TableScanOperator, ScanFilterAndProject).

Pages flowing between operators are ``DevicePage``s — padded device
batches with validity masks — so a pipeline's hot ops chain on device
without host round-trips. Host boundaries are scans (numpy -> device) and
output (device -> numpy).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..block import DevicePage, Page
from ..connectors.spi import ColumnHandle, Connector, ConnectorSplit
from ..expr.compiler import PageProcessor


class Operator:
    """One stage of a pipeline (reference: operator/Operator.java)."""

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, page: DevicePage):
        raise NotImplementedError

    def get_output(self) -> Optional[DevicePage]:
        return None

    def finish(self):
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    _finishing = False


class SourceOperator(Operator):
    """Pipeline head driven by splits (reference: SourceOperator.java)."""

    def add_split(self, split: ConnectorSplit):
        raise NotImplementedError

    def no_more_splits(self):
        pass

    def add_input(self, page):
        raise AssertionError("source operators take splits, not pages")

    def needs_input(self) -> bool:
        return False


class TableScanOperator(SourceOperator):
    """Pulls pages from connector page sources and uploads them to device
    (reference: operator/TableScanOperator.java)."""

    def __init__(self, connector: Connector, columns: Sequence[ColumnHandle]):
        self.connector = connector
        self.columns = list(columns)
        self._splits: List[ConnectorSplit] = []
        self._source = None
        self._no_more_splits = False
        self._done = False

    def add_split(self, split: ConnectorSplit):
        self._splits.append(split)

    def no_more_splits(self):
        self._no_more_splits = True

    def get_output(self) -> Optional[DevicePage]:
        while True:
            if self._source is None:
                if self._splits:
                    split = self._splits.pop(0)
                    self._source = self.connector.page_source(
                        split, self.columns)
                elif self._no_more_splits or self._finishing:
                    self._done = True
                    return None
                else:
                    return None
            page = self._source.get_next_page()
            if page is None:
                if self._source.is_finished():
                    self._source.close()
                    self._source = None
                    continue
                return None
            if page.num_rows == 0:
                continue
            return DevicePage.from_page(page)

    def is_finished(self) -> bool:
        return self._done


class FilterProjectOperator(Operator):
    """Fused filter+project via a compiled PageProcessor (reference:
    ScanFilterAndProjectOperator / FilterAndProjectOperator +
    operator/project/PageProcessor.java)."""

    def __init__(self, processor: PageProcessor):
        self.processor = processor
        self._pending: Optional[DevicePage] = None
        self._done = False

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, page: DevicePage):
        assert self._pending is None
        self._pending = self.processor.process(page)

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and self._finishing:
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


class LimitOperator(Operator):
    """LIMIT n (reference: operator/LimitOperator.java)."""

    def __init__(self, limit: int):
        self.remaining = limit
        self._pending: Optional[DevicePage] = None
        self._done = False

    def needs_input(self) -> bool:
        return (self._pending is None and self.remaining > 0
                and not self._finishing)

    def add_input(self, page: DevicePage):
        if self.remaining <= 0:
            return
        count = page.count()
        if count <= self.remaining:
            self.remaining -= count
            self._pending = page
        else:
            # keep only the first `remaining` live lanes
            valid = np.asarray(page.valid)
            live = np.nonzero(valid)[0]
            keep = np.zeros_like(valid)
            keep[live[: self.remaining]] = True
            import jax.numpy as jnp

            self._pending = DevicePage(page.types, page.cols, page.nulls,
                                       jnp.asarray(keep), page.dictionaries)
            self.remaining = 0

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and (self._finishing or self.remaining <= 0):
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


class ValuesOperator(SourceOperator):
    """Inline literal rows (reference: operator/ValuesOperator.java)."""

    def __init__(self, pages: Sequence[Page]):
        self._pages = list(pages)
        self._done = False

    def add_split(self, split):
        raise AssertionError("values has no splits")

    def get_output(self) -> Optional[DevicePage]:
        if self._pages:
            return DevicePage.from_page(self._pages.pop(0))
        self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done


class OutputCollectorOperator(Operator):
    """Pipeline sink: densifies device pages back to host Pages
    (reference analog: TaskOutputOperator feeding the OutputBuffer)."""

    def __init__(self):
        self.pages: List[Page] = []
        self._done = False

    def add_input(self, page: DevicePage):
        host = page.to_page()
        if host.num_rows:
            self.pages.append(host)

    def get_output(self):
        return None

    def finish(self):
        super().finish()
        self._done = True

    def is_finished(self) -> bool:
        return self._done
