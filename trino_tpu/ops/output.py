"""Stage-output repartitioning + exchange source operators.

Reference analog: ``operator/output/PartitionedOutputOperator.java`` +
``PagePartitioner.java`` (producer side: per-row partition assignment,
per-partition page builders, enqueue to OutputBuffer),
``execution/buffer/`` (PartitionedOutputBuffer / BroadcastOutputBuffer),
and ``operator/ExchangeOperator.java`` (consumer side).

TPU-first notes: partition ids are computed ON DEVICE from the same
order-preserving uint64 normalization the join/group kernels use, so a
hash exchange and the downstream hash join/aggregation agree on row
routing; string keys hash via a host LUT of stable crc32 values (codes
are pool-local, values are not). The per-partition row extraction runs
host-side on the transferred batch — the all_to_all device collective
path (parallel/exchange.py) replaces it when stages are co-resident on
one mesh.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import Block, Dictionary, DevicePage, Page
from ..parallel.exchange import (hash_partition_ids, key_to_u64,
                                 string_hash_lut)
from .operator import Operator, SourceOperator


class OutputBuffer:
    """Thread-safe per-partition page queues for one fragment's output
    (reference: execution/buffer/PartitionedOutputBuffer.java). With
    ``broadcast=True`` every consumer reads all pages."""

    def __init__(self, num_partitions: int, broadcast: bool = False):
        self.num_partitions = num_partitions
        self.broadcast = broadcast
        self._lock = threading.Lock()
        self._pages: List[List[Page]] = [
            [] for _ in range(1 if broadcast else num_partitions)]

    def enqueue(self, partition: int, page: Page):
        if page.num_rows == 0:
            return
        with self._lock:
            self._pages[0 if self.broadcast else partition].append(page)

    def pages(self, partition: int) -> List[Page]:
        with self._lock:
            return list(self._pages[0 if self.broadcast else partition])

    @property
    def total_rows(self) -> int:
        with self._lock:
            return sum(p.num_rows for ps in self._pages for p in ps)


class PartitionedOutputOperator(Operator):
    """Routes each row of the input to an output-buffer partition.
    kind: 'hash' (by key columns), 'single' (partition 0), 'broadcast'.
    """

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], buffer: OutputBuffer,
                 kind: str = "hash"):
        assert kind in ("hash", "single", "broadcast")
        self.input_types = list(input_types)
        self.key_channels = list(key_channels)
        self.buffer = buffer
        self.kind = kind
        self._done = False
        self._lut_cache: Dict[tuple, np.ndarray] = {}

    def add_input(self, page: DevicePage):
        n = self.buffer.num_partitions
        if self.kind != "hash" or n == 1:
            host = page.to_page()
            self.buffer.enqueue(0, host)
            return
        keys_u64 = []
        for c in self.key_channels:
            t = page.types[c]
            lut = None
            if t.is_string:
                d = page.dictionaries[c]
                key = (id(d), len(d) if d is not None else 0)
                lut = self._lut_cache.get(key)
                if lut is None:
                    lut = string_hash_lut(d)
                    self._lut_cache[key] = lut
                lut = jnp.asarray(lut)
            keys_u64.append(key_to_u64(page.cols[c], page.nulls[c], t, lut))
        part = np.asarray(hash_partition_ids(keys_u64, n))
        valid = np.asarray(page.valid)
        cols = [np.asarray(c) for c in page.cols]
        nulls = [np.asarray(x) for x in page.nulls]
        for p in range(n):
            idx = np.nonzero(valid & (part == p))[0]
            if len(idx) == 0:
                continue
            blocks = []
            for t, c, nl, d in zip(page.types, cols, nulls,
                                   page.dictionaries):
                bn = nl[idx]
                blocks.append(Block(t, c[idx], bn if bn.any() else None, d))
            self.buffer.enqueue(p, Page(blocks, len(idx)))

    def get_output(self):
        if self._finishing:
            self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done


class ExchangeSourceOperator(SourceOperator):
    """Reads this task's partition of an upstream fragment's output
    (reference: operator/ExchangeOperator.java). Pages from different
    producer tasks may carry different dictionary pools — string columns
    re-encode into one pool via Page.concat."""

    def __init__(self, pages_thunk: Callable[[], List[Page]],
                 types_: Sequence[T.Type]):
        self._thunk = pages_thunk
        self.types = list(types_)
        self._pages: Optional[List[Page]] = None
        self._done = False

    def add_split(self, split):
        raise AssertionError("exchange source has no splits")

    def get_output(self) -> Optional[DevicePage]:
        if self._pages is None:
            items = self._thunk()
            if items and isinstance(items[0], DevicePage):
                # device-collective exchange: rows arrived by all_to_all
                # with unified pools — pass straight through
                self._pages = list(items)
            else:
                pages = [p for p in items if p.num_rows]
                if pages and any(t.is_string for t in self.types):
                    pages = [Page.concat(pages)]
                self._pages = pages
        if self._pages:
            nxt = self._pages.pop(0)
            if isinstance(nxt, DevicePage):
                return nxt
            return DevicePage.from_page(nxt)
        self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done
