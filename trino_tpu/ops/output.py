"""Stage-output repartitioning + exchange source operators.

Reference analog: ``operator/output/PartitionedOutputOperator.java`` +
``PagePartitioner.java`` (producer side: per-row partition assignment,
per-partition page builders, enqueue to OutputBuffer),
``execution/buffer/`` (PartitionedOutputBuffer / BroadcastOutputBuffer),
and ``operator/ExchangeOperator.java`` (consumer side).

TPU-first notes: partition ids are computed ON DEVICE from the same
order-preserving uint64 normalization the join/group kernels use, so a
hash exchange and the downstream hash join/aggregation agree on row
routing; string keys hash via a host LUT of stable crc32 values (codes
are pool-local, values are not). The per-partition row extraction runs
host-side on the transferred batch — the all_to_all device collective
path (parallel/exchange.py) replaces it when stages are co-resident on
one mesh.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import Block, Dictionary, DevicePage, Page
from ..parallel.exchange import (hash_partition_ids, key_to_u64,
                                 string_hash_lut)
from .operator import Operator, SourceOperator


class ListenToken:
    """Snapshot of a buffer state version; ``on_ready(cb)`` fires cb
    once when the state changes after the snapshot — immediately if it
    already has (reference: the ListenableFuture returned by
    Operator.isBlocked / OutputBuffer.isFull)."""

    __slots__ = ("_buffer", "_version")

    def __init__(self, buffer: "OutputBuffer", version: int):
        self._buffer = buffer
        self._version = version

    def on_ready(self, cb: Callable[[], None]):
        self._buffer._register(cb, self._version)


class OutputBuffer:
    """Thread-safe per-partition page queues for one fragment's output
    (reference: execution/buffer/PartitionedOutputBuffer.java). With
    ``broadcast=True`` every consumer reads all pages (per-consumer
    cursors).

    Two consumption modes share one producer API:
    - barrier (``pages``): snapshot after the producing stage finished;
    - streaming (``poll``/``at_end``/``listen``): pages are consumed as
      producers enqueue them; ``set_no_more_pages`` marks the end;
      ``full``/``listen`` on the producer side give backpressure
      (reference: PipelinedQueryScheduler's streaming exchanges).
    """

    #: scaled-writer boundaries attach their UniformPartitionRebalancer
    #: here so the STAGE-level stats surface (EXPLAIN ANALYZE exchange
    #: line) carries the rebalance counters, same as the producer
    #: operator's metrics
    rebalancer = None

    def __init__(self, num_partitions: int, broadcast: bool = False,
                 max_pending_pages: Optional[int] = None):
        self.num_partitions = num_partitions
        self.broadcast = broadcast
        #: producer backpressure: a partition holding this many
        #: undrained pages reports full. None = unbounded — REQUIRED for
        #: barrier-mode stages (the consumer stage hasn't started when
        #: the producer runs, so any bound would deadlock); streaming
        #: mode sets a bound. Broadcast buffers are always unbounded
        #: (every consumer must see every page; build sides are small).
        self.max_pending_pages = max_pending_pages
        self._lock = threading.Lock()
        self._pages: List[List[Page]] = [
            [] for _ in range(1 if broadcast else num_partitions)]
        #: per-(partition,consumer) read cursors (broadcast keeps all
        #: pages; partitioned consumers advance a drain cursor so the
        #: barrier ``pages`` snapshot still sees everything)
        self._cursors: Dict[tuple, int] = {}
        self._no_more = False
        self._aborted = False
        self._version = 0
        self._listeners: List[tuple] = []  # (cb, seen_version)
        self._total_rows = 0
        #: per-PARTITION enqueued row counts (broadcast: one logical
        #: partition) — the host-path skew observability mirroring
        #: DeviceExchange.stats, so EXPLAIN ANALYZE reads identically
        #: whichever path a stage boundary took
        self._partition_rows = [0] * (1 if broadcast else num_partitions)
        #: hot-partition lane split (round 16): partition -> lane count.
        #: The host analog of the device collective's receiver spread —
        #: a partition holding most of the exchange's rows saturates its
        #: single pending-page bound and stalls EVERY producer however
        #: much slack its siblings have.  Extra lanes multiply the hot
        #: partition's capacity; enqueue round-robins rows-insensitive
        #: pages across lanes and ``poll`` drains them transparently
        #: (consumer-task co-location is untouched: all lanes ARE the
        #: partition).  Only hash-kind producers may request a split —
        #: merge-kind streams are per-producer SORTED and interleaving
        #: lanes would break the consumer's merge invariant.
        self._hot_lanes: Dict[int, int] = {}
        self._lane_pages: Dict[tuple, List[Page]] = {}
        self._lane_rows: Dict[tuple, int] = {}
        self._enq_rr: Dict[int, int] = {}
        self._drain_rr: Dict[int, int] = {}
        # streaming observability: did any consumer dequeue a page
        # before the producers finished?
        self.first_poll_ts: Optional[float] = None
        self.no_more_ts: Optional[float] = None

    # -- hot-partition lanes ----------------------------------------------

    def split_partition(self, partition: int, ways: int) -> bool:
        """Grow ``partition`` to ``ways`` drain lanes (idempotent,
        monotonic).  Returns whether the lane set changed.  Callers are
        responsible for the kind gate: ONLY order-insensitive (hash)
        producers may split."""
        if self.broadcast or ways <= 1:
            return False
        with self._lock:
            if self._aborted:
                return False
            cur = self._hot_lanes.get(partition, 1)
            if cur >= ways:
                return False
            self._hot_lanes[partition] = ways
            for lane in range(1, ways):
                self._lane_pages.setdefault((partition, lane), [])
                self._lane_rows.setdefault((partition, lane), 0)
            fired = self._bump_locked()
        for cb in fired:
            cb()
        return True

    def _lane_pending_locked(self, partition: int, lane: int) -> int:
        if lane == 0:
            return len(self._pages[partition]) - self._cursors.get(
                (partition, "drain"), 0)
        return len(self._lane_pages[(partition, lane)]) - \
            self._cursors.get((partition, "drain", lane), 0)

    # -- state/version plumbing -----------------------------------------

    def _bump_locked(self) -> List[Callable]:
        self._version += 1
        fired = [cb for cb, _ in self._listeners]
        self._listeners.clear()
        return fired

    def _register(self, cb: Callable[[], None], seen_version: int):
        with self._lock:
            if self._version == seen_version:
                self._listeners.append((cb, seen_version))
                return
        cb()

    def listen(self) -> ListenToken:
        with self._lock:
            return ListenToken(self, self._version)

    # -- producer side ---------------------------------------------------

    def enqueue(self, partition: int, page: Page):
        if page.num_rows == 0:
            return
        with self._lock:
            if self._aborted:
                return
            tgt = 0 if self.broadcast else partition
            lanes = 1 if self.broadcast else self._hot_lanes.get(tgt, 1)
            if lanes > 1:
                k = self._enq_rr.get(tgt, 0)
                self._enq_rr[tgt] = k + 1
                lane = k % lanes
            else:
                lane = 0
            if lane == 0:
                self._pages[tgt].append(page)
            else:
                self._lane_pages[(tgt, lane)].append(page)
                self._lane_rows[(tgt, lane)] += page.num_rows
            self._total_rows += page.num_rows
            self._partition_rows[tgt] += page.num_rows
            fired = self._bump_locked()
        for cb in fired:
            cb()

    def set_no_more_pages(self):
        import time as _time

        with self._lock:
            if self._no_more:
                return
            self._no_more = True
            self.no_more_ts = _time.monotonic()
            fired = self._bump_locked()
        for cb in fired:
            cb()

    def abort(self):
        """Failure path: drop pages, mark ended, wake everyone — blocked
        producers and consumers must all unwind so the query's error can
        propagate instead of deadlocking."""
        with self._lock:
            self._aborted = True
            self._no_more = True
            self._pages = [[] for _ in self._pages]
            self._lane_pages = {k: [] for k in self._lane_pages}
            fired = self._bump_locked()
        for cb in fired:
            cb()

    def full(self, partitions: Optional[Sequence[int]] = None) -> bool:
        if self.broadcast or self.max_pending_pages is None:
            return False
        with self._lock:
            if self._aborted:
                return False
            idxs = range(len(self._pages)) if partitions is None \
                else partitions
            for i in idxs:
                # a split partition reports full only when EVERY lane
                # is at the bound — the whole point of the extra lanes
                lanes = self._hot_lanes.get(i, 1)
                if all(self._lane_pending_locked(i, lane)
                       >= self.max_pending_pages
                       for lane in range(lanes)):
                    return True
        return False

    # -- streaming consumer side ----------------------------------------

    def poll(self, partition: int, consumer_id: int = 0) -> Optional[Page]:
        import time as _time

        with self._lock:
            if self.broadcast:
                cur = self._cursors.get((0, consumer_id), 0)
                ps = self._pages[0]
                if cur < len(ps):
                    self._cursors[(0, consumer_id)] = cur + 1
                    page = ps[cur]
                else:
                    return None
            else:
                page = None
                lanes = self._hot_lanes.get(partition, 1)
                start = self._drain_rr.get(partition, 0)
                for probe in range(lanes):
                    lane = (start + probe) % lanes
                    ps = self._pages[partition] if lane == 0 \
                        else self._lane_pages[(partition, lane)]
                    ckey = (partition, "drain") if lane == 0 \
                        else (partition, "drain", lane)
                    cur = self._cursors.get(ckey, 0)
                    if cur < len(ps):
                        self._cursors[ckey] = cur + 1
                        page = ps[cur]
                        # single-consumer partition: release the slot
                        # so the exchange doesn't pin the whole
                        # intermediate dataset for the query's lifetime
                        ps[cur] = None
                        self._drain_rr[partition] = lane + 1
                        break
                if page is None:
                    return None
            if self.first_poll_ts is None:
                self.first_poll_ts = _time.monotonic()
            fired = self._bump_locked()  # space freed: wake producers
        for cb in fired:
            cb()
        return page

    def _drained_locked(self, partition: int) -> bool:
        return all(self._lane_pending_locked(partition, lane) <= 0
                   for lane in range(self._hot_lanes.get(partition, 1)))

    def at_end(self, partition: int, consumer_id: int = 0) -> bool:
        with self._lock:
            if not self._no_more:
                return False
            if self.broadcast:
                return self._cursors.get((0, consumer_id), 0) >= \
                    len(self._pages[0])
            return self._drained_locked(partition)

    def has_page(self, partition: int, consumer_id: int = 0) -> bool:
        with self._lock:
            if self.broadcast:
                return self._cursors.get((0, consumer_id), 0) < \
                    len(self._pages[0])
            return not self._drained_locked(partition)

    def channel(self, partition: int, consumer_id: int = 0):
        return ExchangeChannel(self, partition, consumer_id)

    # -- barrier consumer side (legacy snapshot) -------------------------

    def pages(self, partition: int) -> List[Page]:
        with self._lock:
            tgt = 0 if self.broadcast else partition
            out = [p for p in self._pages[tgt] if p is not None]
            if not self.broadcast:
                for lane in range(1, self._hot_lanes.get(tgt, 1)):
                    out.extend(p for p in self._lane_pages[(tgt, lane)]
                               if p is not None)
            return out

    @property
    def total_rows(self) -> int:
        with self._lock:
            return self._total_rows

    @property
    def stats(self) -> dict:
        """Host-path exchange skew stats — the SAME surface as
        ``DeviceExchange.stats`` (partition_rows / skew_ratio / rows),
        with device-only fields pinned to host values, so EXPLAIN
        ANALYZE renders stage boundaries identically on both paths."""
        with self._lock:
            rows = list(self._partition_rows)
            hot = dict(self._hot_lanes)
        mean_rows = (sum(rows) / len(rows)) if rows else 0.0
        out = {
            "kind": "host",
            "sizing": None,
            "per_dest": None,
            "a2a_retries": 0,
            "count_collectives": 0,
            "data_collectives": 0,
            "rows": sum(rows),
            "partition_rows": rows,
            "skew_ratio": (round(max(rows) / mean_rows, 3)
                           if mean_rows > 0 else 0.0),
            # device-path parity (DeviceExchange.stats): which
            # partitions went hot and how wide their lanes spread
            "hot_partitions": sorted(hot),
            "splits": len(hot),
            "split_ways": max(hot.values()) if hot else 1,
            "hot_spread": hot,
        }
        if self.rebalancer is not None:
            out.update(self.rebalancer.stats())
        return out

    @property
    def overlapped(self) -> bool:
        """True iff a consumer dequeued a page while producers were
        still running (the streaming-overlap witness)."""
        return self.first_poll_ts is not None and (
            self.no_more_ts is None
            or self.first_poll_ts < self.no_more_ts)


def wait_readable(buffer: OutputBuffer, timeout: float = 0.25):
    """Block the calling thread until the buffer's state version moves
    (page enqueued/drained, no-more, abort) or the timeout passes — the
    thread-world adapter used by the worker's long-poll result server."""
    ev = threading.Event()
    buffer.listen().on_ready(ev.set)
    ev.wait(timeout)


class ExchangeChannel:
    """One consumer's view of an OutputBuffer partition — the streaming
    handle ExchangeSourceOperator drives (reference:
    operator/DirectExchangeClient.java)."""

    __slots__ = ("buffer", "partition", "consumer_id")

    def __init__(self, buffer: OutputBuffer, partition: int,
                 consumer_id: int):
        self.buffer = buffer
        self.partition = partition
        self.consumer_id = consumer_id

    def poll(self) -> Optional[Page]:
        return self.buffer.poll(self.partition, self.consumer_id)

    def at_end(self) -> bool:
        return self.buffer.at_end(self.partition, self.consumer_id)

    def has_page(self) -> bool:
        return self.buffer.has_page(self.partition, self.consumer_id)

    def listen(self) -> ListenToken:
        return self.buffer.listen()


class PartitionedOutputOperator(Operator):
    """Routes each row of the input to an output-buffer partition.
    kind: 'hash' (by key columns), 'single' (partition 0), 'broadcast',
    'merge' (everything to this task's OWN partition so the consumer
    sees one sorted stream per producer).
    """

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], buffer: OutputBuffer,
                 kind: str = "hash", task_partition: int = 0,
                 rebalancer=None, hot_split_threshold: float = 0.5):
        assert kind in ("hash", "single", "broadcast", "merge")
        self.input_types = list(input_types)
        self.key_channels = list(key_channels)
        self.buffer = buffer
        self.kind = kind
        self.task_partition = task_partition
        #: host analog of the device collective's hot-partition split
        #: (round 16): when one partition's observed share of this
        #: producer's rows exceeds the threshold on a BOUNDED buffer,
        #: the partition grows extra drain lanes so its pending-page
        #: bound scales like the device path's receiver spread.  Hash
        #: kind ONLY — merge streams are sorted and must not interleave
        #: — and never under a rebalancer (scaled writers already
        #: spread hot partitions across lanes).
        self.hot_split_threshold = float(hot_split_threshold)
        self._observed_rows: Optional[np.ndarray] = None
        #: scaled-writer boundary: a UniformPartitionRebalancer mapping
        #: MORE logical hash partitions than writer lanes; hot logical
        #: partitions are scaled across several lanes (rows round-robin
        #: within the assigned set), re-assigned from observed counts
        #: (reference: ScaleWriterPartitioningExchanger). Writer lanes
        #: don't need key co-location, so remapping is free to chase
        #: balance — the generic hash path must NOT set this.
        self.rebalancer = rebalancer
        #: per-logical-partition round-robin cursor, persistent ACROSS
        #: pages — restarting at lane 0 each page would concentrate a
        #: scaled partition's rows on its first lane under small pages
        #: (the reference exchanger keeps this counter per partition)
        self._rr: Dict[int, int] = {}
        self._done = False
        self._lut_cache: Dict[tuple, np.ndarray] = {}

    def needs_input(self) -> bool:
        # backpressure: stall the pipeline while any destination
        # partition has too many undrained pages
        return not self._finishing and not self.buffer.full()

    def blocked_token(self):
        if self._finishing:
            return None
        # snapshot-then-recheck: a drain between full() and listen()
        # must not park us on a version that never moves again
        token = self.buffer.listen()
        return token if self.buffer.full() else None

    def add_input(self, page: DevicePage):
        n = self.buffer.num_partitions
        if self.kind == "merge":
            self.buffer.enqueue(self.task_partition, page.to_page())
            return
        if self.kind != "hash" or n == 1:
            host = page.to_page()
            self.buffer.enqueue(0, host)
            return
        keys_u64 = []
        for c in self.key_channels:
            t = page.types[c]
            lut = None
            if t.is_string:
                d = page.dictionaries[c]
                key = (id(d), len(d) if d is not None else 0)
                lut = self._lut_cache.get(key)
                if lut is None:
                    lut = string_hash_lut(d)
                    self._lut_cache[key] = lut
                lut = jnp.asarray(lut)
            keys_u64.append(key_to_u64(page.cols[c], page.nulls[c], t, lut))
        n_logical = self.rebalancer.n if self.rebalancer is not None else n
        part = np.asarray(hash_partition_ids(keys_u64, n_logical))
        valid = np.asarray(page.valid)
        if self.rebalancer is not None:
            part = self._rebalanced_lanes(part, valid)
        elif self.hot_split_threshold < 1.0 and n > 1 and \
                self.buffer.max_pending_pages is not None:
            self._split_hot(np.bincount(part[valid], minlength=n)[:n])
        cols = [np.asarray(c) for c in page.cols]
        nulls = [np.asarray(x) for x in page.nulls]
        for p in range(n):
            idx = np.nonzero(valid & (part == p))[0]
            if len(idx) == 0:
                continue
            blocks = []
            for t, c, nl, d in zip(page.types, cols, nulls,
                                   page.dictionaries):
                bn = nl[idx]
                blocks.append(Block(t, c[idx], bn if bn.any() else None, d))
            self.buffer.enqueue(p, Page(blocks, len(idx)))

    def _split_hot(self, page_rows: np.ndarray):
        """Accumulate this producer's per-partition row histogram and
        grow lanes for any partition above the hot threshold — the same
        observed-share trigger as DeviceExchange's count-pass split,
        applied to the host buffer's capacity bounds."""
        if self._observed_rows is None:
            self._observed_rows = page_rows.astype(np.int64)
        else:
            self._observed_rows += page_rows
        total = int(self._observed_rows.sum())
        if total == 0:
            return
        ways = max(2, self.buffer.num_partitions)
        for p in np.nonzero(self._observed_rows / total
                            > self.hot_split_threshold)[0]:
            self.buffer.split_partition(int(p), ways)

    def _rebalanced_lanes(self, part: np.ndarray,
                          valid: np.ndarray) -> np.ndarray:
        """Logical partition ids -> writer lanes through the current
        rebalancer assignment; feeds the observation that adapts it.
        Scaled partitions round-robin their rows across the assigned
        lane set by row position (deterministic)."""
        reb = self.rebalancer
        reb.observe(np.bincount(part[valid], minlength=reb.n)[:reb.n])
        assignment = reb.assignment()
        first_lane = np.asarray([lanes[0] for lanes in assignment],
                                dtype=part.dtype)
        lane = first_lane[part]
        for lp, lanes in enumerate(assignment):
            if len(lanes) <= 1:
                continue
            idx = np.nonzero(valid & (part == lp))[0]
            if len(idx):
                start = self._rr.get(lp, 0)
                lane[idx] = np.asarray(lanes)[
                    (start + np.arange(len(idx))) % len(lanes)]
                self._rr[lp] = (start + len(idx)) % len(lanes)
        return lane

    def metrics(self) -> Optional[dict]:
        """Host-path exchange stats for OperatorStats (hash kind only:
        single/broadcast/merge routing has no skew to observe).
        Rebalancer counters already ride buffer.stats — the buffer is
        the one merge point."""
        if self.kind != "hash":
            return None
        return self.buffer.stats

    def get_output(self):
        if self._finishing:
            self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done


class ExchangeSourceOperator(SourceOperator):
    """Reads this task's partition of an upstream fragment's output
    (reference: operator/ExchangeOperator.java).

    Two source modes, decided by what the planner's exchange_reader
    hands over:
    - a CALLABLE (barrier mode): a thunk returning the full page list
      once the producing stage finished; string columns re-encode into
      one pool via Page.concat;
    - an object with ``poll``/``at_end``/``listen`` (streaming mode,
      e.g. ExchangeChannel): pages are consumed as producers enqueue
      them, each re-encoded INCREMENTALLY into stable per-channel pools
      (downstream kernels require one pool per channel across pages);
      when no page is available the operator reports a blocked token so
      the task executor parks the task instead of spinning."""

    def __init__(self, pages_thunk, types_: Sequence[T.Type],
                 source_fragment: Optional[int] = None):
        self._streaming = hasattr(pages_thunk, "poll")
        self._chan = pages_thunk if self._streaming else None
        self._thunk = None if self._streaming else pages_thunk
        self.types = list(types_)
        #: producing fragment id (EXPLAIN ANALYZE attribution of the
        #: exchange metrics below)
        self.source_fragment = source_fragment
        self._pages: Optional[List[Page]] = None
        self._done = False
        #: streaming: the stable target pool per pooled channel — the
        #: first arriving page's pool; later pages remap into it
        self._target_dicts: List[Optional[Dictionary]] = \
            [None] * len(self.types)

    def add_split(self, split):
        raise AssertionError("exchange source has no splits")

    def metrics(self) -> Optional[dict]:
        """The upstream exchange's skew stats, read from the consumer
        side — by the time this driver finishes, the collective has run
        (device path) / all producers enqueued (host path)."""
        chan = self._chan
        stats = None
        if chan is not None:
            stats = getattr(chan, "stats", None)
            if stats is None:
                buf = getattr(chan, "buffer", None)
                stats = getattr(buf, "stats", None)
        if stats and self.source_fragment is not None:
            stats = dict(stats)
            stats["source_fragment"] = self.source_fragment
        return stats

    def blocked_token(self):
        if self._streaming and not self._done:
            token = self._chan.listen()
            # re-check AFTER snapshotting the version: a page/no_more
            # arriving between poll() and listen() must not park us
            if self._chan.at_end() or self._chan.has_page():
                return None
            return token
        return None

    def _reencode(self, page: Page) -> Page:
        """Remap pooled columns into the stable target pools (host-side
        LUT gathers; target pools grow via Dictionary.code)."""
        blocks = []
        changed = False
        for c, t in enumerate(self.types):
            b = page.block(c).numpy()
            if not t.is_pooled or b.dictionary is None:
                blocks.append(b)
                continue
            tgt = self._target_dicts[c]
            if tgt is None:
                self._target_dicts[c] = b.dictionary
                blocks.append(b)
                continue
            if b.dictionary is tgt:
                blocks.append(b)
                continue
            remap = (np.asarray(tgt.encode(list(b.dictionary.values)),
                                dtype=np.int32)
                     if len(b.dictionary) else np.zeros(1, np.int32))
            blocks.append(Block(t, remap[b.data], b.nulls, tgt))
            changed = True
        return Page(blocks, page.num_rows) if changed else page

    def get_output(self) -> Optional[DevicePage]:
        if self._streaming:
            item = self._chan.poll()
            if item is not None:
                if isinstance(item, DevicePage):
                    return item  # device collective: pools pre-unified
                return DevicePage.from_page(self._reencode(item))
            if self._chan.at_end():
                self._done = True
            return None
        if self._pages is None:
            items = self._thunk()
            if items and isinstance(items[0], DevicePage):
                # device-collective exchange: rows arrived by all_to_all
                # with unified pools — pass straight through
                self._pages = list(items)
            else:
                pages = [p for p in items if p.num_rows]
                if pages and any(t.is_string for t in self.types):
                    pages = [Page.concat(pages)]
                self._pages = pages
        if self._pages:
            nxt = self._pages.pop(0)
            if isinstance(nxt, DevicePage):
                return nxt
            return DevicePage.from_page(nxt)
        self._done = True
        return None

    def is_finished(self) -> bool:
        return self._done
