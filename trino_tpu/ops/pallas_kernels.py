"""Pallas TPU kernels for the hot grouping path.

SURVEY.md §7 names the group-by scatter ("segment reduce over sorted
group ids") as the one native kernel of the build: it sits under every
GROUP BY (ops/aggregation._group_reduce) and under the distinct /
first-row machinery. Reference analog: the row-at-a-time update loops of
``operator/MultiChannelGroupByHash.java:199-294`` and
``operator/aggregation/*Accumulator`` — redesigned here for the TPU
memory system instead of translated.

Kernel design (TPU-first, not a scatter):
  After the engine's bucket sort, group ids are NON-DECREASING WITH
  STEPS OF AT MOST 1 (they are a cumsum of boundary bits). So a chunk of
  C consecutive rows touches at most C consecutive segments, and every
  contribution of chunk i lands inside a single 128-aligned window of
  the output that starts at ``align_down(gid[i*C])``. That turns the
  scatter-add into:
    - grid over row chunks (sequential on a TensorCore, so read-modify-
      write accumulation into the output block is race-free),
    - per chunk, a one-hot (C x W) binning matrix against the window,
    - SUM: two MXU matmuls on a hi/lo 16-bit split (exact for int32 and
      for float32 inputs that are int-valued), or one for floats,
    - MIN/MAX: masked VPU reduce over the same one-hot,
    - one dynamic-slice update of the aligned window — contiguous, tile-
      aligned, no scatter unit needed.
  The scalar-prefetch operand carries each chunk's window start so the
  index map / store offset is known before the chunk's data arrives.

Dispatch: ``segment_reduce`` uses the Pallas kernel when the default
backend is TPU (or when TRINO_TPU_PALLAS forces it — tests run it in
interpret mode on CPU) and the dtype is int32/float32; anything else
takes the identical-semantics ``jax.ops.segment_*`` path. Both paths are
cross-checked in tests/test_pallas_kernels.py.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..telemetry.profiler import instrument

_CHUNK = 512          # rows per grid step
_LANE = 128           # TPU lane width: window starts are lane-aligned
_WIN = _CHUNK + _LANE  # aligned window covering any chunk's segments


def pallas_mode() -> str:
    """'tpu' (compiled), 'interpret' (forced, CPU), or '' (disabled)."""
    # trace-static mode switch: read once per compile, by design
    forced = os.environ.get(  # qlint: ignore[trace-purity, cache-coherence] trace-static process-mode knob, read once per compile by design
        "TRINO_TPU_PALLAS", "")
    if forced in ("0", "off"):
        return ""
    try:
        backend = jax.default_backend()
    except Exception:  # backend init failure: the caller's problem
        return ""
    if backend == "tpu":
        return "tpu"
    if forced:
        return "interpret"
    return ""


#: dtypes the compiled TPU kernel handles; 64-bit dtypes additionally
#: run under interpret mode (CPU tests with x64 — on TPU hardware f64
#: does not exist and the engine runs 32-bit storage)
_SUPPORTED = ("int32", "float32")
_SUPPORTED_INTERPRET = _SUPPORTED + ("int64", "float64", "uint64")

_IDENTITY = {
    ("sum", "int32"): 0,
    ("sum", "float32"): 0.0,
    ("sum", "int64"): 0,
    ("sum", "uint64"): 0,
    ("sum", "float64"): 0.0,
    ("min", "int32"): np.iinfo(np.int32).max,
    ("min", "float32"): np.inf,
    ("min", "int64"): np.iinfo(np.int64).max,
    ("min", "uint64"): np.iinfo(np.uint64).max,
    ("min", "float64"): np.inf,
    ("max", "int32"): np.iinfo(np.int32).min,
    ("max", "float32"): -np.inf,
    ("max", "int64"): np.iinfo(np.int64).min,
    ("max", "uint64"): 0,
    ("max", "float64"): -np.inf,
}

#: process-wide count of kernel executions (test observability)
kernel_calls = 0


def _kernel(starts_ref, col_ref, gid_ref, out_ref, *, kind: str,
            dtype: str, n_chunks: int):
    i = pl.program_id(0)
    ident = _IDENTITY[(kind, dtype)]

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.full(out_ref.shape, ident, out_ref.dtype)

    start = starts_ref[i]
    col = col_ref[0, 0, :]                   # (C,)
    local = gid_ref[0, 0, :] - start         # (C,) window offsets
    in_win = (local >= 0) & (local < _WIN)
    # one-hot binning matrix: onehot[r, w] == row r feeds window slot w
    wslots = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK, _WIN), 1)
    onehot = (local[:, None] == wslots) & in_win[:, None]

    if kind == "sum":
        if dtype in ("int64", "uint64", "float64"):
            # interpret-mode-only path (64-bit never reaches the TPU
            # kernel): masked add keeps int64 sums exact
            contrib = jnp.where(onehot, col[:, None],
                                jnp.asarray(0, col.dtype))
            win = jnp.sum(contrib, axis=0)
        elif dtype == "int32":
            # exact int32 via three f32 MXU passes on a 12/12/8-bit
            # split: every per-chunk part-sum is bounded by C * 2^12 =
            # 2^21 (lo/mid) or C * 2^7 = 2^16 (hi), all far inside
            # f32's 2^24 exact-integer range
            oh = onehot.astype(jnp.float32)

            def dot(v):
                # HIGHEST precision: the default lowers f32 MXU matmuls
                # to bf16 passes whose 8-bit mantissa would round the
                # 12-bit parts — the exactness argument needs true f32
                return jax.lax.dot_general(
                    v[None, :], oh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)[0]

            lo_s = dot((col & 0xFFF).astype(jnp.float32))
            mid_s = dot(((col >> 12) & 0xFFF).astype(jnp.float32))
            hi_s = dot(jnp.right_shift(col, 24).astype(jnp.float32))
            win = ((hi_s.astype(jnp.int32) << 24)
                   + (mid_s.astype(jnp.int32) << 12)
                   + lo_s.astype(jnp.int32))
        else:
            oh = onehot.astype(jnp.float32)
            win = jax.lax.dot_general(
                col[None, :], oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)[0]
        upd = out_ref[0, pl.dslice(start, _WIN)] + win
    else:
        contrib = jnp.where(onehot, col[:, None],
                            jnp.asarray(ident, col.dtype))
        # pairwise halving tree instead of reduce_min/max: Mosaic has no
        # integer reduction lowering, but elementwise minimum/maximum
        # lowers for every dtype; _CHUNK is a power of two
        op = jnp.minimum if kind == "min" else jnp.maximum
        while contrib.shape[0] > 1:
            half = contrib.shape[0] // 2
            contrib = op(contrib[:half], contrib[half:])
        win = contrib[0]
        cur = out_ref[0, pl.dslice(start, _WIN)]
        upd = jnp.minimum(cur, win) if kind == "min" \
            else jnp.maximum(cur, win)
    out_ref[0, pl.dslice(start, _WIN)] = upd


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "kind", "interpret"))
def _segment_reduce_pallas(col, gid, num_segments: int, kind: str,
                           interpret: bool):
    from .. import jit_stats

    jit_stats.bump("segment_reduce_pallas")
    n = col.shape[0]
    dtype = str(col.dtype)
    ident = _IDENTITY[(kind, dtype)]
    n_chunks = max(1, -(-n // _CHUNK))
    n_pad = n_chunks * _CHUNK
    # output sized so every clamped window fits; padding rows carry an
    # out-of-window gid so they contribute nothing
    s_alloc = ((num_segments + _LANE - 1) // _LANE) * _LANE + _WIN
    if n_pad != n:
        col = jnp.concatenate(
            [col, jnp.full((n_pad - n,), ident, col.dtype)])
        gid = jnp.concatenate(
            [gid, jnp.full((n_pad - n,), s_alloc, gid.dtype)])
    gid = gid.astype(jnp.int32)
    starts = jnp.clip((gid[::_CHUNK] // _LANE) * _LANE, 0, s_alloc - _WIN)

    # chunks are blocked as (1, 1, C) windows of a (n_chunks, 1, C)
    # array: Mosaic requires each of the last two BLOCK dims to be
    # divisible by the (8, 128) tile or equal to the array dim — the
    # former 2-D (1, C) block over a (n_chunks, C) array violated the
    # sublane rule whenever n_chunks > 1 and only ever lowered in
    # interpret mode (caught by the AOT lowering smoke test)
    out = pl.pallas_call(
        functools.partial(_kernel, kind=kind, dtype=dtype,
                          n_chunks=n_chunks),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((1, 1, _CHUNK), lambda i, s: (i, 0, 0)),
                pl.BlockSpec((1, 1, _CHUNK), lambda i, s: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, s_alloc), lambda i, s: (0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((1, s_alloc), col.dtype),
        interpret=interpret,
    )(starts, col.reshape(n_chunks, 1, _CHUNK),
      gid.reshape(n_chunks, 1, _CHUNK))
    return out[0, :num_segments]


# profiled entry point (telemetry.profiler): the Pallas program's
# cost/compile attribution when called from host (inside another
# trace the wrapper stages out inline); plain call when off
_segment_reduce_pallas = instrument(
    "segment_reduce_pallas", _segment_reduce_pallas,
    static_argnames=("num_segments", "kind", "interpret"))


def segment_reduce(col, gid, num_segments: int, kind: str,
                   mode: str = None):
    """Segment reduction over SORTED group ids (steps of <= 1, larger
    jumps only into discarded trailing segments). Drop-in for
    ``jax.ops.segment_{sum,min,max}`` on the engine's grouping path;
    auto-selects the Pallas kernel on TPU.

    ``mode``: pass the caller's pallas_mode() when calling from inside a
    jitted function whose cache key includes it — re-deriving the mode
    at trace time would bake the first-seen mode into every later cache
    hit."""
    if mode is None:
        mode = pallas_mode()
    ok = _SUPPORTED if mode == "tpu" else _SUPPORTED_INTERPRET
    if mode and str(col.dtype) in ok:
        global kernel_calls
        kernel_calls += 1
        return _segment_reduce_pallas(col, gid, num_segments, kind,
                                      interpret=(mode != "tpu"))
    if kind == "sum":
        return jax.ops.segment_sum(col, gid, num_segments=num_segments)
    if kind == "min":
        return jax.ops.segment_min(col, gid, num_segments=num_segments)
    return jax.ops.segment_max(col, gid, num_segments=num_segments)
