"""Sort + TopN operators.

Reference analog: ``operator/OrderByOperator.java`` (PagesIndex + compiled
PagesIndexOrdering) and ``operator/TopNOperator.java``.

TPU redesign: ordering keys normalize to (null-bit, u64) operand pairs
(ops/sortkeys.py) and the whole batch sorts in one ``lax.sort`` carrying
all payload columns. TopN keeps a running device-resident top-N: each
incoming page concatenates with the current candidates, sorts, truncates —
memory stays O(N + page).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, padded_size
from ..telemetry.profiler import instrument
from .operator import Operator
from .sortkeys import SortKey, sort_operands


@partial(jax.jit, static_argnames=("num_key_ops",))
def _sorted_by(key_ops, cols, nulls, valid, num_key_ops: int):
    """Sort carrying all columns; invalid lanes last."""
    from .. import jit_stats

    jit_stats.bump("sort_by")
    operands = [(~valid).astype(jnp.uint8)] + list(key_ops) + list(cols) \
        + list(nulls) + [valid]
    s = jax.lax.sort(operands, num_keys=1 + num_key_ops, is_stable=True)
    n = len(cols)
    base = 1 + num_key_ops
    return (tuple(s[base:base + n]), tuple(s[base + n:base + 2 * n]),
            s[-1])


# profiled entry point (telemetry.profiler): cost/compile attribution
# under EXPLAIN ANALYZE VERBOSE; a plain call when profiling is off
_sorted_by = instrument("sort_by", _sorted_by,
                        static_argnames=("num_key_ops",))


def _make_key_ops(page: DevicePage, keys: Sequence[SortKey]):
    ops = []
    for k in keys:
        ops.extend(sort_operands(
            page.cols[k.channel], page.nulls[k.channel],
            page.types[k.channel], page.dictionaries[k.channel],
            ascending=k.ascending,
            nulls_last=k.nulls_last if k.nulls_last is not None
            else k.ascending))
    return tuple(ops)


def _concat_pages(pages: List[DevicePage], cap: int) -> DevicePage:
    from ..block import unify_dictionaries

    types = pages[0].types
    dicts = unify_dictionaries(pages, len(types))
    cols, nulls = [], []
    for i in range(len(types)):
        cols.append(_pad(jnp.concatenate([p.cols[i] for p in pages]), cap))
        nulls.append(_pad(jnp.concatenate([p.nulls[i] for p in pages]), cap,
                          fill=True))
    valid = _pad(jnp.concatenate([p.valid for p in pages]), cap)
    return DevicePage(types, cols, nulls, valid, dicts)


def _pad(arr, cap, fill=False):
    n = arr.shape[0]
    if n == cap:
        return arr
    if arr.dtype == bool:
        pad = jnp.full((cap - n,), fill, dtype=bool)
    else:
        pad = jnp.zeros((cap - n,), dtype=arr.dtype)
    return jnp.concatenate([arr, pad])


class OrderByOperator(Operator):
    """Full sort at finish (reference: OrderByOperator.java)."""

    def __init__(self, input_types: Sequence[T.Type],
                 sort_keys: Sequence[SortKey], memory_context=None):
        self.input_types = list(input_types)
        self.sort_keys = list(sort_keys)
        self._pages: List = []  # DevicePage | SpilledPage
        self._out: List[DevicePage] = []
        self._emitted = False
        self._done = False
        self._ctx = memory_context
        if self._ctx is not None:
            self._ctx.set_revoke_callback(self._revoke)

    def add_input(self, page: DevicePage):
        if self._ctx is None:
            self._pages.append(page)
            return
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._pages, page)

    def _revoke(self) -> int:
        from ..exec.memory import spill_pages

        return spill_pages(self._pages, self._ctx.pool, self._ctx.lock)

    def _pop_out(self) -> DevicePage:
        item = self._out.pop(0)
        # host-sorted chunks upload lazily, one per quantum, so the
        # full sorted relation is never device-resident at once
        return item() if callable(item) else item

    def get_output(self) -> Optional[DevicePage]:
        if self._out:
            return self._pop_out()
        if not self._finishing or self._emitted:
            if self._emitted:
                self._done = True
            return None
        self._emitted = True
        if not self._pages:
            self._done = True
            return None
        self._out = self._sort_all()
        self._pages = []
        if self._ctx is not None:
            self._ctx.close()
        if self._out:
            return self._pop_out()
        self._done = True
        return None

    def _sort_all(self) -> List[DevicePage]:
        from ..exec.memory import SpilledPage, device_page_bytes

        if self._ctx is not None:
            from ..exec.memory import prepare_finish

            pool = self._ctx.pool
            total, uploads = prepare_finish(self._ctx, self._pages)
            if pool.reserved + uploads + 2 * total > pool.max_bytes:
                # the whole-input device sort cannot fit alongside the
                # pool's other reservations: host-merge path
                return self._host_sort(pool.max_bytes // 4)
            # transient: uploads + concat + sorted copy; released when
            # the sorted pages flow downstream
            self._ctx.reserve(uploads + 2 * total, revocable=False)
        self._pages = [p.to_device() if isinstance(p, SpilledPage) else p
                       for p in self._pages]
        cap = padded_size(sum(p.capacity for p in self._pages))
        page = _concat_pages(self._pages, cap)
        key_ops = _make_key_ops(page, self.sort_keys)
        cols, nulls, valid = _sorted_by(key_ops, tuple(page.cols),
                                        tuple(page.nulls), page.valid,
                                        num_key_ops=len(key_ops))
        return [DevicePage(page.types, list(cols), list(nulls), valid,
                           page.dictionaries)]

    def _host_sort(self, chunk_budget: int) -> List[DevicePage]:
        """Bounded-HBM sort: per page, compute the order-encoding key
        operands on device (a small per-page kernel), download the live
        rows, then lexsort on host and re-emit the ordered rows as
        budget-sized DevicePages.  Device residency is one page + one
        output chunk; the full relation lives in host RAM — the same
        spill domain the revoke path uses (reference analog:
        OrderByOperator's spill-merge via FileSingleStreamSpiller,
        with host RAM standing in for disk)."""
        from ..exec.memory import SpilledPage, device_page_bytes

        from ..block import unify_dictionaries

        host_cols: List[List[np.ndarray]] = []
        host_nulls: List[List[np.ndarray]] = []
        host_ops: List[List[np.ndarray]] = []
        dicts = unify_dictionaries(self._pages, len(self.input_types))
        for p in self._pages:
            nb = device_page_bytes(p)
            if self._ctx is not None:
                # one page resident at a time (plus its key operands)
                self._ctx.reserve(2 * nb, revocable=False)
            dev = p.to_device() if isinstance(p, SpilledPage) else p
            ops = _make_key_ops(dev, self.sort_keys)
            keep = np.nonzero(np.asarray(dev.valid))[0]
            host_cols.append([np.asarray(c)[keep] for c in dev.cols])
            host_nulls.append([np.asarray(n)[keep] for n in dev.nulls])
            host_ops.append([np.asarray(o)[keep] for o in ops])
            if self._ctx is not None:
                self._ctx.free(2 * nb)
        nch = len(self.input_types)
        cols = [np.concatenate([pc[i] for pc in host_cols])
                for i in range(nch)]
        nulls = [np.concatenate([pn[i] for pn in host_nulls])
                 for i in range(nch)]
        nops = len(host_ops[0])
        ops = [np.concatenate([po[j] for po in host_ops])
               for j in range(nops)]
        # np.lexsort: LAST key is primary -> reverse the operand order
        order = np.lexsort(tuple(reversed(ops))) if ops else np.arange(0)
        n = order.shape[0]
        # output chunk rows sized so a chunk stays within the budget
        row_bytes = max(1, sum(c.dtype.itemsize + 1 for c in cols) + 1)
        chunk_rows = max(1024, chunk_budget // (2 * row_bytes))
        out: List = []
        types_ = list(self.input_types)

        def make_chunk(idx):
            # deferred: uploads when the driver pulls this chunk, so one
            # chunk is device-resident at a time
            def thunk():
                k = idx.shape[0]
                cap = padded_size(k)
                ccols, cnulls = [], []
                for c, nl in zip(cols, nulls):
                    cc = np.zeros(cap, dtype=c.dtype)
                    cc[:k] = c[idx]
                    nn = np.zeros(cap, dtype=bool)
                    nn[:k] = nl[idx]
                    ccols.append(jnp.asarray(cc))
                    cnulls.append(jnp.asarray(nn))
                v = np.zeros(cap, dtype=bool)
                v[:k] = True
                return DevicePage(types_, ccols, cnulls, jnp.asarray(v),
                                  list(dicts))

            return thunk

        for s in range(0, n, chunk_rows):
            out.append(make_chunk(order[s:s + chunk_rows]))
        return out

    def is_finished(self) -> bool:
        return self._done


class TopNOperator(Operator):
    """ORDER BY ... LIMIT n with bounded memory (reference:
    TopNOperator.java / GroupedTopNBuilder)."""

    def __init__(self, input_types: Sequence[T.Type],
                 sort_keys: Sequence[SortKey], n: int):
        self.input_types = list(input_types)
        self.sort_keys = list(sort_keys)
        self.n = n
        self._top: Optional[DevicePage] = None
        self._emitted = False
        self._done = False

    def add_input(self, page: DevicePage):
        pages = [self._top, page] if self._top is not None else [page]
        cap = padded_size(sum(p.capacity for p in pages))
        merged = _concat_pages(pages, cap)
        key_ops = _make_key_ops(merged, self.sort_keys)
        cols, nulls, valid = _sorted_by(key_ops, tuple(merged.cols),
                                        tuple(merged.nulls), merged.valid,
                                        num_key_ops=len(key_ops))
        keep = padded_size(max(self.n, 16))
        if keep < cap:
            cols = tuple(c[:keep] for c in cols)
            nulls = tuple(x[:keep] for x in nulls)
            valid = valid[:keep]
        valid = valid & (jnp.arange(valid.shape[0]) < self.n)
        self._top = DevicePage(merged.types, list(cols), list(nulls), valid,
                               merged.dictionaries)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        return self._top

    def is_finished(self) -> bool:
        return self._done
