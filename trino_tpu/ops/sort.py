"""Sort + TopN operators.

Reference analog: ``operator/OrderByOperator.java`` (PagesIndex + compiled
PagesIndexOrdering) and ``operator/TopNOperator.java``.

TPU redesign: ordering keys normalize to (null-bit, u64) operand pairs
(ops/sortkeys.py) and the whole batch sorts in one ``lax.sort`` carrying
all payload columns. TopN keeps a running device-resident top-N: each
incoming page concatenates with the current candidates, sorts, truncates —
memory stays O(N + page).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, padded_size
from .operator import Operator
from .sortkeys import SortKey, sort_operands


@partial(jax.jit, static_argnames=("num_key_ops",))
def _sorted_by(key_ops, cols, nulls, valid, num_key_ops: int):
    """Sort carrying all columns; invalid lanes last."""
    operands = [(~valid).astype(jnp.uint8)] + list(key_ops) + list(cols) \
        + list(nulls) + [valid]
    s = jax.lax.sort(operands, num_keys=1 + num_key_ops, is_stable=True)
    n = len(cols)
    base = 1 + num_key_ops
    return (tuple(s[base:base + n]), tuple(s[base + n:base + 2 * n]),
            s[-1])


def _make_key_ops(page: DevicePage, keys: Sequence[SortKey]):
    ops = []
    for k in keys:
        ops.extend(sort_operands(
            page.cols[k.channel], page.nulls[k.channel],
            page.types[k.channel], page.dictionaries[k.channel],
            ascending=k.ascending,
            nulls_last=k.nulls_last if k.nulls_last is not None
            else k.ascending))
    return tuple(ops)


def _concat_pages(pages: List[DevicePage], cap: int) -> DevicePage:
    types = pages[0].types
    dicts = [None] * len(types)
    for p in pages:
        for i, d in enumerate(p.dictionaries):
            if d is not None:
                if dicts[i] is None:
                    dicts[i] = d
                elif dicts[i] is not d:
                    raise T.TrinoError(
                        "dictionary pools differ across sorted pages",
                        "GENERIC_INTERNAL_ERROR")
    cols, nulls = [], []
    for i in range(len(types)):
        cols.append(_pad(jnp.concatenate([p.cols[i] for p in pages]), cap))
        nulls.append(_pad(jnp.concatenate([p.nulls[i] for p in pages]), cap,
                          fill=True))
    valid = _pad(jnp.concatenate([p.valid for p in pages]), cap)
    return DevicePage(types, cols, nulls, valid, dicts)


def _pad(arr, cap, fill=False):
    n = arr.shape[0]
    if n == cap:
        return arr
    if arr.dtype == bool:
        pad = jnp.full((cap - n,), fill, dtype=bool)
    else:
        pad = jnp.zeros((cap - n,), dtype=arr.dtype)
    return jnp.concatenate([arr, pad])


class OrderByOperator(Operator):
    """Full sort at finish (reference: OrderByOperator.java)."""

    def __init__(self, input_types: Sequence[T.Type],
                 sort_keys: Sequence[SortKey], memory_context=None):
        self.input_types = list(input_types)
        self.sort_keys = list(sort_keys)
        self._pages: List = []  # DevicePage | SpilledPage
        self._emitted = False
        self._done = False
        self._ctx = memory_context
        if self._ctx is not None:
            self._ctx.set_revoke_callback(self._revoke)

    def add_input(self, page: DevicePage):
        if self._ctx is None:
            self._pages.append(page)
            return
        from ..exec.memory import reserve_and_append

        reserve_and_append(self._ctx, self._pages, page)

    def _revoke(self) -> int:
        from ..exec.memory import spill_pages

        return spill_pages(self._pages)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        if not self._pages:
            return None
        from ..exec.memory import SpilledPage

        if self._ctx is not None:
            from ..exec.memory import prepare_finish

            total, uploads = prepare_finish(self._ctx, self._pages)
            # transient: uploads + concat + sorted copy; released when
            # the sorted page flows downstream
            self._ctx.reserve(uploads + 2 * total, revocable=False)
        self._pages = [p.to_device() if isinstance(p, SpilledPage) else p
                       for p in self._pages]
        cap = padded_size(sum(p.capacity for p in self._pages))
        page = _concat_pages(self._pages, cap)
        key_ops = _make_key_ops(page, self.sort_keys)
        cols, nulls, valid = _sorted_by(key_ops, tuple(page.cols),
                                        tuple(page.nulls), page.valid,
                                        num_key_ops=len(key_ops))
        self._pages = []
        if self._ctx is not None:
            self._ctx.close()
        return DevicePage(page.types, list(cols), list(nulls), valid,
                          page.dictionaries)

    def is_finished(self) -> bool:
        return self._done


class TopNOperator(Operator):
    """ORDER BY ... LIMIT n with bounded memory (reference:
    TopNOperator.java / GroupedTopNBuilder)."""

    def __init__(self, input_types: Sequence[T.Type],
                 sort_keys: Sequence[SortKey], n: int):
        self.input_types = list(input_types)
        self.sort_keys = list(sort_keys)
        self.n = n
        self._top: Optional[DevicePage] = None
        self._emitted = False
        self._done = False

    def add_input(self, page: DevicePage):
        pages = [self._top, page] if self._top is not None else [page]
        cap = padded_size(sum(p.capacity for p in pages))
        merged = _concat_pages(pages, cap)
        key_ops = _make_key_ops(merged, self.sort_keys)
        cols, nulls, valid = _sorted_by(key_ops, tuple(merged.cols),
                                        tuple(merged.nulls), merged.valid,
                                        num_key_ops=len(key_ops))
        keep = padded_size(max(self.n, 16))
        if keep < cap:
            cols = tuple(c[:keep] for c in cols)
            nulls = tuple(x[:keep] for x in nulls)
            valid = valid[:keep]
        valid = valid & (jnp.arange(valid.shape[0]) < self.n)
        self._top = DevicePage(merged.types, list(cols), list(nulls), valid,
                               merged.dictionaries)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        return self._top

    def is_finished(self) -> bool:
        return self._done
