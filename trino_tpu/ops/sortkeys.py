"""Sortable-key normalization: any SQL value -> order-preserving operands.

The TPU-first replacement for the reference's compiled comparators
(``sql/gen/OrderingCompiler.java``, ``operator/PagesIndexOrdering``): instead
of runtime-generated compare functions over row addresses, every key column
becomes a pair of operands — (null-placement bit, order-preserving uint64) —
and multi-key ordering is ``lax.sort`` with ``num_keys=2k``: XLA's native
lexicographic sort. No sentinel tricks, so no collisions at type extremes.

Value encodings:
- signed ints / dates / timestamps / decimals: x XOR sign-bit bias
- doubles: IEEE-754 total-order trick (flip all bits for negatives,
  flip sign bit for non-negatives)
- booleans: 0/1
- strings: dictionary sort-rank (host LUT over the pool, device gather)
- DESC: bitwise complement of the value operand (null bit independent)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import Dictionary

_SIGN64 = np.uint64(1 << 63)


@dataclass(frozen=True)
class SortKey:
    channel: int
    ascending: bool = True
    nulls_last: bool = True  # SQL default: NULLS LAST for ASC


def _rank_lut(d: Optional[Dictionary]) -> jnp.ndarray:
    if d is None or len(d) == 0:
        return jnp.zeros(1, dtype=jnp.uint64)
    return jnp.asarray(d.sort_rank().astype(np.uint64))


def value_u64(raw, type_: T.Type, dictionary: Optional[Dictionary] = None):
    """Order-preserving uint64 encoding of raw lanes (nulls not handled).

    NOT used for DOUBLE/REAL: the TPU x64 rewriter cannot lower
    f64<->u64 bitcasts, so float keys stay float operands (lax.sort
    compares them natively); see sort_operands/group_operands.
    """
    if type_.is_pooled:
        # strings AND pooled composites (array/map/row): codes are pool
        # insertion order, so sort on the pool's value rank instead
        # (Dictionary.sort_rank totalizes tuples/None)
        return _rank_lut(dictionary)[raw]
    if type_ == T.BOOLEAN:
        return raw.astype(jnp.uint64)
    if type_ in (T.DOUBLE, T.REAL):
        raise AssertionError("float keys use native float operands")
    return raw.astype(jnp.int64).view(jnp.uint64) ^ _SIGN64


def sort_operands(raw, nulls, type_: T.Type,
                  dictionary: Optional[Dictionary] = None,
                  ascending: bool = True, nulls_last: bool = True) -> List:
    """[placement_bit_u8, key] — ascending lex order over the pair equals
    the requested SQL order. key is uint64 except for DOUBLE/REAL, which
    sort as native f64 (desc = negate; NaN sorts as +inf, i.e. largest,
    matching the engine's NaN convention)."""
    is_float = type_ in (T.DOUBLE, T.REAL)
    if is_float:
        key = jnp.asarray(raw, dtype=jnp.float64)
        key = jnp.where(jnp.isnan(key), jnp.inf, key)
        if not ascending:
            key = -key
    else:
        key = value_u64(raw, type_, dictionary)
        if not ascending:
            key = ~key
    if nulls is None:
        null_bit = jnp.zeros(raw.shape, dtype=jnp.uint8)
    else:
        bit = nulls if nulls_last else ~nulls
        null_bit = bit.astype(jnp.uint8)
        zero = 0.0 if is_float else np.uint64(0)
        key = jnp.where(nulls, zero, key)
    return [null_bit, key]


def group_operands(raw, nulls, type_: T.Type) -> List:
    """[tag_u8, key] for equality grouping: NULL is one distinct group;
    +0.0/-0.0 group together; NaNs group together (tag bit 2 marks NaN so
    float compares need no NaN-equality). Strings group by raw code —
    callers canonicalize cross-dictionary codes first."""
    if type_ in (T.DOUBLE, T.REAL):
        f = jnp.asarray(raw, dtype=jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)
        nan = jnp.isnan(f)
        key = jnp.where(nan, 0.0, f)
        tag = nan.astype(jnp.uint8) * np.uint8(2)
        if nulls is not None:
            tag = jnp.where(nulls, np.uint8(1), tag)
            key = jnp.where(nulls, 0.0, key)
        return [tag, key]
    if type_ == T.BOOLEAN:
        key = raw.astype(jnp.uint64)
    else:
        key = raw.astype(jnp.int64).view(jnp.uint64)
    if nulls is None:
        null_bit = jnp.zeros(raw.shape, dtype=jnp.uint8)
    else:
        null_bit = nulls.astype(jnp.uint8)
        key = jnp.where(nulls, np.uint64(0), key)
    return [null_bit, key]
