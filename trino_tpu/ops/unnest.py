"""UNNEST: expand pooled array columns to one row per element.

Reference analog: ``operator/unnest/UnnestOperator.java`` (12 files of
per-type unnesters). TPU redesign: arrays are dictionary codes, so the
expansion is the join-expansion pattern — per-row element counts come
from a host length-LUT over the pool, lanes expand with the cumsum/
searchsorted trick, and element values gather from a FLATTENED element
LUT (elements of pool entry c live at flat[offset[c] .. offset[c] +
len(c))). Varchar elements re-encode into a fresh element pool.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..block import DevicePage, Dictionary, padded_size
from .operator import Operator


class UnnestOperator(Operator):
    def __init__(self, input_types: Sequence[T.Type],
                 array_channels: Sequence[int],
                 element_types: Sequence[T.Type],
                 with_ordinality: bool = False):
        self.input_types = list(input_types)
        self.array_channels = list(array_channels)
        self.element_types = list(element_types)
        self.with_ordinality = with_ordinality
        self._pending: Optional[DevicePage] = None
        self._done = False
        self._luts: Dict = {}  # (chan, id(dict), len) -> lut bundle

    @property
    def output_types(self) -> List[T.Type]:
        out = list(self.input_types) + list(self.element_types)
        if self.with_ordinality:
            out.append(T.BIGINT)
        return out

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def _channel_luts(self, chan: int, d: Optional[Dictionary],
                      et: T.Type):
        """(len_lut, offset_lut, flat_values, element_dict): per-code
        array length, flat offset, and the flattened element payload."""
        key = (chan, id(d), len(d) if d is not None else 0)
        hit = self._luts.get(key)
        if hit is not None:
            return hit[:4]
        values = d.values if d is not None else []
        lens = np.asarray([len(v) for v in values] or [0],
                          dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(lens)[:-1]]) \
            if len(lens) else np.zeros(1, dtype=np.int64)
        flat: List = []
        for v in values:
            flat.extend(v)
        edict = None
        if et.is_pooled:
            edict = Dictionary()
            flat_vals = edict.encode(flat)
            enull = np.asarray([v is None for v in flat] or [False],
                               dtype=bool)
        else:
            flat_vals = np.zeros(max(len(flat), 1), dtype=et.storage)
            enull = np.zeros(max(len(flat), 1), dtype=bool)
            for i, v in enumerate(flat):
                if v is None:
                    enull[i] = True
                elif et.is_decimal:
                    flat_vals[i] = et.to_raw(v)
                else:
                    flat_vals[i] = v
        bundle = (jnp.asarray(lens), jnp.asarray(offsets.astype(np.int64)),
                  (jnp.asarray(flat_vals), jnp.asarray(enull)), edict, d)
        if len(self._luts) >= 128:
            self._luts.clear()
        self._luts[key] = bundle
        return bundle[:4]

    def add_input(self, page: DevicePage):
        n = page.valid.shape[0]
        per_chan = []
        counts = jnp.zeros(n, dtype=jnp.int64)
        for ch, et in zip(self.array_channels, self.element_types):
            lens, offsets, flat, edict = self._channel_luts(
                ch, page.dictionaries[ch], et)
            live = page.valid & ~page.nulls[ch]
            clen = jnp.where(live, lens[page.cols[ch]], 0)
            counts = jnp.maximum(counts, clen)
            per_chan.append((ch, clen, offsets, flat, edict))
        total = int(jnp.sum(counts))  # one scalar sync per page
        cap = padded_size(max(total, 16))
        probe_idx, within, lane_valid = _expand_with_pos(counts, cap)

        out_cols = [c[probe_idx] for c in page.cols]
        out_nulls = [x[probe_idx] for x in page.nulls]
        out_dicts = list(page.dictionaries)
        for (ch, clen, offsets, (flat_vals, flat_null), edict), et in zip(
                per_chan, self.element_types):
            pos = offsets[page.cols[ch][probe_idx]] + within
            pos = jnp.clip(pos, 0, flat_vals.shape[0] - 1)
            in_arr = within < clen[probe_idx]
            out_cols.append(flat_vals[pos].astype(et.storage))
            out_nulls.append(~in_arr | flat_null[pos])
            out_dicts.append(edict)
        if self.with_ordinality:
            out_cols.append(within + 1)
            out_nulls.append(jnp.zeros(cap, dtype=bool))
            out_dicts.append(None)
        self._pending = DevicePage(self.output_types, out_cols, out_nulls,
                                   lane_valid, out_dicts)

    def get_output(self) -> Optional[DevicePage]:
        out, self._pending = self._pending, None
        if out is None and self._finishing:
            self._done = True
        return out

    def is_finished(self) -> bool:
        return self._done


def _expand_with_pos(counts, cap: int):
    """lane j -> (source row, position within that row's expansion)."""
    off_end = jnp.cumsum(counts)
    total = off_end[-1]
    j = jnp.arange(cap, dtype=jnp.int64)
    row = jnp.searchsorted(off_end, j, side="right")
    row = jnp.clip(row, 0, counts.shape[0] - 1)
    start = off_end[row] - counts[row]
    within = j - start
    lane_valid = j < total
    return row.astype(jnp.int32), within, lane_valid
