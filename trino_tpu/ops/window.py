"""Window functions, TPU-first.

Reference analog: ``operator/WindowOperator.java`` + ``operator/window/``
(36 files: PagesIndex sort, per-partition WindowPartition driving
ranking/value/aggregate window functions row by row).

TPU redesign: one ``lax.sort`` orders the whole batch by
(partition keys, order keys); partition/peer-run boundaries come from
adjacent-row comparison; every function computes as a vectorized scan —
rank/dense_rank from boundary prefix sums, running aggregates from
segmented scans (``lax.associative_scan`` with a segment-reset
combiner), full-partition aggregates gathered from the partition-end
lane. No per-row loops, everything static-shape.

Supported frames: full partition (no ORDER BY, or UNBOUNDED..UNBOUNDED),
RANGE UNBOUNDED PRECEDING..CURRENT ROW (the SQL default with ORDER BY —
peers included via run-end gather), and ROWS frames with any bound
combination (UNBOUNDED / CURRENT ROW / k PRECEDING / k FOLLOWING).
Bounded-rows aggregates use prefix-difference for sum/count/avg and a
doubling (sparse-table) range query for min/max — O(n log n) device work
instead of per-row loops. RANGE with value offsets is not supported.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..telemetry.profiler import instrument
from ..block import DevicePage, padded_size
from ..types import TrinoError
from .operator import Operator
from .sort import _concat_pages
from .sortkeys import SortKey, group_operands, sort_operands

RANKING = {"row_number", "rank", "dense_rank", "ntile"}
VALUE_FNS = {"lag", "lead", "first_value", "last_value", "nth_value"}
AGG_FNS = {"count", "count_star", "sum", "avg", "min", "max"}


@dataclass(frozen=True)
class WindowCall:
    """One window function over the operator's shared (partition, order)
    spec. ``frame_mode``: 'partition' (whole partition), 'range' (default
    running frame incl. peers), 'rows' (exact rows). For 'rows',
    ``frame_start``/``frame_end`` are row offsets relative to the current
    row (negative = PRECEDING, positive = FOLLOWING, 0 = CURRENT ROW,
    None = UNBOUNDED); the default (None, 0) is the running frame."""

    function: str
    arg_channel: Optional[int]
    arg_type: Optional[T.Type]
    output_type: T.Type
    frame_mode: str = "range"
    offset: int = 1          # lag/lead distance; ntile buckets; nth n
    frame_start: Optional[int] = None
    frame_end: Optional[int] = 0


def resolve_window_type(function: str, arg_type: Optional[T.Type]) -> T.Type:
    if function in ("row_number", "rank", "dense_rank", "ntile",
                    "count", "count_star"):
        return T.BIGINT
    if function in ("lag", "lead", "first_value", "last_value",
                    "nth_value"):
        return arg_type
    if function == "sum":
        from .aggregation import resolve_agg_type

        return resolve_agg_type("sum", arg_type)
    if function == "avg":
        from .aggregation import resolve_agg_type

        return resolve_agg_type("avg", arg_type)
    if function in ("min", "max"):
        return arg_type
    raise TrinoError(f"unknown window function {function}",
                     "FUNCTION_NOT_FOUND")


def _seg_scan(op, x, reset):
    """Segmented inclusive scan: ``op`` accumulates within a segment,
    restarting where ``reset`` is True (classic associative segmented-scan
    combiner)."""

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    _, out = jax.lax.associative_scan(combine, (reset, x))
    return out


def _suffix_seg_scan(op, x, pend_flag):
    """Segmented scan from each partition's END backwards: out[i] =
    op-fold of x[i..partition_end]."""
    xr = jnp.flip(x)
    reset = jnp.flip(pend_flag)
    return jnp.flip(_seg_scan(op, xr, reset))


def _sparse_table(op, x):
    """Stacked doubling tables: table[k, i] = op-fold of
    x[i .. i + 2^k - 1] (clamped). O(n log n) build, O(1) range query —
    the device replacement for per-row frame loops."""
    n = x.shape[0]
    levels = [x]
    step = 1
    while step < n:
        prev = levels[-1]
        shifted = prev[jnp.minimum(jnp.arange(n) + step, n - 1)]
        levels.append(op(prev, shifted))
        step *= 2
    return jnp.stack(levels)


def _range_query(table, op, lo, hi):
    """op-fold of x[lo..hi] (lo <= hi assumed; caller masks empties) via
    two overlapping power-of-two windows."""
    length = jnp.maximum(hi - lo + 1, 1)
    # float64 log2 is exact at powers of two, so floor() is safe
    k = jnp.floor(jnp.log2(length.astype(jnp.float64))).astype(jnp.int32)
    pow2 = jnp.int64(1) << k.astype(jnp.int64)
    a = table[k, lo]
    b = table[k, jnp.maximum(hi - pow2 + 1, lo)]
    return op(a, b)


@partial(jax.jit, static_argnames=("num_part_ops", "num_order_ops",
                                   "calls"))
def _window_kernel(part_ops, order_ops, cols, nulls, valid,
                   num_part_ops: int, num_order_ops: int,
                   calls: Tuple[WindowCall, ...]):
    """Sort + compute all window outputs. Returns sorted (cols, nulls,
    valid) + per-call (raw, null) output columns."""
    from .. import jit_stats

    jit_stats.bump("window_kernel")
    n = valid.shape[0]
    operands = [(~valid).astype(jnp.uint8)] + list(part_ops) \
        + list(order_ops) + list(cols) + list(nulls) + [valid]
    s = jax.lax.sort(operands,
                     num_keys=1 + num_part_ops + num_order_ops,
                     is_stable=True)
    s_part = s[1:1 + num_part_ops]
    s_order = s[1 + num_part_ops:1 + num_part_ops + num_order_ops]
    base = 1 + num_part_ops + num_order_ops
    ncols = len(cols)
    s_cols = s[base:base + ncols]
    s_nulls = s[base + ncols:base + 2 * ncols]
    s_valid = s[-1]

    idx = jnp.arange(n, dtype=jnp.int64)
    BIG = jnp.int64(n)

    def new_run(ops):
        flag = jnp.zeros(n, dtype=bool).at[0].set(True)
        for o in ops:
            flag = flag | jnp.concatenate(
                [jnp.ones(1, dtype=bool), o[1:] != o[:-1]])
        return flag

    # validity participates in partition detection: sort puts valid rows
    # first, so the valid->padding transition starts a (dead) partition
    # and pend_idx/partition sizes never include padding lanes
    pstart = new_run(list(s_part) + [s_valid])
    rstart = pstart | new_run(s_order) if num_order_ops else pstart

    # index of the current partition/run start (cummax works: indices
    # are monotone)
    pstart_idx = jax.lax.cummax(jnp.where(pstart, idx, 0))
    rstart_idx = jax.lax.cummax(jnp.where(rstart, idx, 0))
    # index of the partition/run end (reverse cummin of flagged indices)
    pend_flag = jnp.concatenate([pstart[1:], jnp.ones(1, dtype=bool)])
    rend_flag = jnp.concatenate([rstart[1:], jnp.ones(1, dtype=bool)])
    pend_idx = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(pend_flag, idx, BIG))))
    rend_idx = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(rend_flag, idx, BIG))))
    pend_idx = jnp.clip(pend_idx, 0, n - 1)
    rend_idx = jnp.clip(rend_idx, 0, n - 1)

    row_number = idx - pstart_idx + 1

    def frame_lo_hi(call):
        """(lo, hi, empty) row-index frame bounds for one call. Python
        branching on the (static) frame spec; device arrays out."""
        if call.frame_mode == "partition":
            return pstart_idx, pend_idx, jnp.zeros(n, dtype=bool)
        if call.frame_mode == "range":
            return pstart_idx, rend_idx, jnp.zeros(n, dtype=bool)
        fs, fe = call.frame_start, call.frame_end
        lo_raw = pstart_idx if fs is None else idx + fs
        hi_raw = pend_idx if fe is None else idx + fe
        lo = jnp.maximum(lo_raw, pstart_idx)
        hi = jnp.minimum(hi_raw, pend_idx)
        empty = lo > hi
        return jnp.clip(lo, 0, n - 1), jnp.clip(hi, 0, n - 1), empty

    outs = []
    for call in calls:
        f = call.function
        if f == "row_number":
            outs.append((row_number, None))
            continue
        if f == "rank":
            outs.append((rstart_idx - pstart_idx + 1, None))
            continue
        if f == "dense_rank":
            prefix = jnp.cumsum(rstart.astype(jnp.int64))
            at_pstart = jax.lax.cummax(jnp.where(pstart, prefix, 0))
            outs.append((prefix - at_pstart + 1, None))
            continue
        if f == "ntile":
            size = (pend_idx - pstart_idx + 1)
            buckets = jnp.int64(call.offset)
            outs.append((((row_number - 1) * buckets) // size + 1, None))
            continue
        if f in ("lag", "lead"):
            x = s_cols[call.arg_channel]
            xn = s_nulls[call.arg_channel]
            k = call.offset if f == "lag" else -call.offset
            src = idx - k
            in_part = (src >= pstart_idx) & (src <= pend_idx)
            src_c = jnp.clip(src, 0, n - 1)
            outs.append((jnp.where(in_part, x[src_c], x[src_c] * 0),
                         ~in_part | xn[src_c]))
            continue
        if f in ("first_value", "last_value", "nth_value"):
            x = s_cols[call.arg_channel]
            xn = s_nulls[call.arg_channel]
            lo, hi, empty = frame_lo_hi(call)
            if f == "first_value":
                pos = lo
            elif f == "last_value":
                pos = hi
            else:
                pos = lo + (call.offset - 1)
                empty = empty | (pos > hi)
            pos = jnp.clip(pos, 0, n - 1)
            outs.append((x[pos], empty | xn[pos]))
            continue

        # aggregates over the frame
        if call.arg_channel is None:       # count(*)
            xval = s_valid.astype(jnp.int64)
            live = s_valid
        else:
            x = s_cols[call.arg_channel]
            live = s_valid & ~s_nulls[call.arg_channel]
            if f in ("sum", "avg", "count"):
                dt = jnp.float64 if call.arg_type in (T.REAL, T.DOUBLE) \
                    else jnp.int64
                xval = jnp.where(live, x.astype(dt),
                                 jnp.zeros((), dtype=dt))
            else:  # min/max sentinels
                if call.arg_type in (T.REAL, T.DOUBLE):
                    sent = jnp.inf if f == "min" else -jnp.inf
                    xval = jnp.where(live, x.astype(jnp.float64), sent)
                else:
                    info = jnp.iinfo(x.dtype)
                    sent = info.max if f == "min" else info.min
                    xval = jnp.where(live, x,
                                     jnp.asarray(sent, dtype=x.dtype))

        fs, fe = call.frame_start, call.frame_end
        both_bounded = call.frame_mode == "rows" \
            and fs is not None and fe is not None
        start_bounded = call.frame_mode == "rows" and fs is not None

        if both_bounded:
            # prefix-difference for additive fns; sparse-table range
            # query for min/max (subtraction has no inverse there)
            lo, hi, empty = frame_lo_hi(call)
            pref_cnt = jnp.cumsum(live.astype(jnp.int64))
            cnt = pref_cnt[hi] - jnp.where(lo > 0, pref_cnt[lo - 1], 0)
            cnt = jnp.where(empty, 0, cnt)
            if f in ("count", "count_star"):
                outs.append((cnt, None))
                continue
            if f in ("sum", "avg"):
                pref = jnp.cumsum(xval)
                val = pref[hi] - jnp.where(lo > 0, pref[lo - 1],
                                           jnp.zeros((), xval.dtype))
                val = jnp.where(empty, jnp.zeros((), xval.dtype), val)
            else:
                op = jnp.minimum if f == "min" else jnp.maximum
                val = _range_query(_sparse_table(op, xval), op, lo, hi)
        elif start_bounded:
            # k PRECEDING .. UNBOUNDED FOLLOWING: suffix scan at lo
            lo, hi, empty = frame_lo_hi(call)
            cnt_sfx = _suffix_seg_scan(jnp.add, live.astype(jnp.int64),
                                       pend_flag)
            cnt = jnp.where(empty, 0, cnt_sfx[lo])
            if f in ("count", "count_star"):
                outs.append((cnt, None))
                continue
            op = {"sum": jnp.add, "avg": jnp.add, "min": jnp.minimum,
                  "max": jnp.maximum}[f]
            sfx = _suffix_seg_scan(op, xval, pend_flag)
            val = sfx[lo]
            if f in ("sum", "avg"):
                val = jnp.where(empty, jnp.zeros((), xval.dtype), val)
        else:
            # running frames: forward segmented scan read at the frame
            # end (partition end / peer-run end / current row / +k rows)
            cnt_scan = _seg_scan(jnp.add, live.astype(jnp.int64), pstart)
            if f in ("count", "count_star"):
                scan = cnt_scan
            elif f in ("sum", "avg"):
                scan = _seg_scan(jnp.add, xval, pstart)
            elif f == "min":
                scan = _seg_scan(jnp.minimum, xval, pstart)
            else:
                scan = _seg_scan(jnp.maximum, xval, pstart)

            if call.frame_mode == "partition":
                at = pend_idx
                empty = jnp.zeros(n, dtype=bool)
            elif call.frame_mode == "range":
                at = rend_idx
                empty = jnp.zeros(n, dtype=bool)
            elif fe == 0:
                at = idx
                empty = jnp.zeros(n, dtype=bool)
            else:  # UNBOUNDED PRECEDING .. k ROWS (k != 0)
                hi_raw = idx + fe
                empty = hi_raw < pstart_idx
                at = jnp.clip(jnp.minimum(hi_raw, pend_idx), 0, n - 1)
            val = scan[at]
            cnt = jnp.where(empty, 0, cnt_scan[at])
            if f in ("count", "count_star"):
                outs.append((cnt, None))
                continue
            if f in ("sum", "avg"):
                val = jnp.where(empty, jnp.zeros((), xval.dtype), val)

        if f == "avg":
            if call.output_type.is_decimal:
                from ..expr.functions import div_round_half_up

                outs.append((div_round_half_up(val, jnp.maximum(cnt, 1)),
                             cnt == 0))
            else:
                outs.append((val.astype(jnp.float64)
                             / jnp.maximum(cnt, 1), cnt == 0))
        else:
            outs.append((val, cnt == 0))

    out_cols = tuple(r for r, _ in outs)
    out_nulls = tuple(jnp.zeros(n, dtype=bool) if nl is None else nl
                      for _, nl in outs)
    return s_cols, s_nulls, s_valid, out_cols, out_nulls


# profiled entry point (telemetry.profiler): cost/compile attribution
# under EXPLAIN ANALYZE VERBOSE; a plain call when profiling is off
_window_kernel = instrument(
    "window_kernel", _window_kernel,
    static_argnames=("num_part_ops", "num_order_ops", "calls"))


class WindowOperator(Operator):
    """Materializes input, sorts by (partition, order), appends one
    column per window call."""

    def __init__(self, input_types: Sequence[T.Type],
                 partition_channels: Sequence[int],
                 sort_keys: Sequence[SortKey],
                 calls: Sequence[WindowCall]):
        self.input_types = list(input_types)
        self.partition_channels = list(partition_channels)
        self.sort_keys = list(sort_keys)
        self.calls = tuple(calls)
        self._pages: List[DevicePage] = []
        self._emitted = False
        self._done = False

    @property
    def output_types(self) -> List[T.Type]:
        return self.input_types + [c.output_type for c in self.calls]

    def add_input(self, page: DevicePage):
        self._pages.append(page)

    def get_output(self) -> Optional[DevicePage]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        self._done = True
        if not self._pages:
            return None
        cap = padded_size(sum(p.capacity for p in self._pages))
        page = _concat_pages(self._pages, cap)
        part_ops: List = []
        for c in self.partition_channels:
            t = page.types[c]
            if getattr(t, "is_pooled", False):
                # partition pooled keys by value RANK (derived pools may
                # alias one value under several codes)
                from .aggregation import _rank_and_inverse

                rank_lut, _ = _rank_and_inverse(page.dictionaries[c])
                part_ops.extend(group_operands(
                    jnp.asarray(rank_lut)[page.cols[c]],
                    page.nulls[c], T.BIGINT))
            else:
                part_ops.extend(group_operands(page.cols[c],
                                               page.nulls[c], t))
        order_ops: List = []
        for k in self.sort_keys:
            order_ops.extend(sort_operands(
                page.cols[k.channel], page.nulls[k.channel],
                page.types[k.channel], page.dictionaries[k.channel],
                ascending=k.ascending, nulls_last=k.nulls_last))
        # pooled (string/array/map/row) min/max args reduce on value
        # RANKS, not raw pool codes (insertion order): append a rank
        # column per such call, retarget the call at it, and map the
        # reduced rank back to a representative code after the kernel
        import dataclasses

        from .aggregation import _rank_and_inverse

        calls = list(self.calls)
        all_cols = list(page.cols)
        all_nulls = list(page.nulls)
        restore: dict = {}
        for i, c in enumerate(calls):
            if c.function in ("min", "max") and c.arg_type is not None \
                    and c.arg_type.is_pooled:
                d = page.dictionaries[c.arg_channel]
                rank_lut, inv = _rank_and_inverse(d)
                restore[i] = (inv, d)
                calls[i] = dataclasses.replace(
                    c, arg_channel=len(all_cols), arg_type=T.BIGINT)
                all_cols.append(jnp.asarray(rank_lut)[
                    page.cols[c.arg_channel]])
                all_nulls.append(page.nulls[c.arg_channel])
        nch = len(page.types)
        s_cols, s_nulls, s_valid, w_cols, w_nulls = _window_kernel(
            tuple(part_ops), tuple(order_ops), tuple(all_cols),
            tuple(all_nulls), page.valid,
            num_part_ops=len(part_ops), num_order_ops=len(order_ops),
            calls=tuple(calls))
        w_cols = list(w_cols)
        for i, (inv, _d) in restore.items():
            r = jnp.clip(w_cols[i], 0, len(inv) - 1)
            w_cols[i] = jnp.asarray(inv)[r]
        cols = list(s_cols[:nch]) + [c.astype(t.storage) for c, t in
                                     zip(w_cols, [c.output_type
                                                  for c in self.calls])]
        nulls = list(s_nulls[:nch]) + list(w_nulls)
        # value functions over pooled args keep the arg's code pool;
        # rank-reduced min/max restores the captured pool
        dicts = list(page.dictionaries) + [
            restore[i][1] if i in restore
            else (page.dictionaries[c.arg_channel]
                  if (c.output_type.is_pooled and c.arg_channel is not None)
                  else None)
            for i, c in enumerate(self.calls)]
        return DevicePage(self.output_types, cols, nulls, s_valid, dicts)

    def is_finished(self) -> bool:
        return self._done
