from .exchange import hash_partition_ids, repartition_a2a  # noqa: F401
