"""Deterministic hysteresis-guarded autoscaling policy.

Reference analog: Trino's cluster managers scale on queue pressure —
e.g. the Galaxy/EMR-style policies reading ``queuedQueries`` and
cluster memory utilization — while the engine itself only exposes the
signals. Here the policy is IN the engine but deliberately mechanical:
no wall-clock sampling, no randomness — every decision is a pure
function of the tick inputs and the controller's counters, so chaos
tests and the bench role replay identically.

Signals per tick (the monitor thread calls ``tick`` once per heartbeat
interval):
- resource-group queue depth (queries admitted but waiting),
- running queries,
- blocked nodes from the heartbeat-piggybacked memory snapshots.

Hysteresis: scale-up needs ``UP_TICKS`` consecutive pressure ticks,
scale-down needs ``down_idle_ticks`` consecutive fully-idle ticks, and
every decision starts a cooldown window during which no further
decision fires — so a bursty queue cannot flap the membership.
Scale-up doubles (bounded by ``max_workers``): reacting to a burst with
+1 worker chases the queue; doubling converges in O(log n) decisions.
Scale-down retires ONE worker at a time: drains are cheap, and a slow
ramp-down keeps capacity for the next burst.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


class Autoscaler:
    """State machine over tick inputs; all mutable state under one
    private lock (ticks come from the monitor thread, reads of
    ``decisions``/counters from metrics scrapes and tests)."""

    #: consecutive pressure ticks required before a scale-up fires
    UP_TICKS = 2
    #: bounded decision history for the bench result line / debugging
    MAX_DECISIONS = 64

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._last_action_at: Optional[float] = None
        self.decisions: List[dict] = []
        self.scale_ups = 0
        self.scale_downs = 0
        self.target: Optional[int] = None

    def _decide(self, direction: str, size: int, target: int,
                reason: str) -> dict:
        decision = {"direction": direction, "from": size, "to": target,
                    "reason": reason}
        self._last_action_at = self._clock()
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self.decisions.append(decision)
        del self.decisions[:-self.MAX_DECISIONS]
        if direction == "up":
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        self.target = target
        return decision

    def _cooled(self, cooldown_s: float) -> bool:
        return self._last_action_at is None or \
            self._clock() - self._last_action_at >= cooldown_s

    def tick(self, *, size: int, queued: int, running: int,
             min_workers: int, max_workers: int, cooldown_s: float,
             up_queue_depth: int, down_idle_ticks: int,
             blocked_nodes: int = 0) -> Optional[dict]:
        """One policy evaluation. Returns a decision dict
        ``{direction, from, to, reason}`` for the membership layer to
        apply, or None. Deterministic given the input sequence."""
        with self._lock:
            if size < min_workers:
                # below the floor is not a policy question: restore
                # immediately, cooldown does not apply
                return self._decide("up", size, min_workers,
                                    "below min_workers")
            pressure = (up_queue_depth > 0 and
                        queued >= up_queue_depth) or blocked_nodes > 0
            if pressure:
                self._pressure_ticks += 1
                self._idle_ticks = 0
                if self._pressure_ticks >= self.UP_TICKS \
                        and size < max_workers \
                        and self._cooled(cooldown_s):
                    target = min(max(size * 2, size + 1), max_workers)
                    why = f"queued={queued}" if queued else \
                        f"blocked_nodes={blocked_nodes}"
                    return self._decide("up", size, target, why)
                return None
            if queued == 0 and running == 0:
                self._idle_ticks += 1
                self._pressure_ticks = 0
                if self._idle_ticks >= max(1, down_idle_ticks) \
                        and size > min_workers \
                        and self._cooled(cooldown_s):
                    return self._decide(
                        "down", size, size - 1,
                        f"idle {self._idle_ticks} ticks")
                return None
            # busy but unpressured: a steady state — reset both streaks
            self._pressure_ticks = 0
            self._idle_ticks = 0
            return None

    def snapshot(self) -> dict:
        with self._lock:
            return {"scale_ups": self.scale_ups,
                    "scale_downs": self.scale_downs,
                    "target": self.target,
                    "decisions": list(self.decisions)}
