"""Elastic cluster membership: node ledger + topology-aware placement.

Reference analog: ``metadata/DiscoveryNodeManager.java`` (the
coordinator's view of active/shutting-down nodes, refreshed from
heartbeats) and ``execution/scheduler/NodeScheduler.java`` /
``UniformNodeSelector`` (task placement preferring nodes that already
hold the split's data, falling back round-robin).

The ledger is the single source of truth for membership EVENTS: every
join and retire bumps a monotonically increasing cluster generation, so
a straggling RPC observed against a retired slot can be attributed to a
stale generation instead of a mystery connection error. Worker slots in
ProcessQueryRunner.workers remain the placement-time view; the ledger
records the churn history behind them (system.runtime.nodes reads it).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

NODE_ACTIVE = "active"
NODE_DRAINING = "draining"
NODE_RETIRED = "retired"


@dataclass
class NodeInfo:
    """One worker process's membership record across its lifetime."""

    node_id: str
    address: Tuple[str, int]
    pid: int
    generation: int           # cluster generation at which it joined
    state: str = NODE_ACTIVE
    reason: str = ""          # why it joined (initial/heal/scale-up)
    joined_at: float = field(default_factory=time.monotonic)
    retired_at: Optional[float] = None
    retired_reason: str = ""


class ClusterLedger:
    """Membership event log + generation counter, all under one private
    lock (independent of the runner's heal lock: ledger writes happen
    from heal, retire, and the monitor thread concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._generation = 0
        self._seq = 0
        self._nodes: Dict[str, NodeInfo] = {}
        self.joined_total = 0
        self.retired_total = 0

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def record_join(self, address: Tuple[str, int], pid: int,
                    reason: str = "") -> NodeInfo:
        with self._lock:
            self._generation += 1
            self._seq += 1
            node = NodeInfo(node_id=f"node-{self._seq}",
                            address=tuple(address), pid=pid,
                            generation=self._generation, reason=reason)
            self._nodes[node.node_id] = node
            self.joined_total += 1
            return node

    def mark_draining(self, node_id: str):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None and node.state == NODE_ACTIVE:
                node.state = NODE_DRAINING

    def record_retire(self, node_id: str, reason: str = "") -> Optional[
            NodeInfo]:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or node.state == NODE_RETIRED:
                return None
            self._generation += 1
            node.state = NODE_RETIRED
            node.retired_at = time.monotonic()
            node.retired_reason = reason
            self.retired_total += 1
            return node

    def snapshot(self) -> List[NodeInfo]:
        """Membership history, join order (deterministic)."""
        with self._lock:
            return sorted(self._nodes.values(),
                          key=lambda n: n.generation)

    def counts(self) -> Tuple[int, int]:
        with self._lock:
            return self.joined_total, self.retired_total


def place_task(t: int, retry: int, candidates: Sequence,
               upstream_addrs: Optional[Sequence[tuple]] = None):
    """Deterministic topology-aware placement of task index ``t``.

    Prefer candidates already holding this stage's exchange inputs
    (their address appears among the upstream producer locations — a
    co-located consumer pulls those pages loopback-cheap and keeps
    spool locality); break score ties round-robin by task index, so the
    no-signal case (leaf scans, symmetric input spread, spool-only
    inputs) degenerates to EXACTLY the historical ``t % len`` schedule.
    Retries rotate over the full candidate list regardless of topology:
    the preferred node just failed this task, affinity is stale.
    """
    cands = list(candidates)
    if not cands:
        raise ValueError("no candidates to place task on")
    if retry:
        return cands[(t + retry) % len(cands)]
    if upstream_addrs:
        held = {}
        for a in upstream_addrs:
            a = tuple(a)
            held[a] = held.get(a, 0) + 1
        scores = [held.get(tuple(c.addr), 0) for c in cands]
        best = max(scores)
        if best > 0:
            tied = [c for c, s in zip(cands, scores) if s == best]
            return tied[t % len(tied)]
    return cands[t % len(cands)]
