"""Coordinator-side cluster memory governance.

Reference analog: ``memory/ClusterMemoryManager.java`` (polls every
worker's MemoryInfo, tracks per-query cluster-wide reservations,
enforces query.max-total-memory) with its pluggable
``memory/LowMemoryKiller.java`` implementations —
``TotalReservationOnBlockedNodesLowMemoryKiller`` (default) and
``TotalReservationLowMemoryKiller`` — plus the fault-tolerant
scheduler's ``PartitionMemoryEstimator`` (observed-peak-driven retry
budgets).

Transport: worker pool snapshots PIGGYBACK on the heartbeat ping the
process runner already sends (no extra RPC); ``ClusterMemoryManager``
aggregates them, exposes the cluster view for QueryResult.stats /
EXPLAIN ANALYZE / the HTTP protocol payload, and registers kills that
the per-query contexts consume as EXCEEDED_CLUSTER_MEMORY — an
INSUFFICIENT_RESOURCES error, so the victim's retry re-admits with an
escalated budget instead of replaying the identical doomed plan.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..types import TrinoError


@dataclass
class NodeMemorySnapshot:
    """One worker's pool state as of its last heartbeat."""

    worker_id: int
    max_bytes: int = 0
    reserved_bytes: int = 0
    peak_bytes: int = 0
    blocked_events: int = 0
    #: query id -> {"reserved", "peak", "spilled"}
    queries: Dict[str, Dict[str, int]] = field(default_factory=dict)
    time: float = 0.0

    @property
    def blocked(self) -> bool:
        """A node is blocked when admission failed on it since the
        previous heartbeat consumed the counter (the killer's trigger:
        reservations that cannot make progress)."""
        return self.blocked_events > 0


# -- low-memory killer policies ------------------------------------------


class LowMemoryKiller:
    """Victim selection over the cluster's node snapshots (reference:
    ``spi/memory/LowMemoryKiller``).  Deterministic: byte totals decide,
    lexicographically-smallest query id breaks ties, so a given cluster
    state always names the same victim."""

    name = "none"

    def choose_victim(self,
                      nodes: List[NodeMemorySnapshot]) -> Optional[str]:
        return None

    @staticmethod
    def _largest(totals: Dict[str, int]) -> Optional[str]:
        best = None
        for qid, total in totals.items():
            if total <= 0:
                continue
            if best is None or total > totals[best] \
                    or (total == totals[best] and qid < best):
                best = qid
        return best


class TotalReservationOnBlockedNodesKiller(LowMemoryKiller):
    """Kill the query holding the most memory ON THE BLOCKED NODES —
    freeing it unblocks exactly the starved pools (reference:
    ``TotalReservationOnBlockedNodesLowMemoryKiller.java``)."""

    name = "total-reservation-on-blocked-nodes"

    def choose_victim(self, nodes):
        totals: Dict[str, int] = {}
        for n in nodes:
            if not n.blocked:
                continue
            for qid, q in n.queries.items():
                totals[qid] = totals.get(qid, 0) + q.get("reserved", 0)
        return self._largest(totals)


class TotalReservationKiller(LowMemoryKiller):
    """Kill the cluster-wide largest query (reference:
    ``TotalReservationLowMemoryKiller.java``) — blunter, but frees the
    most bytes per kill."""

    name = "total-reservation"

    def choose_victim(self, nodes):
        totals: Dict[str, int] = {}
        for n in nodes:
            for qid, q in n.queries.items():
                totals[qid] = totals.get(qid, 0) + q.get("reserved", 0)
        return self._largest(totals)


KILLER_POLICIES = {
    "none": LowMemoryKiller,
    "total-reservation": TotalReservationKiller,
    "total-reservation-on-blocked-nodes":
        TotalReservationOnBlockedNodesKiller,
}


def killer_for(policy: str) -> LowMemoryKiller:
    cls = KILLER_POLICIES.get(policy)
    if cls is None:
        raise TrinoError(f"unknown memory killer policy {policy!r}",
                         "INVALID_SESSION_PROPERTY")
    return cls()


class QueryKilledError(TrinoError):
    """The low-memory killer (or the query_max_total_memory cap) chose
    this query as the victim — INSUFFICIENT_RESOURCES, so the retry
    loop re-admits it with an escalated budget."""

    def __init__(self, query_id: str, reason: str):
        super().__init__(
            f"Query {query_id} killed by the cluster memory manager: "
            f"{reason}", "EXCEEDED_CLUSTER_MEMORY")
        self.query_id = query_id
        self.reason = reason


# -- memory-aware retry sizing -------------------------------------------


class MemoryEstimator:
    """Observed peak memory per query attempt, the input to retry
    escalation (reference: ``PartitionMemoryEstimator`` — size the next
    attempt from what the failed one actually used, not from hope)."""

    GROWTH = 2.0

    def __init__(self):
        self._peaks: Dict[str, int] = {}
        self._lock = threading.Lock()

    def record_peak(self, query_id: str, peak: int):
        with self._lock:
            if len(self._peaks) >= 1024 and query_id not in self._peaks:
                # bounded for a long-lived coordinator: attempt ids are
                # unique per query, so old entries are dead weight
                self._peaks.clear()
            if peak > self._peaks.get(query_id, 0):
                self._peaks[query_id] = peak

    def peak_for(self, query_id: str) -> int:
        with self._lock:
            return self._peaks.get(query_id, 0)

    def next_budget(self, query_id: str, current: int,
                    floor: int) -> int:
        """The re-admission budget for the attempt after a memory
        failure: grow from the observed peak when the heartbeat caught
        one, else from the failed budget itself."""
        observed = self.peak_for(query_id)
        return int(max(floor, self.GROWTH * max(observed, current)))


# -- the manager ----------------------------------------------------------


class ClusterMemoryManager:
    """Aggregates heartbeat-piggybacked worker pool snapshots, enforces
    query_max_total_memory, and runs the low-memory killer when nodes
    report blocked pools (reference: ``ClusterMemoryManager.process``).

    Kill flags are registered here and consumed by the coordinator's
    per-query execution (the synchronous analog of the reference's
    fail-query callback)."""

    def __init__(self, policy: str = "total-reservation-on-blocked-nodes",
                 query_max_total_bytes: int = 0):
        self.killer = killer_for(policy)
        self.query_max_total_bytes = int(query_max_total_bytes)
        self.estimator = MemoryEstimator()
        self._snapshots: Dict[int, NodeMemorySnapshot] = {}
        self._kills: Dict[str, str] = {}     # qid -> reason (pending)
        #: every qid ever killed: one pressure episode = ONE kill, even
        #: though worker snapshots keep naming the dying victim for a
        #: few more heartbeats (bounded; see _kill)
        self._kill_history: set = set()
        self.kill_count = 0
        #: what chose the most recent victim: the killer policy name or
        #: "query-max-total-memory" (the cap path never consults the
        #: policy, and kill events must not claim it did)
        self.last_kill_source = self.killer.name
        self._lock = threading.Lock()

    # -- heartbeat intake -------------------------------------------------

    def update(self, worker_id: int, memory: Optional[dict]):
        """Fold one worker's ping payload in (None = worker has no pool
        configured or predates the protocol: drop its stale snapshot).
        ``blocked_events`` deltas ACCUMULATE across heartbeats — a probe
        that is not followed by a governance tick (on-demand heal,
        manual heartbeat) must not swallow the blocked signal — and are
        zeroed when a kill consumes them."""
        with self._lock:
            if not memory:
                self._snapshots.pop(worker_id, None)
                return
            prior = self._snapshots.get(worker_id)
            pending = prior.blocked_events if prior is not None else 0
            self._snapshots[worker_id] = NodeMemorySnapshot(
                worker_id,
                memory.get("max_bytes", 0),
                memory.get("reserved_bytes", 0),
                memory.get("peak_bytes", 0),
                memory.get("blocked_events", 0) + pending,
                dict(memory.get("queries", {})),
                time.monotonic())
        for qid, q in (memory.get("queries") or {}).items():
            self.estimator.record_peak(qid, q.get("peak", 0))

    def forget_worker(self, worker_id: int):
        with self._lock:
            self._snapshots.pop(worker_id, None)

    # -- governance -------------------------------------------------------

    def query_totals(self) -> Dict[str, int]:
        with self._lock:
            totals: Dict[str, int] = {}
            for n in self._snapshots.values():
                for qid, q in n.queries.items():
                    totals[qid] = totals.get(qid, 0) + q.get("reserved", 0)
            return totals

    def maybe_kill(self) -> Optional[str]:
        """One governance tick: enforce the per-query cluster cap, then
        — if any node is blocked — let the policy pick a victim.
        Returns the newly-killed query id, if any."""
        with self._lock:
            history = set(self._kill_history)  # _kill re-checks under
            # its own lock; this copy only avoids pointless candidates
        if self.query_max_total_bytes > 0:
            totals = self.query_totals()
            over = sorted(q for q, t in totals.items()
                          if t > self.query_max_total_bytes
                          and q not in history)
            if over:
                self.last_kill_source = "query-max-total-memory"
                return self._kill(
                    over[0],
                    f"total reservation {totals[over[0]]} bytes exceeds "
                    f"query_max_total_memory "
                    f"{self.query_max_total_bytes}")
        with self._lock:
            nodes = list(self._snapshots.values())
            blocked = [n for n in nodes if n.blocked]
        if not blocked:
            return None
        victim = self.killer.choose_victim(nodes)
        self.last_kill_source = self.killer.name
        if victim is None or victim in history:
            # this governance tick CONSUMED the blocked signal and
            # decided nothing is killable (the blocking query already
            # failed and released): without this, a latched signal
            # would kill an innocent later query
            with self._lock:
                for n in self._snapshots.values():
                    n.blocked_events = 0
            return None
        return self._kill(
            victim, f"nodes {sorted(n.worker_id for n in blocked)} "
            f"blocked on memory; policy {self.killer.name} chose the "
            "largest reservation")

    def _kill(self, qid: str, reason: str) -> Optional[str]:
        """Register one kill; None (and no event upstream) when this
        attempt id was already killed — snapshots keep naming a dying
        victim for a few heartbeats, and check_killed popping the flag
        must not let it re-register."""
        with self._lock:
            if qid in self._kill_history:
                return None
            if len(self._kill_history) >= 256:
                self._kill_history.clear()
            if len(self._kills) >= 64:   # victims that never checked in
                self._kills.pop(next(iter(self._kills)))
            self._kill_history.add(qid)
            self._kills[qid] = reason
            self.kill_count += 1
            # consume the blocked signal: one blocked episode yields
            # ONE kill, the next heartbeat re-arms it if pressure
            # persists
            for n in self._snapshots.values():
                n.blocked_events = 0
        return qid

    def kill(self, qid: str, reason: str) -> str:
        """Explicit kill registration (tests, admin surface)."""
        return self._kill(qid, reason)

    def check_killed(self, query_id: str):
        """Raise if this query (attempt) was chosen as a victim; the
        flag is consumed so the NEXT attempt runs clean."""
        with self._lock:
            reason = self._kills.pop(query_id, None)
        if reason is not None:
            raise QueryKilledError(query_id, reason)

    # -- observability ----------------------------------------------------

    def cluster_stats(self) -> dict:
        """The cluster-memory section for QueryResult.stats / EXPLAIN
        ANALYZE / the HTTP protocol payload."""
        with self._lock:
            nodes = list(self._snapshots.values())
            kills = self.kill_count
        return {
            "workers": len(nodes),
            "total_max_bytes": sum(n.max_bytes for n in nodes),
            "total_reserved_bytes": sum(n.reserved_bytes for n in nodes),
            "blocked_nodes": sum(1 for n in nodes if n.blocked),
            "queries": self.query_totals(),
            "kills": kills,
            "killer_policy": self.killer.name,
        }
