"""Device-collective stage exchange: the engine's hash shuffle as ONE
XLA ``all_to_all`` over the mesh.

Reference analog: the ENTIRE pipelined data plane of a hash exchange —
``operator/output/PartitionedOutputOperator.java`` + ``PagePartitioner``
(producer), ``execution/buffer/PartitionedOutputBuffer.java`` (buffer),
``operator/ExchangeOperator.java:48`` + ``DirectExchangeClient.java:55``
(consumer) — collapsed, for co-resident stages, into a single SPMD
program: each producer task owns one mesh device, rows are bucket-sorted
by destination on device, and one ICI collective delivers every row to
the consumer task that owns its hash partition. No serialization, no
host round-trip, no HTTP.

String columns: pools are unified BEFORE the collective (host builds a
code-remap LUT per divergent pool, devices apply it as a gather), and
key hashing uses a value-stable crc LUT so equal strings route equally
regardless of pool. This is the exchange-boundary "pool unification"
contract that downstream group-by/join kernels rely on.

Overflow protocol: all_to_all lanes are fixed capacity (per_dest per
sender/receiver pair); on overflow the host doubles per_dest and re-runs
the collective — static shapes with a retry loop instead of the
reference's unbounded buffers.
"""

from __future__ import annotations

import threading
from functools import lru_cache, partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

from .. import types as T
from ..block import DevicePage, Dictionary, padded_size
from .exchange import (hash_partition_ids, key_to_u64, repartition_a2a,
                       shard_map, string_hash_lut)


def device_exchange_supported(types_: Sequence[T.Type]) -> bool:
    return all(t.storage is not None for t in types_)


class DeviceExchange:
    """One fragment's hash-output boundary, executed as a collective.

    Producer tasks (one per mesh device) ``add_page`` their DevicePages;
    after all producers finish (the runner's stage barrier), the first
    consumer to call ``pages`` triggers the collective; consumer task t
    reads the rows whose keys hash to partition t.

    Drop-in for ``ops.output.OutputBuffer`` on the consumer side: exposes
    ``pages(partition)`` (returning DevicePages, which
    ExchangeSourceOperator passes through).
    """

    def __init__(self, n_partitions: int, devices: Sequence):
        # p-partitions-on-d-devices layout: with fewer devices than
        # partitions (a single real chip being the important case),
        # partition p lives on device p % d; partition ids are carried
        # through the collective and consumers split their device's slab
        # by mask. d == n degenerates to the exact 1:1 mapping.
        assert len(devices) >= 1
        self.n = n_partitions
        self.devices = list(devices)[:min(n_partitions, len(devices))]
        self.d = len(self.devices)
        self.types: Optional[List[T.Type]] = None
        self.key_channels: Optional[List[int]] = None
        self._by_task: Dict[int, List[DevicePage]] = {}
        self._lock = threading.Lock()
        self._result: Optional[List[List[DevicePage]]] = None
        self.a2a_retries = 0
        self.collective_ran = False  # test observability
        # streaming-scheduler support: the collective is a barrier — it
        # needs every producer's rows — so consumers park on a listen
        # token until the runner signals set_no_more_pages()
        self._no_more = False
        self._listeners: List = []

    def set_no_more_pages(self):
        with self._lock:
            if self._no_more:
                return
            self._no_more = True
            fired = list(self._listeners)
            self._listeners.clear()
        for cb in fired:
            cb()

    def abort(self):
        with self._lock:
            self._no_more = True
            self._result = [[] for _ in range(self.n)]
            self._by_task.clear()
            fired = list(self._listeners)
            self._listeners.clear()
        for cb in fired:
            cb()

    def channel(self, partition: int) -> "DeviceExchangeChannel":
        return DeviceExchangeChannel(self, partition)

    #: process-wide count of executed collectives (dryrun/test
    #: observability); guarded by _total_lock — instances have their own
    #: locks, and two exchanges can collect concurrently
    total_collectives = 0
    _total_lock = threading.Lock()

    # -- producer side --------------------------------------------------

    def configure(self, types_: Sequence[T.Type],
                  key_channels: Sequence[int]):
        with self._lock:
            if self.types is None:
                self.types = list(types_)
                self.key_channels = list(key_channels)
            else:
                assert self.types == list(types_) and \
                    self.key_channels == list(key_channels), \
                    "producer tasks disagree on exchange layout"

    def add_page(self, task_id: int, page: DevicePage):
        with self._lock:
            self._by_task.setdefault(task_id, []).append(page)

    # -- consumer side --------------------------------------------------

    def pages(self, partition: int) -> List[DevicePage]:
        with self._lock:
            if self._result is None:
                self._result = self._collect()
        return self._result[partition]

    @property
    def total_rows(self) -> int:
        if self._result is None:
            return 0
        return sum(p.count() for ps in self._result for p in ps)

    # -- the collective -------------------------------------------------

    def _collect(self) -> List[List[DevicePage]]:
        n, d, types_ = self.n, self.d, self.types
        if types_ is None or not self._by_task:
            return [[] for _ in range(n)]
        nch = len(types_)

        # unify string pools: remap every divergent pool's codes into the
        # first pool seen per channel (device gather through a host LUT)
        target: List[Optional[Dictionary]] = [None] * nch
        for t in range(n):
            for p in self._by_task.get(t, []):
                for c in range(nch):
                    if p.dictionaries[c] is not None and target[c] is None:
                        target[c] = p.dictionaries[c]

        def unified_cols(p: DevicePage) -> List:
            cols = list(p.cols)
            for c in range(nch):
                d = p.dictionaries[c]
                if d is not None and d is not target[c]:
                    remap = (np.asarray(target[c].encode(list(d.values)),
                                        dtype=np.int32)
                             if len(d) else np.zeros(1, np.int32))
                    cols[c] = jnp.asarray(remap)[p.cols[c]]
            return cols

        # stack per-DEVICE rows (padded lanes + valid masks carried
        # as-is): producer task t's pages land in device slab t % d
        dev_pages: List[List[DevicePage]] = [[] for _ in range(d)]
        for t in sorted(self._by_task):
            dev_pages[t % d].extend(self._by_task[t])
        dev_caps = [sum(p.capacity for p in ps) for ps in dev_pages]
        cap = padded_size(max(max(dev_caps), 16))
        total_rows = 0
        s_cols = [[] for _ in range(nch)]
        s_nulls = [[] for _ in range(nch)]
        s_valid = []

        def pad(a):
            k = a.shape[0]
            if k == cap:
                return a
            return jnp.concatenate(
                [a, jnp.zeros((cap - k,), dtype=a.dtype)])

        for ps in dev_pages:
            total_rows += sum(p.count() for p in ps)
            page_cols = [unified_cols(p) for p in ps]
            for c in range(nch):
                if ps:
                    s_cols[c].append(pad(jnp.concatenate(
                        [pc[c] for pc in page_cols])))
                    s_nulls[c].append(pad(jnp.concatenate(
                        [p.nulls[c] for p in ps])))
                else:
                    s_cols[c].append(jnp.zeros((cap,),
                                               dtype=types_[c].storage))
                    s_nulls[c].append(jnp.zeros((cap,), dtype=bool))
            if ps:
                s_valid.append(pad(jnp.concatenate([p.valid for p in ps])))
            else:
                s_valid.append(jnp.zeros((cap,), dtype=bool))

        if total_rows == 0:
            return [[] for _ in range(n)]

        cols = tuple(jnp.stack(s_cols[c]) for c in range(nch))
        nulls = tuple(jnp.stack(s_nulls[c]) for c in range(nch))
        valid = jnp.stack(s_valid)

        luts = tuple(jnp.asarray(string_hash_lut(target[c]))
                     for c in self.key_channels if types_[c].is_string)

        mesh = Mesh(np.asarray(self.devices), ("x",))
        per_dest = padded_size(max(32, (2 * cap) // d))
        while True:
            prog = _exchange_program(mesh, tuple(types_),
                                     tuple(self.key_channels), n, d,
                                     per_dest)
            out_cols, out_nulls, out_valid, out_part, overflow = prog(
                cols, nulls, valid, luts)
            jax.block_until_ready(out_valid)
            if int(np.asarray(overflow).sum()) == 0:
                break
            if per_dest >= cap:
                raise RuntimeError(
                    f"device exchange overflow with per_dest={per_dest} "
                    f">= sender capacity {cap} (bug, not skew)")
            per_dest = min(per_dest * 2, cap)
            self.a2a_retries += 1

        self.collective_ran = True
        with DeviceExchange._total_lock:
            DeviceExchange.total_collectives += 1
        # release producer-side inputs: without this the exchange pins
        # ~2x the exchanged bytes in HBM for the rest of the query
        self._by_task.clear()
        out_dicts = list(target)
        result: List[List[DevicePage]] = []
        for p in range(n):
            dev = p % d
            pv = out_valid[dev]
            if d < n:  # split the device slab by carried partition id
                pv = pv & (out_part[dev] == p)
            dp = DevicePage(list(types_),
                            [c[dev] for c in out_cols],
                            [x[dev] for x in out_nulls],
                            pv, out_dicts)
            result.append([dp])
        return result


@lru_cache(maxsize=128)
def _exchange_program(mesh: Mesh, types_: tuple, key_channels: tuple,
                      n: int, d: int, per_dest: int):
    """Build the jitted SPMD shuffle: normalize keys -> partition ids ->
    bucket-sort -> all_to_all. Memoized on (mesh, types, keys, n, d,
    per_dest) so repeat shapes reuse the compiled program.

    With d < n the collective routes to DEVICE p % d and the partition id
    rides along as an extra carried channel so the consumer can split its
    slab; with d == n device == partition and the carry is still returned
    (cheap) but unused."""

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P(None)),
             out_specs=(P("x"), P("x"), P("x"), P("x"), P("x")),
             check_vma=False)
    def prog(cols, nulls, valid, luts):
        cols = tuple(c[0] for c in cols)
        nulls = tuple(x[0] for x in nulls)
        valid = valid[0]
        keys = []
        li = 0
        for c in key_channels:
            lut = None
            if types_[c].is_string:
                lut = luts[li]
                li += 1
            keys.append(key_to_u64(cols[c], nulls[c], types_[c], lut))
        part = hash_partition_ids(keys, n)
        dest = part % d if d < n else part
        false_ = jnp.zeros(valid.shape, dtype=bool)
        ex_cols, ex_nulls, ex_valid, overflow = repartition_a2a(
            cols + (part,), nulls + (false_,), valid, dest,
            num_partitions=d, per_dest=per_dest)
        return (tuple(c[None] for c in ex_cols[:-1]),
                tuple(x[None] for x in ex_nulls[:-1]),
                ex_valid[None], ex_cols[-1][None], overflow[None])

    return jax.jit(prog)


class _DeviceExchangeToken:
    """Listen token over the exchange's producers-done event."""

    __slots__ = ("_ex",)

    def __init__(self, ex: DeviceExchange):
        self._ex = ex

    def on_ready(self, cb):
        with self._ex._lock:
            if not self._ex._no_more:
                self._ex._listeners.append(cb)
                return
        cb()


class DeviceExchangeChannel:
    """Streaming-consumer adapter: parks until ALL producers finished
    (the collective is inherently a barrier), then streams the
    partition's DevicePages."""

    def __init__(self, ex: DeviceExchange, partition: int):
        self.ex = ex
        self.partition = partition
        self._pages: Optional[List[DevicePage]] = None

    def poll(self):
        if not self.ex._no_more:
            return None
        if self._pages is None:
            self._pages = list(self.ex.pages(self.partition))
        return self._pages.pop(0) if self._pages else None

    def at_end(self) -> bool:
        return self.ex._no_more and self._pages is not None \
            and not self._pages

    def has_page(self) -> bool:
        return self.ex._no_more and (self._pages is None
                                     or len(self._pages) > 0)

    def listen(self):
        return _DeviceExchangeToken(self.ex)


class DeviceExchangeSinkOperator:
    """Pipeline tail handing DevicePages to the exchange (replaces
    PartitionedOutputOperator on the device path — no host transfer)."""

    _finishing = False

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], exchange: DeviceExchange,
                 task_id: int):
        exchange.configure(input_types, key_channels)
        self.exchange = exchange
        self.task_id = task_id
        self._done = False

    def needs_input(self) -> bool:
        return not self._finishing

    def blocked_token(self):
        return None

    def add_input(self, page: DevicePage):
        self.exchange.add_page(self.task_id, page)

    def get_output(self):
        if self._finishing:
            self._done = True
        return None

    def finish(self):
        self._finishing = True

    def is_finished(self) -> bool:
        return self._done
