"""Device-collective stage exchange: the engine's hash shuffle as ONE
XLA ``all_to_all`` over the mesh.

Reference analog: the ENTIRE pipelined data plane of a hash exchange —
``operator/output/PartitionedOutputOperator.java`` + ``PagePartitioner``
(producer), ``execution/buffer/PartitionedOutputBuffer.java`` (buffer),
``operator/ExchangeOperator.java:48`` + ``DirectExchangeClient.java:55``
(consumer) — collapsed, for co-resident stages, into a single SPMD
program: each producer task owns one mesh device, rows are bucket-sorted
by destination on device, and one ICI collective delivers every row to
the consumer task that owns its hash partition. No serialization, no
host round-trip, no HTTP.

String columns: pools are unified BEFORE the collective (host builds a
code-remap LUT per divergent pool, devices apply it as a gather), and
key hashing uses a value-stable crc LUT so equal strings route equally
regardless of pool. This is the exchange-boundary "pool unification"
contract that downstream group-by/join kernels rely on.

Sizing protocol (skew-adaptive): all_to_all lanes are fixed capacity
(per_dest per sender/receiver pair), so per_dest must be chosen before
the data collective compiles. Three modes (``device_exchange_sizing``
session property):

- ``exact``: a count-first pass — a tiny counting collective (per-sender
  destination histograms + psum/pmax, O(n*d) scalars, negligible vs the
  payload) — observes the exact max (sender, dest) load and sizes
  per_dest exactly; the doubling retry below becomes dead code in
  practice (kept as a bug backstop).
- ``history`` (default): a process-wide EWMA of observed max loads keyed
  by exchange shape (types/keys/n/d — the plan-node signature),
  pow2-bucketed through ``padded_size`` so repeat shapes reuse the
  ``_exchange_program`` lru_cache; pre-sizes per_dest and skips the
  count pass once confident, falling back to ``exact`` until then.
- ``legacy``: the original guess (2*cap/d); on lane overflow the host
  doubles per_dest and re-runs the whole collective — under real skew
  that pays the full shuffle twice or more (the 2x cost cliff the
  count-first pass removes).

Hot-partition SPLITTING (scaled receivers): lanes are per (sender,
dest) pair, so ONE partition holding most of the rows caps the whole
collective at a single receiver lane's capacity however the collective
is sized — the workload count-first sizing alone cannot fix (reference:
``ScaleWriterPartitioningExchanger`` + ``UniformPartitionRebalancer``).
When the count pass's per-partition histogram (or the sizing history's
remembered partition fractions) shows a partition above
``hot_partition_split_threshold`` of the exchange's rows, the jit'd
``_exchange_program`` SALTS that partition's destination with a
row-index-derived sub-bucket — its rows spread across ALL d receiver
devices — and the consumer-side ``pages(partition)`` gather re-merges
the sub-buckets (each partition's pages may now come from several
device slabs; the original partition id is carried through the
collective, so co-location per CONSUMER TASK is preserved, which is all
downstream aggregation/join operators require). The hot set rides into
the compiled program as a TRACED (n,) mask argument, so split and
unsplit runs of the same shape share one cache entry — no recompiles.

Every collective records skew observability into ``self.stats``:
per-partition row counts, max/mean skew ratio, per-receiver lane loads,
hot partitions split and the receiver lanes they spread across,
per_dest chosen, retries, collective count and bytes moved — surfaced
through OperatorStats / EXPLAIN ANALYZE and the bench output.
"""

from __future__ import annotations

import threading
from functools import lru_cache, partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

from .. import jit_stats
from .. import types as T
from ..block import DevicePage, Dictionary, padded_size
from ..telemetry.profiler import instrument
from .exchange import (hash_partition_ids, key_to_u64, partition_histogram,
                       repartition_a2a, shard_map, string_hash_lut)


def device_exchange_supported(types_: Sequence[T.Type]) -> bool:
    return all(t.storage is not None for t in types_)


SIZING_MODES = ("exact", "history", "legacy")


class ExchangeSizingHistory:
    """Process-wide EWMA of observed max (sender, dest) lane loads, keyed
    by exchange shape (types/key_channels/n/d — the plan-node signature,
    stable across queries of the same shape). ``presize`` returns a
    pow2-bucketed per_dest through the SAME ``padded_size`` bucketing the
    exact mode uses, so a stable workload re-lands on the identical
    ``_exchange_program`` cache entry instead of recompiling.

    Reference analog: the observed-size adaptive partition sizing of
    ``HashDistributionSplitAssigner`` — capacity decided from counts seen,
    not guessed (the hybrid-hash-join robustness argument)."""

    def __init__(self, alpha: float = 0.5):
        self.alpha = alpha
        self._lock = threading.Lock()
        self._ewma: Dict[tuple, float] = {}
        self._obs: Dict[tuple, int] = {}
        #: last observed per-partition row FRACTIONS per shape — the
        #: hot-partition-split decision for a history-presized repeat
        #: (no count pass ran, so the hot set must be remembered too)
        self._fracs: Dict[tuple, list] = {}
        #: scaled-writer rebalancers keyed by exchange shape — same
        #: lifetime as the sizing EWMAs they ride with, so a repeat
        #: query reuses the learned partition->lane assignment instead
        #: of re-converging (reference: UniformPartitionRebalancer
        #: living on the long-lived exchange, not the query)
        self._rebalancers: Dict[tuple, object] = {}

    def observe(self, key: tuple, max_load: int,
                fractions: Optional[Sequence[float]] = None) -> None:
        with self._lock:
            prev = self._ewma.get(key)
            if prev is None or max_load >= prev:
                # grow IMMEDIATELY: an undersized presize costs a full
                # re-shuffle through the doubling backstop, an oversized
                # one only pads lanes — so track load spikes at once and
                # decay slowly
                self._ewma[key] = float(max_load)
            else:
                self._ewma[key] = (self.alpha * max_load
                                   + (1 - self.alpha) * prev)
            self._obs[key] = self._obs.get(key, 0) + 1
            if fractions is not None:
                self._fracs[key] = list(fractions)

    def presize(self, key: tuple) -> Optional[int]:
        """pow2-bucketed per_dest, or None while unconfident (no
        observation yet for this exchange shape)."""
        with self._lock:
            if self._obs.get(key, 0) < 1:
                return None
            return padded_size(max(int(round(self._ewma[key])), 16))

    def fractions(self, key: tuple) -> Optional[list]:
        """Last observed per-partition row fractions for this shape
        (None until observed) — feeds the presized hot-set decision."""
        with self._lock:
            fr = self._fracs.get(key)
            return list(fr) if fr is not None else None

    def rebalancer(self, key: tuple, factory):
        """The process-wide scaled-writer rebalancer for this exchange
        shape, created on first use by ``factory()``."""
        with self._lock:
            rb = self._rebalancers.get(key)
            if rb is None:
                rb = self._rebalancers[key] = factory()
            return rb

    def export_seed(self) -> list:
        """Serializable (key, ewma, obs, fractions) rows — the sizing
        knowledge a heartbeat piggybacks coordinator-ward so a new or
        replacement worker presizes exchanges from cluster history
        instead of re-learning from scratch."""
        with self._lock:
            return [[list(k), self._ewma[k], self._obs.get(k, 0),
                     self._fracs.get(k)] for k in self._ewma]

    def import_seed(self, seed) -> int:
        """Merge an exported seed, keeping the larger EWMA per shape
        (grow-immediately mirrors ``observe``); idempotent, so repeated
        heartbeat piggybacks are free. Returns rows merged."""
        if not seed:
            return 0
        merged = 0
        with self._lock:
            for row in seed:
                try:
                    key = tuple(tuple(x) if isinstance(x, list) else x
                                for x in row[0])
                    ewma, obs, fracs = float(row[1]), int(row[2]), row[3]
                except (TypeError, ValueError, IndexError):
                    continue
                if ewma >= self._ewma.get(key, 0.0):
                    self._ewma[key] = ewma
                    if fracs is not None:
                        self._fracs[key] = list(fracs)
                self._obs[key] = max(self._obs.get(key, 0), obs)
                merged += 1
        return merged

    def reset(self) -> None:
        with self._lock:
            self._ewma.clear()
            self._obs.clear()
            self._fracs.clear()
            self._rebalancers.clear()


#: the process-wide sizing history (one engine process = one history,
#: like the jit caches it protects)
SIZING_HISTORY = ExchangeSizingHistory()


class DeviceExchange:
    """One fragment's hash-output boundary, executed as a collective.

    Producer tasks (one per mesh device) ``add_page`` their DevicePages;
    after all producers finish (the runner's stage barrier), the first
    consumer to call ``pages`` triggers the collective; consumer task t
    reads the rows whose keys hash to partition t.

    Drop-in for ``ops.output.OutputBuffer`` on the consumer side: exposes
    ``pages(partition)`` (returning DevicePages, which
    ExchangeSourceOperator passes through).
    """

    def __init__(self, n_partitions: int, devices: Sequence,
                 sizing: str = "history",
                 history_key: Optional[tuple] = None,
                 hot_split_threshold: float = 0.5):
        # p-partitions-on-d-devices layout: with fewer devices than
        # partitions (a single real chip being the important case),
        # partition p lives on device p % d; partition ids are carried
        # through the collective and consumers split their device's slab
        # by mask. d == n degenerates to the exact 1:1 mapping.
        assert len(devices) >= 1
        assert sizing in SIZING_MODES, sizing
        self.n = n_partitions
        self.devices = list(devices)[:min(n_partitions, len(devices))]
        self.d = len(self.devices)
        self.sizing = sizing
        #: history key override (defaults to the exchange shape —
        #: types/key_channels/n/d — at collect time)
        self.history_key = history_key
        #: a partition holding MORE than this fraction of the
        #: exchange's rows is split across all d receiver devices
        #: (>= 1.0 disables splitting; single-device meshes never split)
        self.hot_split_threshold = hot_split_threshold
        self.types: Optional[List[T.Type]] = None
        self.key_channels: Optional[List[int]] = None
        self._by_task: Dict[int, List[DevicePage]] = {}
        self._lock = threading.Lock()
        self._result: Optional[List[List[DevicePage]]] = None
        self.a2a_retries = 0
        self.count_collectives = 0
        self.data_collectives = 0
        self.collective_ran = False  # test observability
        #: skew observability of the last collective (per-partition row
        #: counts, skew ratio, per_dest chosen, retries, bytes moved) —
        #: populated by _collect, surfaced via OperatorStats / EXPLAIN
        #: ANALYZE / bench
        self.stats: Optional[Dict] = None
        # streaming-scheduler support: the collective is a barrier — it
        # needs every producer's rows — so consumers park on a listen
        # token until the runner signals set_no_more_pages()
        self._no_more = False
        self._listeners: List = []

    def set_no_more_pages(self):
        with self._lock:
            if self._no_more:
                return
            self._no_more = True
            fired = list(self._listeners)
            self._listeners.clear()
        for cb in fired:
            cb()

    def abort(self):
        with self._lock:
            self._no_more = True
            self._result = [[] for _ in range(self.n)]
            self._by_task.clear()
            fired = list(self._listeners)
            self._listeners.clear()
        for cb in fired:
            cb()

    def channel(self, partition: int) -> "DeviceExchangeChannel":
        return DeviceExchangeChannel(self, partition)

    #: process-wide count of executed collectives (dryrun/test
    #: observability); guarded by _total_lock — instances have their own
    #: locks, and two exchanges can collect concurrently
    total_collectives = 0
    #: process-wide count of count-first sizing collectives (history
    #: hits skip them — assertable)
    total_count_collectives = 0
    #: process-wide count of hot partitions split across receivers
    #: (bench SKEW_RESULT / test observability)
    total_splits = 0
    _total_lock = threading.Lock()

    # -- producer side --------------------------------------------------

    def configure(self, types_: Sequence[T.Type],
                  key_channels: Sequence[int]):
        with self._lock:
            if self.types is None:
                self.types = list(types_)
                self.key_channels = list(key_channels)
            else:
                assert self.types == list(types_) and \
                    self.key_channels == list(key_channels), \
                    "producer tasks disagree on exchange layout"

    def add_page(self, task_id: int, page: DevicePage):
        with self._lock:
            self._by_task.setdefault(task_id, []).append(page)

    # -- consumer side --------------------------------------------------

    def pages(self, partition: int) -> List[DevicePage]:
        with self._lock:
            if self._result is None:
                self._result = self._collect()
        return self._result[partition]

    @property
    def total_rows(self) -> int:
        if self._result is None:
            return 0
        return sum(p.count() for ps in self._result for p in ps)

    # -- the collective -------------------------------------------------

    def _collect(self) -> List[List[DevicePage]]:
        n, d, types_ = self.n, self.d, self.types
        if types_ is None or not self._by_task:
            return [[] for _ in range(n)]
        nch = len(types_)

        # unify string pools: remap every divergent pool's codes into the
        # first pool seen per channel (device gather through a host LUT)
        target: List[Optional[Dictionary]] = [None] * nch
        for t in range(n):
            for p in self._by_task.get(t, []):
                for c in range(nch):
                    if p.dictionaries[c] is not None and target[c] is None:
                        target[c] = p.dictionaries[c]

        def unified_cols(p: DevicePage) -> List:
            cols = list(p.cols)
            for c in range(nch):
                d = p.dictionaries[c]
                if d is not None and d is not target[c]:
                    remap = (np.asarray(target[c].encode(list(d.values)),
                                        dtype=np.int32)
                             if len(d) else np.zeros(1, np.int32))
                    cols[c] = jnp.asarray(remap)[p.cols[c]]
            return cols

        # stack per-DEVICE rows (padded lanes + valid masks carried
        # as-is): producer task t's pages land in device slab t % d
        dev_pages: List[List[DevicePage]] = [[] for _ in range(d)]
        for t in sorted(self._by_task):
            dev_pages[t % d].extend(self._by_task[t])
        dev_caps = [sum(p.capacity for p in ps) for ps in dev_pages]
        cap = padded_size(max(max(dev_caps), 16))
        total_rows = 0
        s_cols = [[] for _ in range(nch)]
        s_nulls = [[] for _ in range(nch)]
        s_valid = []

        def pad(a):
            k = a.shape[0]
            if k == cap:
                return a
            return jnp.concatenate(
                [a, jnp.zeros((cap - k,), dtype=a.dtype)])

        for ps in dev_pages:
            total_rows += sum(p.count() for p in ps)
            page_cols = [unified_cols(p) for p in ps]
            for c in range(nch):
                if ps:
                    s_cols[c].append(pad(jnp.concatenate(
                        [pc[c] for pc in page_cols])))
                    s_nulls[c].append(pad(jnp.concatenate(
                        [p.nulls[c] for p in ps])))
                else:
                    s_cols[c].append(jnp.zeros((cap,),
                                               dtype=types_[c].storage))
                    s_nulls[c].append(jnp.zeros((cap,), dtype=bool))
            if ps:
                s_valid.append(pad(jnp.concatenate([p.valid for p in ps])))
            else:
                s_valid.append(jnp.zeros((cap,), dtype=bool))

        if total_rows == 0:
            return [[] for _ in range(n)]

        cols = tuple(jnp.stack(s_cols[c]) for c in range(nch))
        nulls = tuple(jnp.stack(s_nulls[c]) for c in range(nch))
        valid = jnp.stack(s_valid)

        luts = tuple(jnp.asarray(string_hash_lut(target[c]))
                     for c in self.key_channels if types_[c].is_string)

        mesh = Mesh(np.asarray(self.devices), ("x",))
        tkey = tuple(types_)
        kkey = tuple(self.key_channels)
        hkey = self.history_key or (
            tuple(str(t) for t in types_), kkey, n, d)
        sizing = self.sizing
        mode_used = sizing
        # hot-partition splitting is a non-legacy feature (legacy IS the
        # pre-split baseline) and needs >= 2 receivers to spread over
        splittable = (self.hot_split_threshold < 1.0 and d > 1
                      and sizing != "legacy")
        hot: set = set()
        per_dest = None
        if sizing == "history":
            per_dest = SIZING_HISTORY.presize(hkey)
            if per_dest is None:
                mode_used = "exact"  # unconfident: fall back to counting
            elif splittable:
                # no count pass ran: the hot set comes from the
                # history's remembered partition fractions
                fracs = SIZING_HISTORY.fractions(hkey)
                if fracs is not None:
                    hot = {p for p, f in enumerate(fracs)
                           if f > self.hot_split_threshold}
        if sizing == "exact" or (sizing == "history" and per_dest is None):
            # count-first pass: the exact max (sender, dest) load from a
            # tiny counting collective; per_dest needs no retry headroom
            cprog = _count_program(mesh, tkey, kkey, n, d)
            hist, need, pair_max = cprog(cols, nulls, valid, luts)
            hist = np.asarray(hist)[0]
            self.count_collectives += 1
            with DeviceExchange._total_lock:
                DeviceExchange.total_count_collectives += 1
            total = int(hist.sum())
            if splittable and total:
                hot = {p for p in range(n)
                       if hist[p] / total > self.hot_split_threshold}
            if hot:
                pair_np = np.asarray(pair_max)[0].reshape(n, d)
                per_dest = padded_size(max(_salted_need_bound(
                    pair_np, hot, n, d), 16))
            else:
                per_dest = padded_size(max(int(np.asarray(need)[0]), 16))
        elif sizing == "legacy":
            per_dest = padded_size(max(32, (2 * cap) // d))
        per_dest = min(per_dest, cap)
        # the hot set rides as a TRACED (n,) mask: split and unsplit
        # runs of one shape share one compiled program (no recompiles)
        hot_mask = np.zeros((n,), dtype=np.int32)
        for p in hot:
            hot_mask[p] = 1
        hot_mask = jnp.asarray(hot_mask)
        lanes_moved = 0
        while True:
            prog = _exchange_program(mesh, tkey, kkey, n, d, per_dest)
            out_cols, out_nulls, out_valid, out_part, overflow = prog(
                cols, nulls, valid, luts, hot_mask)
            jax.block_until_ready(out_valid)
            self.data_collectives += 1
            lanes_moved += d * d * per_dest  # at THIS attempt's capacity
            if int(np.asarray(overflow).sum()) == 0:
                break
            if per_dest >= cap:
                raise T.TrinoError(
                    f"device exchange overflow with per_dest={per_dest} "
                    f">= sender capacity {cap} (bug, not skew)",
                    "GENERIC_INTERNAL_ERROR")
            # backstop only: exact sizing cannot overflow; a stale
            # history presize can, and the doubling recovers it (the
            # observation below re-teaches the history)
            per_dest = min(per_dest * 2, cap)
            self.a2a_retries += 1

        self.collective_ran = True
        with DeviceExchange._total_lock:
            DeviceExchange.total_collectives += 1

        # skew observability + history feedback, from the RESULT (costs
        # one host transfer of the valid/partition lanes, no extra
        # collective in any mode): receiver r's lanes [s*per_dest,
        # (s+1)*per_dest) came from sender s, so per-(receiver, sender)
        # valid counts give the exact max pair load actually observed
        ov = np.asarray(out_valid)
        op_ids = np.asarray(out_part)
        pair_rows = ov.reshape(d, d, per_dest).sum(axis=2)
        observed_max = int(pair_rows.max()) if pair_rows.size else 0
        partition_rows = np.bincount(op_ids[ov], minlength=n)[:n]
        total_rows = int(partition_rows.sum())
        SIZING_HISTORY.observe(
            hkey, observed_max,
            fractions=(partition_rows / total_rows).tolist()
            if total_rows else None)
        mean_rows = float(partition_rows.mean()) if n else 0.0
        # per-receiver-DEVICE loads: the number splitting actually moves
        # (partition skew is a property of the DATA and stays put;
        # spreading a hot partition flattens the receiver lanes)
        lane_rows = ov.reshape(d, -1).sum(axis=1)
        lane_mean = float(lane_rows.mean()) if d else 0.0
        # which receiver devices ended up holding each hot partition's
        # rows: the acceptance witness (>= 2 lanes under real skew) AND
        # the consumer-gather device list below
        devs_for = {
            p: [dev for dev in range(d)
                if ((op_ids[dev] == p) & ov[dev]).any()]
            for p in sorted(hot)}
        hot_spread = {p: len(devs) for p, devs in devs_for.items()}
        if hot:
            with DeviceExchange._total_lock:
                DeviceExchange.total_splits += len(hot)
        lane_bytes = (sum(np.dtype(t.storage).itemsize for t in types_)
                      + 4          # carried partition id (int32)
                      + nch + 1)   # null masks + valid mask (bool lanes)
        self.stats = {
            "kind": "device",
            "sizing": self.sizing,
            "sizing_used": mode_used,
            "per_dest": per_dest,
            "observed_max_pair_rows": observed_max,
            "a2a_retries": self.a2a_retries,
            "count_collectives": self.count_collectives,
            "data_collectives": self.data_collectives,
            "rows": total_rows,
            "partition_rows": [int(r) for r in partition_rows],
            "skew_ratio": (round(float(partition_rows.max()) / mean_rows, 3)
                           if mean_rows > 0 else 0.0),
            "lane_rows": [int(r) for r in lane_rows],
            "lane_skew_ratio": (round(float(lane_rows.max()) / lane_mean, 3)
                                if lane_mean > 0 else 0.0),
            "hot_partitions": sorted(hot),
            "splits": len(hot),
            "split_ways": d if hot else 1,
            "hot_spread": hot_spread,
            "bytes_moved": lanes_moved * lane_bytes,
        }
        # release producer-side inputs: without this the exchange pins
        # ~2x the exchanged bytes in HBM for the rest of the query
        self._by_task.clear()
        out_dicts = list(target)
        result: List[List[DevicePage]] = []
        for p in range(n):
            if p in hot:
                # a split partition's rows landed on several devices:
                # gather its sub-buckets (the downstream "merge" — one
                # DevicePage per receiver slab actually holding rows)
                devs = devs_for[p] or [p % d]
            else:
                devs = [p % d]
            pages: List[DevicePage] = []
            for dev in devs:
                pv = out_valid[dev]
                if d < n or hot:
                    # split the device slab by carried partition id
                    # (with any split active, even n == d slabs hold
                    # foreign partitions' sub-buckets)
                    pv = pv & (out_part[dev] == p)
                pages.append(DevicePage(list(types_),
                                        [c[dev] for c in out_cols],
                                        [x[dev] for x in out_nulls],
                                        pv, out_dicts))
            result.append(pages)
        return result


def _salted_need_bound(pair_max: np.ndarray, hot: set, n: int,
                       d: int) -> int:
    """Safe upper bound on the max (sender, dest) lane load under the
    salted destination map, from the count pass's per-(partition,
    sub-bucket) per-sender maxima (``pair_max[p, sub]`` = pmax over
    senders of that sender's rows with partition p in sub-bucket sub).

    Per destination r, any single sender contributes at most: its rows
    of every UNSPLIT partition homed at r (bounded by the partition's
    per-sender max, i.e. pair_max summed over sub) plus, for every HOT
    partition, exactly its rows in the one sub-bucket that maps to r.
    pmax over senders bounds each term independently, so the sum bounds
    every sender — sized from it, the data collective cannot overflow
    (zero retries by construction, like the unsplit exact mode)."""
    per_part = pair_max.sum(axis=1)  # >= any sender's rows of partition p
    need = np.zeros(d, dtype=np.int64)
    for p in range(n):
        if p in hot:
            for sub in range(d):
                need[(p + sub) % d] += pair_max[p, sub]
        else:
            need[p % d] += per_part[p]
    return int(need.max()) if need.size else 0


def _normalized_keys(cols, nulls, luts, types_: tuple,
                     key_channels: tuple) -> List:
    """Per-row uint64 key columns for partition hashing — THE one
    normalization both the count and data programs run, so they cannot
    disagree on routing (a disagreement would turn exact sizing into
    silent overflow)."""
    keys = []
    li = 0
    for c in key_channels:
        lut = None
        if types_[c].is_string:
            lut = luts[li]
            li += 1
        keys.append(key_to_u64(cols[c], nulls[c], types_[c], lut))
    return keys


@lru_cache(maxsize=128)
def _count_program(mesh: Mesh, types_: tuple, key_channels: tuple,
                   n: int, d: int):
    """The count-first pass: each sender histograms its live rows by
    destination device, a psum gives the global per-partition row counts
    and a pmax the exact max (sender, dest) lane load — O(n*d) scalars
    over the mesh, negligible vs the payload it sizes (the DrJAX
    observation: small pre-collectives are essentially free relative to
    the data movement). Also pmaxes the per-(partition, sub-bucket)
    histogram (n*d scalars) so the host can size the SALTED map exactly
    if it then decides to split a hot partition — one count collective
    covers both layouts. Memoized on (mesh, types, keys, n, d); jit
    re-traces per sender capacity only."""

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P(None)),
             out_specs=(P("x"), P("x"), P("x")),
             check_vma=False)
    def count(cols, nulls, valid, luts):
        cols = tuple(c[0] for c in cols)
        nulls = tuple(x[0] for x in nulls)
        valid = valid[0]
        keys = _normalized_keys(cols, nulls, luts, types_, key_channels)
        part = hash_partition_ids(keys, n)
        dest = part % d if d < n else part
        # the sub-bucket MUST match _exchange_program's salt exactly
        # (same lane layout -> same arange), or exact sizing of a split
        # run silently overflows
        sub = jnp.arange(valid.shape[0], dtype=jnp.int32) % d
        part_hist = partition_histogram(part, valid, n)
        pair_hist = partition_histogram(part * d + sub, valid, n * d)
        pair_need = jnp.max(partition_histogram(dest, valid, d))
        total_hist = jax.lax.psum(part_hist, "x")
        max_need = jax.lax.pmax(pair_need, "x")
        pair_max = jax.lax.pmax(pair_hist, "x")
        return total_hist[None], max_need[None], pair_max[None]

    def counted(cols, nulls, valid, luts):
        jit_stats.bump("device_exchange_count")
        return count(cols, nulls, valid, luts)

    # profiled (telemetry.profiler) under the builder's own memo
    # key: same-shape but different programs never alias
    return instrument("device_exchange_count", jax.jit(counted),
                      key=(mesh, types_, key_channels, n, d))


@lru_cache(maxsize=128)
def _exchange_program(mesh: Mesh, types_: tuple, key_channels: tuple,
                      n: int, d: int, per_dest: int):
    """Build the jitted SPMD shuffle: normalize keys -> partition ids ->
    bucket-sort -> all_to_all. Memoized on (mesh, types, keys, n, d,
    per_dest) so repeat shapes reuse the compiled program.

    With d < n the collective routes to DEVICE p % d and the partition id
    rides along as an extra carried channel so the consumer can split its
    slab; with d == n device == partition and the carry is still returned
    (cheap) but unused.

    ``hot`` is a TRACED (n,) int32 mask of hot partitions: a hot
    partition's rows salt their destination with a row-index-derived
    sub-bucket — ``(home + lane_index % d) % d`` — spreading ONE
    partition's rows across all d receivers while the carried original
    partition id lets the consumer gather re-merge them. Traced (not a
    cache key) so split and unsplit runs share the compiled program."""

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P(None), P(None)),
             out_specs=(P("x"), P("x"), P("x"), P("x"), P("x")),
             check_vma=False)
    def prog(cols, nulls, valid, luts, hot):
        cols = tuple(c[0] for c in cols)
        nulls = tuple(x[0] for x in nulls)
        valid = valid[0]
        keys = _normalized_keys(cols, nulls, luts, types_, key_channels)
        part = hash_partition_ids(keys, n)
        base = part % d  # == part when d == n (part < n)
        sub = jnp.arange(valid.shape[0], dtype=jnp.int32) % d
        dest = jnp.where(hot[part] > 0, (base + sub) % d, base)
        false_ = jnp.zeros(valid.shape, dtype=bool)
        ex_cols, ex_nulls, ex_valid, overflow = repartition_a2a(
            cols + (part,), nulls + (false_,), valid, dest,
            num_partitions=d, per_dest=per_dest)
        return (tuple(c[None] for c in ex_cols[:-1]),
                tuple(x[None] for x in ex_nulls[:-1]),
                ex_valid[None], ex_cols[-1][None], overflow[None])

    def exchanged(cols, nulls, valid, luts, hot):
        # trace-time counter OUTSIDE the shard_map body (which jax may
        # re-trace for lowering): exactly one bump per XLA cache miss,
        # so "repeat shapes do not recompile" is assertable
        jit_stats.bump("device_exchange_program")
        return prog(cols, nulls, valid, luts, hot)

    return instrument(
        "device_exchange_program", jax.jit(exchanged),
        key=(mesh, types_, key_channels, n, d, per_dest))


class _DeviceExchangeToken:
    """Listen token over the exchange's producers-done event."""

    __slots__ = ("_ex",)

    def __init__(self, ex: DeviceExchange):
        self._ex = ex

    def on_ready(self, cb):
        with self._ex._lock:
            if not self._ex._no_more:
                self._ex._listeners.append(cb)
                return
        cb()


class DeviceExchangeChannel:
    """Streaming-consumer adapter: parks until ALL producers finished
    (the collective is inherently a barrier), then streams the
    partition's DevicePages."""

    def __init__(self, ex: DeviceExchange, partition: int):
        self.ex = ex
        self.partition = partition
        self._pages: Optional[List[DevicePage]] = None

    @property
    def stats(self) -> Optional[Dict]:
        """The exchange's skew stats (ready once the collective ran) —
        the consumer-side surface ExchangeSourceOperator.metrics reads."""
        return self.ex.stats

    def poll(self):
        if not self.ex._no_more:
            return None
        if self._pages is None:
            self._pages = list(self.ex.pages(self.partition))
        return self._pages.pop(0) if self._pages else None

    def at_end(self) -> bool:
        return self.ex._no_more and self._pages is not None \
            and not self._pages

    def has_page(self) -> bool:
        return self.ex._no_more and (self._pages is None
                                     or len(self._pages) > 0)

    def listen(self):
        return _DeviceExchangeToken(self.ex)


class DeviceExchangeSinkOperator:
    """Pipeline tail handing DevicePages to the exchange (replaces
    PartitionedOutputOperator on the device path — no host transfer)."""

    _finishing = False

    def __init__(self, input_types: Sequence[T.Type],
                 key_channels: Sequence[int], exchange: DeviceExchange,
                 task_id: int):
        exchange.configure(input_types, key_channels)
        self.exchange = exchange
        self.task_id = task_id
        self._done = False

    def needs_input(self) -> bool:
        return not self._finishing

    def blocked_token(self):
        return None

    def add_input(self, page: DevicePage):
        self.exchange.add_page(self.task_id, page)

    def metrics(self) -> Optional[Dict]:
        """Exchange skew stats for OperatorStats (None until a consumer
        triggered the collective — producer tasks finish before it
        runs; the stage-level attachment in distributed.py reads the
        final value)."""
        return self.exchange.stats

    def get_output(self):
        if self._finishing:
            self._done = True
        return None

    def finish(self):
        self._finishing = True

    def is_finished(self) -> bool:
        return self._done
