"""Distributed query execution: fragment DAG over N in-process workers.

Reference analog: ``testing/trino-testing/.../DistributedQueryRunner.java``
(N TestingTrinoServers in one JVM) driving the fragment execution of
``execution/scheduler/PipelinedQueryScheduler.java``. Here: every
fragment runs ``n_workers`` parallel tasks (threads — JAX releases the
GIL during device compute); stage boundaries are OutputBuffers fed by
PartitionedOutputOperators. Stages execute bottom-up with a barrier per
fragment, i.e. the spooled-exchange (fault-tolerant) execution shape;
the streaming pipelined overlap and the device-collective all_to_all
boundary (parallel/exchange.py) layer on top of the same fragment
contract.

Cache-coherence note (round 17): in-process workers share this
process's ``cache.template_seeds()`` and ``telemetry.stats_store``
singletons, so template-earn state and HBO history are trivially
coherent here — the configure()/heartbeat seed piggyback lives in the
multi-process runner (``parallel/process_runner.py``), where each
worker owns its own stores.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import session_properties as SP
from .. import types as T
from ..block import Page
from ..connectors.spi import Connector
from ..exec.local_planner import (LocalExecutionPlanner,
                                  PhysicalPipeline, grouping_options)
from ..ops.output import OutputBuffer, PartitionedOutputOperator
from ..planner.exchanges import add_exchanges
from ..planner.fragmenter import PlanFragment, fragment_plan, fragments_str
from ..planner.logical_planner import LogicalPlanner, Metadata
from ..planner.optimizer import optimize
from ..planner.plan import OutputNode
from ..runner import QueryResult
from ..sql import ast
from ..sql.analyzer import Session
from ..sql.parser import parse_statement


class DistributedQueryRunner:
    """Executes SQL over a simulated multi-worker cluster in one
    process."""

    def __init__(self, connectors: Dict[str, Connector],
                 session: Optional[Session] = None,
                 n_workers: Optional[int] = None,
                 desired_splits: int = 8,
                 broadcast_threshold: Optional[float] = None):
        from .. import session_properties as SP

        connectors = dict(connectors)
        if "system" not in connectors:
            # in-process workers share this runner's memory, so system
            # tables work without the coordinator-routing the
            # multi-process runner needs
            from ..connectors.system import SystemConnector

            connectors["system"] = SystemConnector(source=self)
        self.metadata = Metadata(connectors)
        self.session = session or Session(
            catalog=next(iter(connectors), None))
        self.n_workers = n_workers if n_workers is not None \
            else SP.value(self.session, "task_concurrency")
        self.desired_splits = desired_splits
        self.broadcast_threshold = broadcast_threshold \
            if broadcast_threshold is not None \
            else SP.value(self.session, "broadcast_join_threshold")
        from ..cache import PlanCache

        #: fragment-plan cache (same PlanCache + key discipline as the
        #: local runner's): repeat statements skip plan/optimize/
        #: exchange planning, and a MATERIAL history misestimate on a
        #: decision node — join inputs, grouped aggs, and the
        #: DISTRIBUTION build sides — invalidates the shape so the
        #: next run re-plans from history
        self.plan_cache = PlanCache()

    # ------------------------------------------------------------------

    def metrics_families(self) -> list:
        """system.runtime.metrics source: the in-process runner exports
        the process-level families (jit traces, exchange counters)."""
        from ..telemetry.metrics import process_families

        return process_families()

    def create_fragments(self, sql_or_stmt,
                         hbo=None) -> List[PlanFragment]:
        stmt = sql_or_stmt if isinstance(sql_or_stmt, ast.Statement) \
            else parse_statement(sql_or_stmt)
        planner = LogicalPlanner(self.metadata, self.session)
        root = planner.plan(stmt)
        from .. import session_properties as SP

        root = optimize(root, self.metadata, planner.allocator,
                        self.session, hbo=hbo)
        trace = getattr(root, "optimizer_trace", None)
        root = add_exchanges(
            root, self.metadata, planner.allocator,
            self.broadcast_threshold,
            SP.value(self.session, "join_distribution_type"),
            scale_writers=SP.value(self.session, "scale_writers_enabled"),
            hbo=hbo if SP.value(self.session,
                                "hbo_distribution_enabled") else None)
        if trace is not None:  # exchange planning rebuilt the root node
            root.optimizer_trace = trace
        self._root = root
        self._fragments = fragment_plan(root)
        return self._fragments

    def explain(self, sql: Optional[str], stmt=None) -> str:
        from ..planner.optimizer import provenance_lines

        if stmt is None:
            stmt = parse_statement(sql)
        # EXPLAIN plans through the statement's history view, so the
        # rendered join order / distribution / strategy choices are
        # exactly what the next execution would run
        text = fragments_str(self.create_fragments(
            stmt, hbo=self._hbo_context(stmt)))
        prov = provenance_lines(self._root)
        return text + ("\n" + "\n".join(prov) if prov else "")

    def execute(self, sql: str) -> QueryResult:
        stmt = parse_statement(sql)
        if isinstance(stmt, ast.Explain) and stmt.analyze and \
                isinstance(stmt.statement, (ast.QueryStatement,
                                            ast.Insert,
                                            ast.CreateTableAsSelect)):
            # DML included: the writer path's exchange surface (scaled
            # writers' rebalance counters) is only observable here
            return self._explain_analyze(stmt.statement,
                                         verbose=stmt.verbose)
        if not isinstance(stmt, ast.QueryStatement):
            if isinstance(stmt, (ast.Insert, ast.CreateTableAsSelect)):
                # writes distribute: scaled writer tasks in the source
                # stage, rowcounts summed (exchanges._v_TableWriterNode)
                return self._execute_query(stmt)
            # remaining DDL doesn't distribute; delegate
            from ..runner import LocalQueryRunner

            return LocalQueryRunner(self.metadata.connectors,
                                    self.session).execute(sql)
        return self._execute_query(stmt)

    def _explain_analyze(self, stmt: ast.QueryStatement,
                         verbose: bool = False) -> QueryResult:
        """Distributed EXPLAIN ANALYZE: run collecting the query/stage/
        task stats tree and render it (reference: the QueryStats
        hierarchy + planprinter; round-2 verdict flagged its absence).
        VERBOSE enables the compiled-program profiler so per-operator
        rows carry flops / bytes / compile-ms and a Kernels line shows
        what this run compiled vs reused."""
        from ..telemetry import profiler

        before = profiler.totals() if verbose else None
        with profiler.profiling(verbose):
            res = self._execute_query(stmt, collect_stats=True)
        tree = res.stats["query_stats"]
        # _execute_query already planned + fragmented; render those
        lines = fragments_str(self._fragments).splitlines()
        lines.append("")
        lines.extend(tree.render())
        if verbose:
            from ..runner import _kernels_line

            lines.append(_kernels_line(before, profiler.totals()))
        return QueryResult(["Query Plan"], [T.VARCHAR],
                           [(line,) for line in lines],
                           stats={"query_stats": tree.to_dict()})

    def _execute_query(self, stmt: ast.QueryStatement,
                       collect_stats: bool = False) -> QueryResult:
        """Profiling envelope around the execution body: the
        ``query_profiling_enabled`` session knob turns the compiled-
        program registry on for this query (EXPLAIN ANALYZE VERBOSE
        layers its own ``profiling(True)`` on top)."""
        from ..telemetry.profiler import profiling

        with profiling(SP.value(self.session,
                                "query_profiling_enabled")):
            return self._execute_query_body(stmt, collect_stats)

    def _hbo_context(self, stmt):
        """History-based-statistics binding (same exclusions as the
        local runner: hbo_enabled off, non-queries, unversioned
        catalogs -> None)."""
        if not SP.value(self.session, "hbo_enabled"):
            return None
        from ..telemetry.stats_store import HboContext

        return HboContext.for_statement(
            stmt, self.session, self.metadata,
            alpha=SP.value(self.session, "hbo_ewma_alpha"))

    def _execute_query_body(self, stmt: ast.QueryStatement,
                            collect_stats: bool = False) -> QueryResult:
        import time as _time

        from ..exec.stats import QueryStatsTree, StageStatsTree

        self._hbo = hbo_ctx = self._hbo_context(stmt)
        key = self._plan_cache_key(stmt)
        cached = self.plan_cache.lookup(key) if key is not None else None
        plan_hit = cached is not None
        if cached is not None:
            self._root, self._fragments = cached
            fragments = self._fragments
        else:
            fragments = self.create_fragments(stmt, hbo=hbo_ctx)
            if key is not None:
                self.plan_cache.store(key, (self._root, self._fragments),
                                      128)
        self._plan_shape = key[0] if key is not None else None
        root: OutputNode = self._root
        buffers: Dict[int, OutputBuffer] = {}
        result_pages: List[Page] = []
        from ..exec.memory import pool_from_session

        # one pool per query across all tasks: device HBM is a
        # per-process resource (reference: ClusterMemoryManager enforcing
        # a query's global limit over per-node reservations)
        self._memory_pool = pool_from_session(self.session)
        self._stage_stats: List[StageStatsTree] = []
        # history recording needs per-operator row counts, so HBO turns
        # the stats-collecting driver path on even for plain execute()
        self._collect_stats = collect_stats or hbo_ctx is not None
        t0 = _time.perf_counter()

        # tasks run as cooperative generators on the process-wide
        # TaskExecutor: concurrent queries time-share the pool through
        # the multilevel feedback queue instead of each query pinning
        # its own threads (reference: TaskExecutor.java per worker JVM)
        from ..exec.task_executor import shared_executor

        executor = shared_executor()
        streaming = SP.value(self.session, "streaming_execution")
        try:
            if streaming:
                result_pages = self._execute_streaming(
                    executor, fragments, root, buffers)
            else:
                for frag in fragments:
                    ntasks = 1 if frag.partitioning == "single" \
                        else self.n_workers
                    if frag.output_kind == "output":
                        collected = self._run_output_fragment(
                            executor, frag, root, ntasks, buffers)
                        result_pages = collected
                    else:
                        buffers[frag.fragment_id] = self._run_fragment(
                            executor, frag, ntasks, buffers)

            rows: List[tuple] = []
            for p in result_pages:
                rows.extend(p.to_rows())
            stats = {"memory": self._memory_pool.stats()}
        except BaseException:
            # reap spill files + free residue even when the query dies
            self._memory_pool.close()
            raise
        names = root.column_names
        types_ = [s.type for s in root.outputs]
        if streaming:
            stats["streaming_overlap"] = {
                fid: buf.overlapped for fid, buf in buffers.items()
                if isinstance(buf, OutputBuffer)}
        if plan_hit:
            stats["plan_cache"] = "hit"
        if hbo_ctx is not None:
            summary = self._hbo_record(hbo_ctx, root, stats)
            if summary:
                stats["hbo"] = summary
        if collect_stats:
            # attach each stage's output-boundary exchange skew stats —
            # only now, after every consumer ran: the device collective
            # is consumer-triggered, so producer-stage completion would
            # be too early to read it
            by_stage = {s.stage_id: s for s in self._stage_stats}
            for fid, buf in buffers.items():
                stage = by_stage.get(fid)
                if stage is not None:
                    stage.exchange = getattr(buf, "stats", None)
            tree = QueryStatsTree(
                stages=self._stage_stats,
                wall_ms=(_time.perf_counter() - t0) * 1e3,
                memory=self._memory_pool.stats())
            if hbo_ctx is not None:
                tree.estimates = self._hbo_estimates
                tree.worst_misestimate = (stats.get("hbo") or
                                          {}).get("worst")
            stats["query_stats"] = tree
        self._memory_pool.close()  # reap spill files, free residue
        return QueryResult(names, types_, rows, stats=stats)

    def _plan_cache_key(self, stmt) -> Optional[tuple]:
        """Fragment-plan cache key, or None when uncacheable: mirrors
        the local runner's discipline (shape + literals + session and
        snapshot fingerprints — SET SESSION and DDL/writes move the
        key), plus the planning inputs owned by this runner."""
        if not SP.value(self.session, "plan_cache_enabled"):
            return None
        if not isinstance(stmt, ast.QueryStatement):
            return None
        from ..cache import (normalize_statement, session_fingerprint,
                             snapshot_fingerprint, statement_catalogs)

        shape, literals = normalize_statement(stmt)
        snap = snapshot_fingerprint(
            statement_catalogs(stmt, self.session), self.metadata)
        if snap is None:
            return None
        return (shape, literals, session_fingerprint(self.session),
                snap, self.n_workers, self.desired_splits,
                self.broadcast_threshold)

    def _hbo_record(self, hbo_ctx, root, stats) -> Optional[dict]:
        """Fold this query's per-node actuals (summed across every
        stage's tasks) into the history store; stashes the estimate
        map for EXPLAIN ANALYZE's per-node Q-error rendering.  A
        material misestimate on a decision node (join input, grouped
        agg, or a DISTRIBUTION build side) drops cached fragment plans
        of the shape — the next run re-plans against history."""
        op_stats = [o for s in self._stage_stats
                    for t in s.tasks for o in t.operators]
        est = hbo_ctx.estimates(root, self.metadata)
        self._hbo_estimates = est[0]
        scan_rows = sum(o.output_rows for o in op_stats
                        if o.name == "TableScanOperator")
        mem = stats.get("memory") or {}
        summary = hbo_ctx.record(root, self.metadata, op_stats,
                                 peak_bytes=mem.get("peak_bytes", 0),
                                 scan_rows=scan_rows, estimates=est)
        shape = getattr(self, "_plan_shape", None)
        if summary and summary["material"] and shape is not None:
            self.plan_cache.invalidate_shape(shape)
        return summary

    # ----------------------------------------------- streaming mode ----

    def _execute_streaming(self, executor, fragments, root: OutputNode,
                           buffers: Dict[int, "OutputBuffer"]):
        """All stages run CONCURRENTLY: every fragment's tasks are
        submitted at once, exchange sources consume pages as producers
        enqueue them (parking on listen tokens while empty), and
        bounded buffers push backpressure upstream (reference:
        execution/scheduler/PipelinedQueryScheduler.java:155)."""
        import threading

        from ..exec.stats import StageStatsTree

        max_pending = SP.value(self.session, "exchange_max_pending_pages")
        plans = []
        for frag in fragments:
            ntasks = 1 if frag.partitioning == "single" \
                else self.n_workers
            out = None
            if frag.output_kind != "output":
                device_ex = self._device_exchange_for(frag, ntasks)
                if device_ex is not None:
                    out = device_ex
                elif frag.output_kind == "single":
                    out = OutputBuffer(1, max_pending_pages=max_pending)
                elif frag.output_kind == "merge":
                    # one partition PER PRODUCER: each task's sorted run
                    # stays separate for the consumer's k-way merge
                    out = OutputBuffer(ntasks,
                                       max_pending_pages=max_pending)
                elif frag.output_kind == "broadcast":
                    out = OutputBuffer(self.n_workers, broadcast=True)
                else:
                    out = OutputBuffer(self.n_workers,
                                       max_pending_pages=max_pending)
                    out.rebalancer = self._rebalancer_for(frag)
                buffers[frag.fragment_id] = out
            plans.append((frag, ntasks, out))

        futures = []
        stages = []
        results: List[List[Page]] = []
        for frag, ntasks, out in plans:
            stage = StageStatsTree(frag.fragment_id, frag.partitioning,
                                   frag.output_kind)
            stages.append(stage)
            is_output = frag.output_kind == "output"
            if is_output:
                results = [[] for _ in range(ntasks)]
            # producers-done wiring: the LAST task of the fragment to
            # exit (normally or not) marks the stream ended, so
            # consumers always unblock
            remaining = [ntasks]
            rlock = threading.Lock()

            def wrapped(gen, out=out, remaining=remaining, rlock=rlock):
                try:
                    yield from gen
                finally:
                    with rlock:
                        remaining[0] -= 1
                        last = remaining[0] == 0
                    if last and out is not None:
                        out.set_no_more_pages()

            for t in range(ntasks):
                gen = self._task_gen(frag, ntasks, t, out, buffers,
                                     stage, root if is_output else None,
                                     results if is_output else None,
                                     streaming=True)
                futures.append(executor.submit(wrapped(gen)))

        self._wait_all(futures,
                       [b for b in buffers.values()])
        if getattr(self, "_collect_stats", False):
            for stage in stages:
                stage.tasks.sort(key=lambda t: t.task_id)
                self._stage_stats.append(stage)
        return [p for r in results for p in r]

    def _wait_all(self, futures, bufs):
        """Wait for every task; on the first error, abort all buffers so
        parked producers/consumers unwind instead of deadlocking, then
        keep waiting so no generator outlives the query."""
        errors: List[BaseException] = []
        aborted = False
        pending = list(futures)
        while pending:
            still = []
            for f in pending:
                if f._event.wait(0.02):
                    if f._error is not None:
                        errors.append(f._error)
                else:
                    still.append(f)
            if errors and not aborted:
                aborted = True
                for b in bufs:
                    b.abort()
            pending = still
        if errors:
            raise errors[0]

    # ------------------------------------------------------------------

    def _make_reader(self, buffers: Dict[int, OutputBuffer], task_id: int,
                     streaming: bool = False):
        def reader(fragment_id: int, kind: str):
            buf = buffers[fragment_id]
            if kind == "merge":
                # per-producer sorted streams for the k-way merge
                if streaming:
                    return [buf.channel(p)
                            for p in range(buf.num_partitions)]
                return [(lambda p=p: buf.pages(p))
                        for p in range(buf.num_partitions)]
            part = 0 if kind == "single" else task_id
            if streaming:
                from .device_exchange import DeviceExchange

                if isinstance(buf, DeviceExchange):
                    return buf.channel(part)
                return buf.channel(part, consumer_id=task_id)

            def thunk():
                return buf.pages(part)

            return thunk

        return reader

    def _task_gen(self, frag: PlanFragment, ntasks: int, t: int, out,
                  buffers, stage, root: Optional[OutputNode],
                  results: Optional[List[List[Page]]],
                  streaming: bool = False):
        """One task of one fragment as a cooperative generator. ``out``
        is the fragment's output (OutputBuffer | DeviceExchange | None
        for the output fragment, which collects into ``results[t]``).
        In streaming mode a no-progress quantum yields Blocked(tokens)
        so the executor parks the task."""
        from ..exec.driver import Driver
        from ..exec.local_planner import project_to_wire_layout
        from ..exec.stats import TaskStatsTree
        from ..exec.task_executor import Blocked

        planner = LocalExecutionPlanner(
            self.metadata, self.desired_splits, task_id=t,
            task_count=ntasks,
            exchange_reader=self._make_reader(buffers, t, streaming),
            memory_pool=self._memory_pool,
            join_max_lanes=SP.value(self.session,
                                    "join_max_expand_lanes"),
            dynamic_filtering=SP.value(
                self.session, "enable_dynamic_filtering"),
            scan_coalesce=SP.value(self.session, "scan_coalesce_enabled"),
            hbo=getattr(self, "_hbo", None),
            **grouping_options(self.session.properties))
        collect = getattr(self, "_collect_stats", False)
        task = TaskStatsTree(t)
        if root is not None:
            plan = planner.plan(OutputNode(frag.root, root.column_names,
                                           root.outputs))
            pipelines = plan.pipelines
        else:
            ops, layout, types_ = planner.visit(frag.root)
            ops, layout, types_, key_channels = project_to_wire_layout(
                frag, ops, layout, types_)
            from .device_exchange import DeviceExchange

            if isinstance(out, DeviceExchange):
                from .device_exchange import DeviceExchangeSinkOperator

                ops.append(DeviceExchangeSinkOperator(
                    types_, key_channels, out, t))
            else:
                ops.append(PartitionedOutputOperator(
                    types_, key_channels, out, frag.output_kind,
                    task_partition=t,
                    rebalancer=getattr(out, "rebalancer", None),
                    hot_split_threshold=SP.value(
                        self.session, "hot_partition_split_threshold")))
            planner.pipelines.append(PhysicalPipeline(ops))
            pipelines = planner.pipelines
        for p in pipelines:
            d = Driver(p.operators, collect_stats=collect)
            for _ in range(10_000_000):
                if d.process():
                    break
                if streaming:
                    # park only after a NO-PROGRESS quantum: a blocked
                    # source with runnable downstream work must keep
                    # running
                    toks = [] if d.last_moved else d.blocked_tokens()
                    yield Blocked(toks) if toks else None
                else:
                    yield  # quantum boundary: hand the thread back
            else:
                raise T.TrinoError("driver did not finish",
                                   "GENERIC_INTERNAL_ERROR")
            if collect:
                d.collect_operator_metrics()
                task.operators.extend(d.stats)
        if root is not None and results is not None:
            results[t] = plan.sink.pages
        if collect:
            stage.tasks.append(task)

    def _rebalancer_for(self, frag: PlanFragment):
        """The scaled-writer rebalancer for a scale_writers hash
        boundary (see rebalancer.writer_rebalancer for the sharing
        contract)."""
        if frag.output_kind != "hash" or not frag.scale_writers:
            return None
        from .rebalancer import writer_rebalancer

        return writer_rebalancer(
            (str(s.type) for s in frag.output_symbols), self.n_workers,
            SP.value(self.session, "rebalance_min_collectives"))

    def _device_exchange_for(self, frag: PlanFragment, ntasks: int):
        """The flagship TPU-native path: a hash stage boundary between
        co-resident stages runs as one all_to_all collective over the
        mesh instead of host-side partitioning (SURVEY.md §2.8). Returns
        None when the fragment must take the host path."""
        from .. import session_properties as SP

        if frag.output_kind != "hash" or ntasks != self.n_workers:
            return None
        if frag.scale_writers:
            # scaled-writer boundaries rebalance on the HOST: the
            # partition->lane map mutates across pages, which a compiled
            # collective cannot follow (and writers consume host pages)
            return None
        if not SP.value(self.session, "device_exchange"):
            return None
        from .device_exchange import (DeviceExchange,
                                      device_exchange_supported)

        if not device_exchange_supported(
                [s.type for s in frag.output_symbols]):
            return None
        import jax

        # fewer devices than workers is fine: DeviceExchange lays p
        # partitions over d devices (p % d) and carries partition ids
        # through the collective, so a single real chip still executes
        # the flagship path
        devices = jax.devices()
        return DeviceExchange(
            self.n_workers, devices,
            sizing=SP.value(self.session, "device_exchange_sizing"),
            hot_split_threshold=SP.value(
                self.session, "hot_partition_split_threshold"))

    def _run_fragment(self, executor, frag: PlanFragment, ntasks: int,
                      buffers: Dict[int, OutputBuffer]):
        # consumer partition count: single -> 1, hash -> n_workers,
        # broadcast -> replicated
        device_ex = self._device_exchange_for(frag, ntasks)
        if device_ex is not None:
            out = device_ex
        elif frag.output_kind == "single":
            out = OutputBuffer(1)
        elif frag.output_kind == "merge":
            out = OutputBuffer(ntasks)  # one partition per producer
        elif frag.output_kind == "broadcast":
            out = OutputBuffer(self.n_workers, broadcast=True)
        else:
            out = OutputBuffer(self.n_workers)
            out.rebalancer = self._rebalancer_for(frag)

        from ..exec.stats import StageStatsTree

        stage = StageStatsTree(frag.fragment_id, frag.partitioning,
                               frag.output_kind)
        executor.run_all([
            self._task_gen(frag, ntasks, t, out, buffers, stage, None,
                           None)
            for t in range(ntasks)])
        if getattr(self, "_collect_stats", False):
            stage.tasks.sort(key=lambda t: t.task_id)
            self._stage_stats.append(stage)
        return out

    def _run_output_fragment(self, executor, frag: PlanFragment,
                             root: OutputNode, ntasks: int,
                             buffers) -> List[Page]:
        from ..exec.stats import StageStatsTree

        results: List[List[Page]] = [[] for _ in range(ntasks)]
        stage = StageStatsTree(frag.fragment_id, frag.partitioning,
                               frag.output_kind)
        executor.run_all([
            self._task_gen(frag, ntasks, t, None, buffers, stage, root,
                           results)
            for t in range(ntasks)])
        if getattr(self, "_collect_stats", False):
            stage.tasks.sort(key=lambda t: t.task_id)
            self._stage_stats.append(stage)
        return [p for r in results for p in r]
