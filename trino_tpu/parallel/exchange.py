"""Device-collective exchange: hash repartitioning as XLA all-to-all.

Reference analog: the ENTIRE pull-based HTTP shuffle path —
``operator/output/PartitionedOutputOperator.java`` + ``PagePartitioner``
(producer side) and ``operator/ExchangeOperator.java`` +
``DirectExchangeClient`` (consumer side), SURVEY.md §2.8.

TPU-first redesign: when producer and consumer stages are co-resident on a
pod slice, a stage boundary needs no serialization, no HTTP, no buffers —
each device bucket-sorts its rows by destination partition and one XLA
``all_to_all`` over ICI delivers every row to its owner. The host never
touches the data.

Capacity model: all_to_all needs equal-sized lanes, so each device sends a
fixed ``per_dest`` lanes to each destination. Rows beyond capacity are
counted in the returned ``overflow`` (host checks and can re-run with a
larger factor); with hash partitioning overflow implies heavy skew.

Count-first sizing: instead of guessing ``per_dest`` and paying the 2x
re-run cliff on overflow, callers can first run a tiny counting
collective (``partition_histogram`` + psum/pmax over the mesh — O(n*d)
scalars, negligible vs the payload) to learn the exact max
(sender, destination) load and size the data ``all_to_all`` exactly;
the overflow retry then remains only as a bug backstop. See
``parallel/device_exchange._count_program`` and ``mesh_query``.
"""

from __future__ import annotations

import zlib
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit_stats
from .. import types as T

try:  # jax >= 0.4.35 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # older jax: the experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# newer jax spells the replication-check kwarg ``check_vma``, older
# releases ``check_rep``; detect once instead of catching TypeError at
# call time (which would mask unrelated argument mistakes)
import inspect as _inspect

_SM_PARAMS = _inspect.signature(_shard_map).parameters
_SM_CHECK_KW = ("check_vma" if "check_vma" in _SM_PARAMS
                else "check_rep" if "check_rep" in _SM_PARAMS else None)


def shard_map(*args, **kwargs):
    """Version-compat ``shard_map``: call sites write ``check_vma``;
    the shim renames (or drops) it to whatever this jax supports."""
    if "check_vma" in kwargs and _SM_CHECK_KW != "check_vma":
        kwargs = dict(kwargs)
        val = kwargs.pop("check_vma")
        if _SM_CHECK_KW is not None:
            kwargs[_SM_CHECK_KW] = val
    return _shard_map(*args, **kwargs)


def string_hash_lut(d) -> np.ndarray:
    """code -> stable value hash (crc32): equal strings route equally
    regardless of which dictionary pool coded them. THE one definition —
    host and device exchange paths must agree or mixed-path joins break."""
    if d is None or len(d) == 0:
        return np.zeros(1, dtype=np.uint64)
    return np.asarray([zlib.crc32(("" if v is None else v).encode())
                       for v in d.values], dtype=np.uint64)


def key_to_u64(raw, nulls, type_: T.Type, lut: Optional[jnp.ndarray] = None):
    """Value-stable uint64 normalization of one key column for partition
    hashing (device op). ``lut`` is the string channel's crc LUT. THE one
    definition shared by the host path (ops/output.PartitionedOutput-
    Operator) and the device collective (parallel/device_exchange)."""
    if type_.is_string:
        k = lut[raw]
    elif type_ in (T.DOUBLE, T.REAL):
        # deterministic quantization (equal floats -> equal id); f64<->u64
        # bitcasts don't lower on the TPU x64 path
        k = (jnp.asarray(raw, jnp.float64)
             * 65536.0).astype(jnp.int64).view(jnp.uint64)
    elif type_ == T.BOOLEAN:
        k = raw.astype(jnp.uint64)
    else:
        k = raw.astype(jnp.int64).view(jnp.uint64)
    return jnp.where(nulls, jnp.uint64(0), k)


def hash_partition_ids(keys_u64: Sequence, num_partitions: int):
    """Combine pre-normalized uint64 key columns into partition ids.

    Mirrors the reference's InterpretedHashGenerator (CRC-style combined
    row hash -> partition), using splitmix64 finalization per column.
    """
    acc = jnp.zeros(keys_u64[0].shape, dtype=jnp.uint64)
    for k in keys_u64:
        z = (k + np.uint64(0x9E3779B97F4A7C15)) * np.uint64(0xBF58476D1CE4E5B9)
        z = z ^ (z >> np.uint64(27))
        acc = acc * np.uint64(31) + z
    acc = acc ^ (acc >> np.uint64(33))
    return (acc % np.uint64(num_partitions)).astype(jnp.int32)


def subbucket_ids(keys_u64: Sequence, num_sub: int):
    """Second, INDEPENDENT hash of pre-normalized key columns into
    ``num_sub`` sub-buckets — the hot-partition SPLIT salt for
    aggregation/join inputs: equal keys land in equal sub-buckets (so a
    group's rows stay co-located on one receiver), while the distinct
    keys of a hot partition spread across receivers. Uses the murmur3
    finalizer with different constants than ``hash_partition_ids`` so
    the sub-bucket is uncorrelated with the partition id."""
    acc = jnp.zeros(keys_u64[0].shape, dtype=jnp.uint64)
    for k in keys_u64:
        z = (k ^ np.uint64(0x94D049BB133111EB)) * np.uint64(0xFF51AFD7ED558CCD)
        z = z ^ (z >> np.uint64(29))
        acc = acc * np.uint64(37) + z
    acc = acc ^ (acc >> np.uint64(32))
    return (acc % np.uint64(num_sub)).astype(jnp.int32)


def partition_histogram(part_ids, valid, num_partitions: int):
    """Per-destination live-row counts of ONE sender (device op): the
    count-first pass each sender runs before a collective to size its
    lanes from data instead of a capacity guess. Dead rows drop into a
    discarded overflow slot."""
    idx = jnp.where(valid, part_ids, num_partitions).astype(jnp.int32)
    hist = jnp.zeros((num_partitions + 1,), jnp.int32).at[idx].add(
        1, mode="drop")
    return hist[:num_partitions]


@partial(jax.jit, static_argnames=("num_partitions", "per_dest", "axis_name"))
def repartition_a2a(cols: Tuple, nulls: Tuple, valid, part_ids,
                    num_partitions: int, per_dest: int,
                    axis_name: str = "x"):
    """Inside shard_map: route each live row to the device owning its
    partition. Returns (cols, nulls, valid, overflow_count) with capacity
    num_partitions * per_dest on each receiver.

    Implementation: bucket-sort rows by destination, lay them into a
    (num_partitions, per_dest) send grid, one lax.all_to_all, flatten.
    """
    jit_stats.bump("repartition_a2a")
    cap = valid.shape[0]
    # sort rows by (invalid, destination): live rows grouped by dest
    dest = jnp.where(valid, part_ids, num_partitions)
    operands = [dest.astype(jnp.int32)] + list(cols) + list(nulls) + [valid]
    s = jax.lax.sort(operands, num_keys=1, is_stable=False)
    s_dest, s_rest = s[0], s[1:]
    ncols = len(cols)
    s_cols, s_nulls, s_valid = (s_rest[:ncols], s_rest[ncols:2 * ncols],
                                s_rest[-1])

    # position of each row within its destination bucket
    start = jnp.searchsorted(s_dest, jnp.arange(num_partitions,
                                                dtype=jnp.int32))
    pos = jnp.arange(cap, dtype=jnp.int32) - start[jnp.clip(
        s_dest, 0, num_partitions - 1)]
    in_grid = s_valid & (pos < per_dest)
    overflow = jnp.sum(s_valid & ~in_grid)

    # scatter into the (num_partitions * per_dest) send grid
    slot = jnp.where(in_grid,
                     jnp.clip(s_dest, 0, num_partitions - 1) * per_dest + pos,
                     num_partitions * per_dest)  # dropped lanes -> overflow slot

    def to_grid(col):
        grid = jnp.zeros((num_partitions * per_dest + 1,), dtype=col.dtype)
        grid = grid.at[slot].set(col, mode="drop")
        return grid[:-1].reshape(num_partitions, per_dest)

    g_cols = [to_grid(c) for c in s_cols]
    g_nulls = [to_grid(n) for n in s_nulls]
    g_valid = to_grid(in_grid)

    # the collective: row i of my grid goes to device i
    def a2a(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                                  tiled=True)

    r_cols = tuple(a2a(c).reshape(-1) for c in g_cols)
    r_nulls = tuple(a2a(n).reshape(-1) for n in g_nulls)
    r_valid = a2a(g_valid).reshape(-1)
    return r_cols, r_nulls, r_valid, overflow
