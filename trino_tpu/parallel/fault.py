"""Fault-tolerance substrate: failure taxonomy, deadlines, backoff, and
the deterministic fault-injection harness.

Reference analogs:
- ``spi/ErrorType.java`` — every failure is USER / INTERNAL / EXTERNAL /
  INSUFFICIENT_RESOURCES; retry policies consult the TYPE, not the
  message: user errors (division by zero, bad casts) are deterministic
  and fail fast, while infrastructure faults consume the retry budget
  (``execution/QueryStateMachine.java`` + ``faulttolerant/`` schedulers).
- ``execution/FailureInjector.java:40`` — injected task failures keyed
  by task id with an error type, for fault-tolerance tests.
- ``failuredetector/HeartbeatFailureDetector.java`` — the decay model
  behind worker-death detection (process_runner's heartbeat loop).

The ``FaultSchedule`` generalizes the seed's one-shot
``inject_task_failure`` into a seeded, deterministic chaos harness:
each armed fault is addressed by (task-id pattern, fault kind,
occurrence count) and is consumed exactly once per matching launch, so
a chaos run replays identically under a fixed schedule.
"""

from __future__ import annotations

import threading
import time
import traceback
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..types import TrinoError

# -- error taxonomy ------------------------------------------------------

USER = "USER"
INTERNAL = "INTERNAL"
EXTERNAL = "EXTERNAL"
INSUFFICIENT_RESOURCES = "INSUFFICIENT_RESOURCES"

ERROR_TYPES = (USER, INTERNAL, EXTERNAL, INSUFFICIENT_RESOURCES)

#: error codes that are NOT user mistakes — everything else raised as a
#: TrinoError is deterministic user input (retrying cannot help)
_INTERNAL_CODES = {"GENERIC_INTERNAL_ERROR", "PAGE_TRANSPORT_ERROR",
                   "REMOTE_TASK_ERROR", "NO_NODES_AVAILABLE"}
_RESOURCE_CODES = {"EXCEEDED_LOCAL_MEMORY_LIMIT",
                   "EXCEEDED_GLOBAL_MEMORY_LIMIT",
                   "EXCEEDED_MEMORY_LIMIT", "CLUSTER_OUT_OF_MEMORY",
                   "EXCEEDED_NODE_MEMORY", "EXCEEDED_CLUSTER_MEMORY"}


def classify_error_code(code: str) -> str:
    if code in _RESOURCE_CODES:
        return INSUFFICIENT_RESOURCES
    if code in _INTERNAL_CODES:
        return INTERNAL
    return USER


def classify_exception(exc: BaseException) -> str:
    """Map an exception to its error type (reference: each
    StandardErrorCode declares its ErrorType; here the taxonomy is
    derived from exception class + code)."""
    if isinstance(exc, RemoteTaskError):
        return exc.error_type
    if isinstance(exc, TrinoError):
        return classify_error_code(exc.code)
    if isinstance(exc, MemoryError):
        return INSUFFICIENT_RESOURCES
    if isinstance(exc, (ConnectionError, OSError, EOFError)):
        return EXTERNAL
    # torn spool files / lost exchange streams: the transport or the
    # durable store failed the engine (name-matched to avoid cycles)
    if type(exc).__name__ in ("SpoolCorruption", "ExchangeConnectionLost"):
        return EXTERNAL
    # AnalysisError and friends are user errors but never reach workers;
    # anything else raised during execution is an engine bug
    if type(exc).__name__ == "AnalysisError":
        return USER
    return INTERNAL


def is_retryable(error_type: str) -> bool:
    """USER errors are deterministic: re-running the same input re-fails
    (the reference's FTE retries only non-USER error types)."""
    return error_type != USER


def serialize_failure(exc: BaseException) -> dict:
    """Worker-side: pack a task failure for the RPC response so the
    coordinator sees the real error, its type, and the remote stack
    (reference: ExecutionFailureInfo shipped in TaskStatus)."""
    # TrinoError carries .code; an already-typed RemoteTaskError (a
    # transitively-propagated upstream failure) carries .error_code —
    # keep the original code either way so USER errors surface with
    # their real code after any number of exchange hops
    code = getattr(exc, "code", None) or getattr(exc, "error_code", None)
    return {
        "error": repr(exc),
        "error_type": classify_exception(exc),
        "error_code": code or "GENERIC_INTERNAL_ERROR",
        "remote_traceback": traceback.format_exc(),
        # a transport loss observed remotely stays a transport loss
        # after the hop: the coordinator's worker-lost (heal + query
        # retry) path keys off this flag
        "connection_lost": bool(getattr(exc, "connection_lost", False)),
        # torn durable state: a task retry would re-read the same bytes,
        # only a fresh query attempt (new spool) can recover — the
        # coordinator must not burn task retries on it
        "retry_scope": getattr(exc, "retry_scope", None) or (
            "query" if type(exc).__name__ == "SpoolCorruption"
            else "task"),
    }


class RemoteTaskError(RuntimeError):
    """A task/RPC failure with its taxonomy and the remote traceback —
    what `fetch_pages`/task RPCs raise instead of a bare string
    (reference: RemoteTaskException wrapping the worker's failure)."""

    def __init__(self, message: str, error_type: str = INTERNAL,
                 error_code: str = "GENERIC_INTERNAL_ERROR",
                 remote_traceback: str = "",
                 connection_lost: bool = False,
                 retry_scope: str = "task"):
        super().__init__(message)
        self.error_type = error_type
        self.error_code = error_code
        self.remote_traceback = remote_traceback
        self.connection_lost = connection_lost
        #: "task" (default) or "query": query-scoped failures (torn
        #: spool) are pointless to retry on another worker
        self.retry_scope = retry_scope

    @classmethod
    def from_response(cls, resp: dict, context: str = ""):
        msg = resp.get("error", "unknown remote failure")
        if context:
            msg = f"{context}: {msg}"
        tb = resp.get("remote_traceback") or ""
        if tb:
            msg = f"{msg}\n--- remote traceback ---\n{tb.rstrip()}"
        return cls(msg, resp.get("error_type", INTERNAL),
                   resp.get("error_code", "GENERIC_INTERNAL_ERROR"),
                   tb, bool(resp.get("connection_lost")),
                   resp.get("retry_scope") or "task")


# -- deadlines + backoff -------------------------------------------------


class Deadline:
    """Per-query wall-clock budget (`query_max_run_time`) enforced at
    every coordinator->worker RPC: the remaining budget caps each RPC
    timeout, and an expired deadline raises EXCEEDED_TIME_LIMIT — a USER
    error, so it is never retried (reference:
    QueryTracker.enforceTimeLimits)."""

    def __init__(self, max_run_time: float = 0.0):
        self.max_run_time = max_run_time
        self._expires = (time.monotonic() + max_run_time) \
            if max_run_time and max_run_time > 0 else None

    def remaining(self) -> Optional[float]:
        if self._expires is None:
            return None
        return self._expires - time.monotonic()

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def check(self):
        if self.expired():
            raise TrinoError(
                f"query exceeded maximum run time of "
                f"{self.max_run_time}s", "EXCEEDED_TIME_LIMIT")

    def rpc_timeout(self, base: float) -> float:
        """Cap an RPC timeout by the remaining query budget."""
        self.check()
        rem = self.remaining()
        return base if rem is None else max(0.001, min(base, rem))


class BackoffPolicy:
    """Exponential backoff with deterministic jitter around query/task
    retries (reference: failure recovery's ExponentialBackoff). Seeded:
    the same (seed, attempt) always yields the same delay, so chaos runs
    replay identically."""

    def __init__(self, initial: float = 0.05, maximum: float = 2.0,
                 multiplier: float = 2.0, jitter: float = 0.25,
                 seed: int = 0):
        self.initial = initial
        self.maximum = maximum
        self.multiplier = multiplier
        self.jitter = jitter
        self.seed = seed

    def delay(self, attempt: int) -> float:
        base = min(self.maximum,
                   self.initial * (self.multiplier ** max(0, attempt)))
        # deterministic jitter in [1-j, 1+j): hash the (seed, attempt)
        # pair instead of sampling a shared RNG so concurrent queries
        # cannot perturb each other's schedules
        h = zlib.crc32(f"{self.seed}:{attempt}".encode()) / 0xFFFFFFFF
        return base * (1.0 - self.jitter + 2.0 * self.jitter * h)

    @staticmethod
    def seed_for(query_id: str) -> int:
        return zlib.crc32(query_id.encode())


class DecayingFailureStats:
    """Per-worker failure rate with exponential decay (reference:
    ``failuredetector/HeartbeatFailureDetector.java``'s DecayCounter):
    each recorded failure contributes weight 1 that halves every
    ``half_life_s`` seconds, so a worker that flapped a minute ago
    outranks one that failed within the last second, and a long-healed
    worker converges back to 0.  The scheduler sorts task/retry
    placement by this score so flapping workers shed load without being
    fenced outright."""

    def __init__(self, half_life_s: float = 60.0):
        import math

        self._decay = math.log(2.0) / max(half_life_s, 1e-9)
        self._weight = 0.0
        self._ts = 0.0
        self._lock = threading.Lock()
        self.total = 0              # undecayed lifetime count

    def _decayed_locked(self, now: float) -> float:
        import math

        if self._weight and now > self._ts:
            self._weight *= math.exp(-self._decay * (now - self._ts))
        self._ts = max(self._ts, now)
        return self._weight

    def record(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._weight = self._decayed_locked(now) + 1.0
            self.total += 1

    def score(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._decayed_locked(now)


# -- recovery observability ----------------------------------------------


@dataclass
class RecoveryStats:
    """What self-healing actually did, per query and cumulatively
    (surfaced through QueryResult.stats['recovery'], EXPLAIN ANALYZE and
    the bench output). Counters are bumped from parallel task threads,
    transport-retry callbacks and the monitor thread — mutate through
    the locked methods, not bare `+=`."""

    task_attempts: int = 0
    task_retries: int = 0
    query_retries: int = 0
    retries_by_type: Dict[str, int] = field(default_factory=dict)
    backoff_wall_s: float = 0.0
    workers_replaced: int = 0
    speculative_launched: int = 0
    speculative_wins: int = 0
    #: INSUFFICIENT_RESOURCES retries that re-admitted with a grown
    #: memory budget / reduced task width (memory-aware escalation)
    memory_escalations: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def incr(self, counter: str, amount=1):
        with self._lock:
            setattr(self, counter, getattr(self, counter) + amount)

    def record_retry(self, error_type: str, query_level: bool = False):
        with self._lock:
            if query_level:
                self.query_retries += 1
            else:
                self.task_retries += 1
            self.retries_by_type[error_type] = \
                self.retries_by_type.get(error_type, 0) + 1

    _FIELDS = ("task_attempts", "task_retries", "query_retries",
               "backoff_wall_s", "workers_replaced",
               "speculative_launched", "speculative_wins",
               "memory_escalations")

    def merge(self, other: "RecoveryStats"):
        with other._lock:
            snap = {f: getattr(other, f) for f in self._FIELDS}
            by_type = dict(other.retries_by_type)
        with self._lock:
            for f, v in snap.items():
                setattr(self, f, getattr(self, f) + v)
            for k, v in by_type.items():
                self.retries_by_type[k] = \
                    self.retries_by_type.get(k, 0) + v

    def to_dict(self) -> dict:
        return {
            "task_attempts": self.task_attempts,
            "task_retries": self.task_retries,
            "query_retries": self.query_retries,
            "retries_by_type": dict(self.retries_by_type),
            "backoff_wall_s": round(self.backoff_wall_s, 4),
            "workers_replaced": self.workers_replaced,
            "speculative_launched": self.speculative_launched,
            "speculative_wins": self.speculative_wins,
            "memory_escalations": self.memory_escalations,
        }


# -- deterministic fault injection ---------------------------------------

#: every fault shape the harness can inject, and where it fires
FAULT_KINDS = (
    "error",                # raise INTERNAL at task start (seed behavior)
    "user-error",           # raise a USER-typed error at task start
    "kill-worker",          # os._exit the worker process mid-task
    "drop-connection",      # close a results connection mid-frame
    "delay",                # straggler: sleep before executing
    "fail-after-publish",   # task fails AFTER its spool output published
    "kill-after-publish",   # os._exit the worker AFTER spool publish:
    #                         the output must outlive the process
    "truncate-spool",       # corrupt the published spool file mid-frame
    "revoke-memory",        # force a full pool revocation every
    #                         `countdown` reservations: pressure lands
    #                         mid-build AND mid-probe deterministically
)


@dataclass
class FaultSpec:
    pattern: str            # task-id prefix to match
    kind: str               # one of FAULT_KINDS
    remaining: int = 1      # occurrences left to fire
    delay_s: float = 0.0    # for kind == "delay"
    error_code: str = "DIVISION_BY_ZERO"   # for kind == "user-error"
    countdown: int = 1      # for kind == "revoke-memory": the period of
    #                         reservations between forced revocations
    fired: int = 0


class FaultSchedule:
    """Seeded, deterministic chaos harness (reference:
    FailureInjector.injectTaskFailure — generalized to five fault
    shapes). Faults are armed by (task-id pattern, kind, occurrences);
    ``match`` consumes one occurrence per matching task launch and
    returns the directive the coordinator ships with ``run_task``.

    Determinism: occurrence accounting is exact (first `remaining`
    matching launches, in launch order, fire the fault), and the seed
    parameterizes any randomized knob (currently delay jitter) through
    a private RNG — two runs with the same schedule and the same launch
    order inject identically.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.specs: List[FaultSpec] = []
        self._lock = threading.Lock()

    def add(self, pattern: str, kind: str = "error", times: int = 1,
            delay_s: float = 0.0,
            error_code: str = "DIVISION_BY_ZERO",
            countdown: int = 1) -> "FaultSchedule":
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"expected one of {FAULT_KINDS}")
        self.specs.append(FaultSpec(pattern, kind, times, delay_s,
                                    error_code, countdown))
        return self

    def match(self, task_id: str) -> Optional[dict]:
        """Consume and return the directive for this task launch, or
        None. First matching armed spec wins (schedule order)."""
        with self._lock:
            for spec in self.specs:
                if spec.remaining > 0 and task_id.startswith(spec.pattern):
                    spec.remaining -= 1
                    spec.fired += 1
                    directive = {"kind": spec.kind}
                    if spec.kind == "delay":
                        # deterministic jitter: +-10% keyed by (seed,
                        # pattern, occurrence)
                        h = zlib.crc32(
                            f"{self.seed}:{spec.pattern}:{spec.fired}"
                            .encode()) / 0xFFFFFFFF
                        directive["delay_s"] = spec.delay_s * \
                            (0.9 + 0.2 * h)
                    if spec.kind == "user-error":
                        directive["error_code"] = spec.error_code
                    if spec.kind == "revoke-memory":
                        directive["countdown"] = spec.countdown
                    return directive
        return None

    def pending(self) -> Dict[str, int]:
        with self._lock:
            return {s.pattern: s.remaining for s in self.specs
                    if s.remaining > 0}

    def armed(self) -> bool:
        return bool(self.pending())
