"""A COMPLETE distributed query as one SPMD mesh program.

Reference analog: a two-stage Trino query plan — stage 1 scan + partial
aggregation, hash exchange, stage 2 final aggregation (the plan shape of
``sql/planner/optimizations/AddExchanges.java`` for q1) — with the entire
HTTP shuffle (``operator/ExchangeOperator.java:48`` /
``DirectExchangeClient.java:55`` / ``PagePartitioner.java:182``) replaced
by one XLA ``all_to_all`` over ICI inside a ``shard_map``.

This is the engine's flagship TPU-native exchange, packaged so the driver
dry-run (``__graft_entry__.dryrun_multichip``) executes a full query —
scan shard -> fused filter/project -> local partial agg -> all_to_all
repartition of groups -> merge-final aggregation on the owning device —
and cross-checks the result against single-device execution.

Sizing protocol (count-first): the program is split at the exchange —
stage 1 (fused filter/project + partial agg) also emits its
per-destination live-group histogram plus a tiny ``psum``/``pmax`` of
those counts, so the host knows the EXACT max (sender, dest) lane load
before compiling the exchange+final program and ``per_dest`` needs no
guessing. The legacy doubling retry remains as a backstop (and for
callers pinning ``per_dest``), but a retry now re-runs only the
exchange+final program, never stage 1 — the old fused-program protocol
paid the whole scan+partial-agg again per doubling.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import jit_stats
from ..block import Block, Page, padded_size
from ..ops.aggregation import (_final_project, _group_reduce, _merge_states,
                               _state_plan)
from ..ops.global_hash_agg import (EMPTY, global_hash_insert,
                                   global_hash_reduce, pack_keys,
                                   unpack_keys)
from ..ops.kernel_sizing import KERNEL_SIZING
from ..ops.sortkeys import group_operands
from ..telemetry.profiler import instrument
from .exchange import (hash_partition_ids, partition_histogram,
                       repartition_a2a, shard_map, subbucket_ids)


#: memoized SPMD programs + expression builds: jax.jit caches live on
#: the returned callables, so rebuilding one per run_q1_mesh call (or
#: per retry) would re-trace + re-lower identical programs every time
#: (the lru_cache analog of device_exchange._exchange_program; Mesh
#: hashes by devices + axis names)
_PROGRAM_CACHE: dict = {}


def _cached_program(key, build):
    hit = _PROGRAM_CACHE.get(key)
    if hit is None:
        hit = _PROGRAM_CACHE[key] = build()
    return hit


def _shard_page(page: Page, n_shards: int):
    """Split a host page into n contiguous row shards, padded to one
    common capacity; returns stacked (n, cap) arrays per column."""
    rows = page.num_rows
    per = -(-rows // n_shards)
    cap = padded_size(max(per, 16))
    ncols = page.channel_count
    cols = [np.zeros((n_shards, cap), dtype=b.type.storage)
            for b in page.blocks]
    nulls = [np.zeros((n_shards, cap), dtype=bool) for _ in range(ncols)]
    valid = np.zeros((n_shards, cap), dtype=bool)
    for s in range(n_shards):
        lo, hi = s * per, min((s + 1) * per, rows)
        k = max(hi - lo, 0)
        if k == 0:
            continue
        for c, b in enumerate(page.blocks):
            cols[c][s, :k] = np.asarray(b.data[lo:hi])
            if b.nulls is not None:
                nulls[c][s, :k] = np.asarray(b.nulls[lo:hi])
        valid[s, :k] = True
    return ([jnp.asarray(c) for c in cols], [jnp.asarray(x) for x in nulls],
            jnp.asarray(valid))


def q1_stage1_fn(mesh: Mesh, proc, step):
    """Build the jitted stage-1 SPMD program: per-device fused
    filter/project + local partial aggregation, PLUS the count-first
    sizing collective — each device's per-destination live-group
    histogram, psummed into global per-partition row counts and pmaxed
    into the exact max (sender, dest) lane load. O(n^2) scalars over the
    mesh, free next to the partial-agg compute it rides on."""
    n = mesh.devices.size

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P(None)),
             out_specs=(P("x"),) * 7,
             check_vma=False)
    def stage1(cols, nulls, valid, luts):
        cols = tuple(c[0] for c in cols)
        nulls = tuple(x[0] for x in nulls)
        valid = valid[0]
        kr, kn, states, pvalid = step(cols, nulls, valid, luts)
        # route each partial group to its owning device. Keys are
        # dictionary codes from pools shared across co-resident shards,
        # so raw codes route consistently.
        keys_u64 = [k.astype(jnp.int64).view(jnp.uint64) for k in kr]
        part = hash_partition_ids(
            [jnp.where(jnp.asarray(b), jnp.uint64(0), k)
             for k, b in zip(keys_u64, kn)], n)
        hist = partition_histogram(part, pvalid, n)
        total_hist = jax.lax.psum(hist, "x")
        max_need = jax.lax.pmax(jnp.max(hist), "x")
        return (tuple(k[None] for k in kr),
                tuple(jnp.asarray(b)[None] for b in kn),
                tuple(s[None] for s in states),
                pvalid[None], part[None],
                total_hist[None], max_need[None])

    def staged(cols, nulls, valid, luts):
        jit_stats.bump("mesh_q1_stage1")
        return stage1(cols, nulls, valid, luts)

    return jax.jit(staged)


def q1_exchange_final_fn(mesh: Mesh, proc, aggs, per_dest: int):
    """Build the jitted exchange+final SPMD program: all_to_all of the
    partial groups at the (count-first or caller-pinned) ``per_dest``,
    then merge-final aggregation on the owning device. Separate from
    stage 1 so a backstop retry re-runs ONLY the shuffle, never the
    scan/partial-agg.

    ``hot`` is a TRACED (n,) hot-partition mask: a hot partition's
    groups salt their destination with a KEY-derived sub-bucket —
    unlike the generic device exchange's row-index salt, every partial
    of one group shares a sub-bucket, so the group still meets on
    exactly one device and the per-device merge-final aggregation
    stays correct with no extra merge stage. Traced, not a cache key:
    split and unsplit runs share the compiled program."""
    n = mesh.devices.size
    key_types = proc.output_types[:2]
    kinds = tuple(k for a in aggs for (k, _) in _state_plan(a))

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P("x"), P("x"), P(None)),
             out_specs=(P("x"), P("x"), P("x"), P("x")),
             check_vma=False)
    def dist(kr, kn, states, pvalid, part, hot):
        kr = tuple(k[0] for k in kr)
        kn = tuple(b[0] for b in kn)
        states = tuple(s[0] for s in states)
        pvalid = pvalid[0]
        part = part[0]
        keys_u64 = [jnp.where(jnp.asarray(b), jnp.uint64(0),
                              k.astype(jnp.int64).view(jnp.uint64))
                    for k, b in zip(kr, kn)]
        sub = subbucket_ids(keys_u64, n)
        dest = jnp.where(hot[part] > 0, (part + sub) % n, part)
        ex_cols, ex_nulls, ex_valid, overflow = repartition_a2a(
            tuple(kr) + tuple(states),
            tuple(kn) + tuple(
                jnp.zeros(s.shape, dtype=bool) for s in states),
            pvalid, dest, num_partitions=n, per_dest=per_dest)
        # merge-final aggregation of received partial states
        key_ops: List = []
        for i, t in enumerate(key_types):
            key_ops.extend(group_operands(ex_cols[i], ex_nulls[i], t))
        merged: List = []
        idx = 2
        for a in aggs:
            k = len(_state_plan(a))
            merged.extend(_merge_states(
                a, [ex_cols[idx + j] for j in range(k)], ex_valid))
            idx += k
        from ..ops.pallas_kernels import pallas_mode

        out_keys, out_key_nulls, reduced, out_valid = _group_reduce(
            tuple(key_ops), tuple(ex_cols[:2]), tuple(merged), ex_valid,
            num_keys=2, num_states=len(merged), kinds=kinds,
            pallas=pallas_mode())
        fin_cols = list(out_keys)
        fin_nulls = [jnp.asarray(x) for x in out_key_nulls]
        idx = 0
        for a in aggs:
            k = len(_state_plan(a))
            raw, null = _final_project(a, [reduced[idx + j]
                                           for j in range(k)])
            fin_cols.append(raw.astype(a.output_type.storage))
            fin_nulls.append(null | ~out_valid)
            idx += k
        return (tuple(c[None] for c in fin_cols),
                tuple(x[None] for x in fin_nulls),
                out_valid[None], overflow[None])

    def exchanged(kr, kn, states, pvalid, part, hot):
        jit_stats.bump("mesh_q1_exchange_final")
        return dist(kr, kn, states, pvalid, part, hot)

    return jax.jit(exchanged)


def q1_global_hash_fn(mesh: Mesh, proc, aggs, table_size: int):
    """Build the jitted GLOBAL-HASH alternative to the exchange+final
    program ("Global Hash Tables Strike Back!", PAPERS.md): no
    all_to_all of partial groups at all — every device claims its
    partial groups' slots in ONE replicated open-addressing table
    (splitmix64 probing, pmin-agreed claims) and the state columns
    merge by collective scatter-add (psum/pmin/pmax over the table).
    Each device then finalizes the table shard it owns, so the output
    layout matches the exchange path's (n, per-device) shape.  For
    low-NDV grouping the collectives move O(table) bytes instead of
    O(partial groups) rows."""
    n = mesh.devices.size
    kinds = tuple(k for a in aggs for (k, _) in _state_plan(a))
    shard = table_size // n
    widths = (32, 32)  # q1 keys are dictionary codes: small, non-negative

    @partial(shard_map, mesh=mesh,
             in_specs=(P("x"), P("x"), P("x"), P("x")),
             out_specs=(P("x"), P("x"), P("x"), P("x")),
             check_vma=False)
    def dist(kr, kn, states, pvalid):
        kr = tuple(k[0] for k in kr)
        kn = tuple(b[0] for b in kn)
        states = tuple(s[0] for s in states)
        pvalid = pvalid[0]
        merged: List = []
        idx = 0
        for a in aggs:
            k = len(_state_plan(a))
            merged.extend(_merge_states(
                a, [states[idx + j] for j in range(k)], pvalid))
            idx += k
        packed = pack_keys(kr, kn, widths)
        table, slot_of, resolved, unresolved = global_hash_insert(
            packed, pvalid, table_size, axis_name="x")
        reduced = global_hash_reduce(slot_of, resolved, pvalid,
                                     tuple(merged), kinds, table_size,
                                     axis_name="x")
        # finalize the owned shard: slot -> group row
        i = jax.lax.axis_index("x")

        def sl(arr):
            return jax.lax.dynamic_slice(arr, (i * shard,), (shard,))

        t_sh = sl(table)
        occupied = t_sh != EMPTY
        fin_cols = []
        fin_nulls = []
        for (kv, knull), kcol in zip(unpack_keys(t_sh, widths), kr):
            fin_cols.append(kv.astype(kcol.dtype))
            fin_nulls.append(knull & occupied)
        idx = 0
        for a in aggs:
            k = len(_state_plan(a))
            raw, null = _final_project(a, [sl(reduced[idx + j])
                                           for j in range(k)])
            fin_cols.append(raw.astype(a.output_type.storage))
            fin_nulls.append(null | ~occupied)
            idx += k
        return (tuple(c[None] for c in fin_cols),
                tuple(x[None] for x in fin_nulls),
                occupied[None], unresolved[None])

    def hashed(kr, kn, states, pvalid):
        jit_stats.bump("mesh_q1_global_hash")
        return dist(kr, kn, states, pvalid)

    return jax.jit(hashed)


def run_q1_mesh(devices: Sequence, schema: str = "micro",
                per_dest: Optional[int] = None,
                max_per_dest: int = 1 << 16,
                stats_out: Optional[dict] = None,
                hot_split_threshold: Optional[float] = None,
                agg_strategy: str = "auto"):
    """Execute distributed q1 over the mesh.

    ``per_dest=None`` (default) sizes the exchange count-first: stage 1
    reports the exact max lane load and the data collective runs ONCE,
    zero retries by construction. Passing ``per_dest`` pins the legacy
    guess (tests use per_dest=1 to exercise the doubling backstop).
    ``stats_out``, when given, is filled with the exchange's skew stats
    (partition_rows, skew_ratio, per_dest, retries, collectives).

    ``hot_split_threshold`` (None = off) enables hot-partition
    splitting: a partition above that fraction of stage 1's live
    groups spreads its groups across receivers by key-derived
    sub-bucket (aggregation-safe — every group still meets on exactly
    one device). Sizing keeps the UNSALTED count (an upper bound in
    the common case); the doubling backstop covers the remainder.

    ``agg_strategy`` picks the merge shape after stage 1: 'exchange'
    (all_to_all of partial groups + per-device merge-final — the
    legacy shape), 'global_hash' (one replicated table updated by
    collective scatter-add — no row shuffle), or 'auto' (default): the
    ``planner.optimizer.choose_agg_strategy`` cost rule decides from
    stage 1's observed live-group count.  A pinned ``per_dest`` forces
    the exchange shape (it IS an exchange knob), and a global-hash
    probe-budget overflow falls back to the exchange path — results
    are identical either way.

    Returns (result_rows, n_overflow_retries, connector, scanned_pages) —
    the latter two so callers can re-run the same data locally for the
    equivalence check."""
    from ..benchmarks import q1_device_step, scan_q1_pages
    from ..connectors.tpch import TpchConnector

    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("x",))
    conn = TpchConnector(page_rows=1 << 14)
    pages = scan_q1_pages(conn, schema, n)
    whole = Page.concat(pages)
    cols, nulls, valid = _shard_page(whole, n)
    types = [b.type for b in whole.blocks]
    dicts = [b.dictionary for b in whole.blocks]
    tsig = tuple(map(str, types))

    def _build_q1_programs():
        from ..benchmarks import q1_expressions

        proc, step = q1_device_step(types)
        _, _, aggs = q1_expressions(types)
        return proc, step, aggs

    # memoized per type signature: a fresh proc/step per call would
    # rebuild the per-instance jit caches and re-trace every repeat run
    proc, step, aggs = _cached_program(("q1_step", tsig),
                                       _build_q1_programs)
    luts = proc._fill_luts(dicts)

    s1 = _cached_program(
        ("stage1", mesh, tsig),
        lambda: instrument("mesh_q1_stage1",
                           q1_stage1_fn(mesh, proc, step),
                           key=("stage1", mesh, tsig)))
    kr, kn, states, pvalid, part, hist, need = s1(
        tuple(cols), tuple(nulls), valid, luts)
    part_rows = np.asarray(hist)[0]
    exact_need = int(np.asarray(need)[0])
    sizing = "exact" if per_dest is None else "legacy"
    if per_dest is None:
        per_dest = padded_size(max(exact_need, 16))

    # merge-shape decision: the cost rule reads stage 1's observed
    # live-group count (an upper bound on distinct groups — the same
    # histogram the sizing pass already paid for)
    total_groups = int(part_rows.sum())
    pinned = sizing == "legacy"
    strategy = {"auto": "auto", "exchange": "exchange",
                "global_hash": "global-hash",
                "global-hash": "global-hash"}.get(agg_strategy)
    if strategy is None:
        from ..types import TrinoError

        raise TrinoError(f"unknown agg_strategy {agg_strategy!r}",
                         "GENERIC_INTERNAL_ERROR")
    detail = f"forced agg_strategy={agg_strategy}"
    if pinned and strategy != "exchange":
        strategy, detail = "exchange", "per_dest pinned -> exchange"
    elif strategy == "auto":
        from ..planner.optimizer import choose_agg_strategy

        strategy, detail = choose_agg_strategy(total_groups, n)

    retries = 0
    out_cols = out_nulls = out_valid = None
    if strategy == "global-hash":
        # table sized 2x the observed partial-group bound (load <= 0.5)
        # through the kernel sizing history, so repeat runs whose group
        # count jitters reuse the compiled program; must shard evenly
        # over the mesh (both are powers of two)
        table_size = KERNEL_SIZING.suggest(
            ("global-hash-q1", tsig, n), 2 * max(total_groups, 1),
            minimum=max(16, n))
        if table_size % n:
            # the table must shard evenly over the mesh (pow2 capacity
            # over a pow2 mesh always does; an odd mesh keeps exchange)
            strategy = "exchange"
            detail += f"; table {table_size} !% {n} devices -> exchange"
    if strategy == "global-hash":
        fn = _cached_program(
            ("global_hash", mesh, tsig, table_size),
            lambda: instrument(
                "mesh_q1_global_hash",
                q1_global_hash_fn(mesh, proc, aggs, table_size),
                key=("global_hash", mesh, tsig, table_size)))
        out_cols, out_nulls, out_valid, unresolved = fn(
            kr, kn, states, pvalid)
        jax.block_until_ready(out_valid)
        n_unresolved = int(np.asarray(unresolved)[0])
        if n_unresolved:
            # probe budget exhausted (adversarial collisions): the
            # exchange path is the exact fallback
            strategy = "exchange"
            detail += f"; global-hash overflow {n_unresolved} -> exchange"
        elif stats_out is not None:
            stats_out.update({
                "kind": "device", "agg_strategy": "global-hash",
                "strategy_detail": detail,
                "table_slots": table_size,
                "rows": total_groups,
                "partition_rows": [int(r) for r in part_rows],
                "a2a_retries": 0, "data_collectives": 1,
            })

    hot: set = set()
    if strategy == "exchange":
        # hot-partition split decision from stage 1's histogram
        if hot_split_threshold is not None and hot_split_threshold < 1.0 \
                and n > 1 and total_groups:
            hot = {p for p in range(n)
                   if part_rows[p] / total_groups > hot_split_threshold}
        hot_mask = np.zeros((n,), dtype=np.int32)
        for p in hot:
            hot_mask[p] = 1
        hot_mask = jnp.asarray(hot_mask)

        collectives = 0
        while True:
            fn = _cached_program(
                ("final", mesh, tsig, per_dest),
                lambda: instrument(
                    "mesh_q1_exchange_final",
                    q1_exchange_final_fn(mesh, proc, aggs, per_dest),
                    key=("final", mesh, tsig, per_dest)))
            out_cols, out_nulls, out_valid, overflow = fn(
                kr, kn, states, pvalid, part, hot_mask)
            jax.block_until_ready(out_valid)
            collectives += 1
            if int(np.asarray(overflow).sum()) == 0:
                break
            per_dest *= 2
            retries += 1
            if per_dest > max_per_dest:
                from ..types import TrinoError

                raise TrinoError(
                    f"exchange overflow persists at per_dest={per_dest}",
                    "GENERIC_INTERNAL_ERROR")

        if stats_out is not None:
            mean_rows = float(part_rows.mean()) if n else 0.0
            stats_out.update({
                "kind": "device", "sizing": sizing, "per_dest": per_dest,
                "agg_strategy": "exchange", "strategy_detail": detail,
                "observed_max_pair_rows": exact_need,
                "a2a_retries": retries, "data_collectives": collectives,
                "rows": int(part_rows.sum()),
                "partition_rows": [int(r) for r in part_rows],
                "skew_ratio": (round(float(part_rows.max()) / mean_rows, 3)
                               if mean_rows > 0 else 0.0),
                "hot_partitions": sorted(hot),
                "splits": len(hot),
                "split_ways": n if hot else 1,
            })

    # assemble the distributed result: compact valid lanes per device
    out_types = list(proc.output_types[:2]) + [a.output_type for a in aggs]
    out_dicts = dicts[:2] + [None] * len(aggs)
    blocks: List[Block] = []
    oc = [np.asarray(c) for c in out_cols]      # (n, cap2)
    on = [np.asarray(x) for x in out_nulls]
    ov = np.asarray(out_valid)
    keep = np.nonzero(ov.reshape(-1))[0]
    for t, c, x, d in zip(out_types, oc, on, out_dicts):
        data = c.reshape(-1)[keep]
        nl = x.reshape(-1)[keep]
        blocks.append(Block(t, data, nl if nl.any() else None, d))
    rows = Page(blocks, len(keep)).to_rows()
    return rows, retries, conn, pages


def run_q1_mesh_demo(devices: Sequence, schema: str = "micro") -> None:
    """Dry-run entry: run the full distributed q1 and cross-check against
    single-device execution (DistributedQueryRunner-analog gate)."""
    rows, retries, conn, pages = run_q1_mesh(devices, schema)

    from ..benchmarks import build_q1_driver

    driver, sink = build_q1_driver(conn, schema, source_pages=list(pages))
    driver.run_to_completion()
    local_rows: List[tuple] = []
    for p in sink.pages:
        local_rows.extend(p.to_rows())

    key = lambda r: (r[0], r[1])  # noqa: E731
    got, want = sorted(rows, key=key), sorted(local_rows, key=key)
    assert len(got) == len(want), \
        f"distributed {len(got)} groups vs local {len(want)}"
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            if isinstance(a, float):
                assert abs(a - b) <= 1e-9 * max(1.0, abs(b)), (g, w)
            else:
                assert a == b, (g, w)
    print(f"mesh q1 ({len(devices)} devices, schema={schema}): "
          f"{len(got)} groups match local execution; "
          f"a2a retries={retries}")
